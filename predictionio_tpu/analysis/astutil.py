"""Shared AST helpers for the rule modules.

The jit-detection here is *syntactic*: it recognizes the decoration and
call idioms this codebase (and JAX code generally) actually uses —
``@jax.jit``, ``@functools.partial(jax.jit, static_argnames=...)``,
``jax.jit(fn, ...)`` as an expression, ``pjit``/``shard_map`` variants —
without importing jax or resolving names. False negatives from exotic
aliasing (``mylint = jax.jit``) are acceptable; false positives are not.
"""

from __future__ import annotations

import ast
import dataclasses

JIT_LAST_COMPONENTS = frozenset({"jit", "pjit", "shard_map"})

# attribute reads that are static under tracing (safe to branch on)
STATIC_ATTRS = frozenset(
    {"shape", "ndim", "dtype", "size", "sharding", "aval", "weak_type"}
)
# builtin calls whose result is static even on a tracer argument
STATIC_CALLS = frozenset({"len", "isinstance", "hasattr", "getattr", "type"})

MUTATING_METHODS = frozenset(
    {
        "append",
        "extend",
        "insert",
        "add",
        "update",
        "pop",
        "popitem",
        "clear",
        "remove",
        "discard",
        "setdefault",
        "appendleft",
        "extendleft",
    }
)

MUTABLE_FACTORIES = frozenset(
    {"dict", "list", "set", "defaultdict", "OrderedDict", "Counter", "deque"}
)


def dotted(node: ast.AST) -> str | None:
    """'jax.numpy.asarray' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_component(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


@dataclasses.dataclass(frozen=True)
class JitInfo:
    """Static-argument declarations extracted from a jit decoration/call."""

    kind: str  # last component: jit / pjit / shard_map
    static_argnums: frozenset[int]
    static_argnames: frozenset[str]


def _const_str_or_collection(node: ast.AST | None) -> frozenset:
    """Literal 'x', ('x', 'y'), ['x'] -> the set of constants (str or int)."""
    if node is None:
        return frozenset()
    if isinstance(node, ast.Constant) and isinstance(node.value, (str, int)):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, (str, int)):
                out.add(elt.value)
        return frozenset(out)
    return frozenset()


def _info_from_keywords(kind: str, keywords: list[ast.keyword]) -> JitInfo:
    nums: frozenset = frozenset()
    names: frozenset = frozenset()
    for kw in keywords:
        if kw.arg == "static_argnums":
            nums = frozenset(
                v for v in _const_str_or_collection(kw.value) if isinstance(v, int)
            )
        elif kw.arg == "static_argnames":
            names = frozenset(
                v for v in _const_str_or_collection(kw.value) if isinstance(v, str)
            )
    return JitInfo(kind, nums, names)


def jit_expr_info(expr: ast.AST) -> JitInfo | None:
    """JitInfo when ``expr`` denotes a jit transform (bare or partial'd)."""
    last = last_component(expr)
    if last in JIT_LAST_COMPONENTS:
        return JitInfo(last, frozenset(), frozenset())
    if isinstance(expr, ast.Call):
        func_last = last_component(expr.func)
        if func_last == "partial" and expr.args:
            inner = last_component(expr.args[0])
            if inner in JIT_LAST_COMPONENTS:
                return _info_from_keywords(inner, expr.keywords)
        if func_last in JIT_LAST_COMPONENTS:
            # jax.jit(fn, static_argnames=...) used as expression/decorator
            return _info_from_keywords(func_last, expr.keywords)
    return None


def jit_decorator_info(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> JitInfo | None:
    for dec in fn.decorator_list:
        info = jit_expr_info(dec)
        if info is not None:
            return info
    return None


def param_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def traced_param_names(
    fn: ast.FunctionDef | ast.AsyncFunctionDef, info: JitInfo
) -> set[str]:
    a = fn.args
    positional = [p.arg for p in a.posonlyargs + a.args]
    traced = set(positional) | {p.arg for p in a.kwonlyargs}
    traced -= {positional[i] for i in info.static_argnums if i < len(positional)}
    traced -= set(info.static_argnames)
    traced -= {"self", "cls"}
    return traced


def dynamic_names(node: ast.AST) -> set[str]:
    """Names whose *concrete value* the expression inspects.

    ``x.shape[0]``, ``len(x)``, ``isinstance(x, T)`` and ``x is None`` are
    static under tracing and contribute nothing; a bare ``x`` (or ``x + 1``,
    ``x[0] > 0`` ...) forces the traced value and contributes ``x``.
    """
    if isinstance(node, ast.Name):
        return {node.id}
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return set()
        return dynamic_names(node.value)
    if isinstance(node, ast.Call):
        func_last = last_component(node.func)
        if isinstance(node.func, ast.Name) and func_last in STATIC_CALLS:
            return set()
        out = set()
        if isinstance(node.func, ast.Attribute):
            out |= dynamic_names(node.func.value)
        for arg in node.args:
            out |= dynamic_names(arg)
        for kw in node.keywords:
            out |= dynamic_names(kw.value)
        return out
    if isinstance(node, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
    ):
        # `x is None` / `x is not None` inspect identity, not the value
        return set()
    out = set()
    for child in ast.iter_child_nodes(node):
        out |= dynamic_names(child)
    return out


def is_mutable_literal(node: ast.AST) -> bool:
    return isinstance(
        node,
        (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp),
    )


def is_mutable_factory_call(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        last = last_component(node.func)
        return last in MUTABLE_FACTORIES
    return False


def module_level_statements(tree: ast.Module) -> list[ast.stmt]:
    """Statements that execute at import time: module body plus class
    bodies, excluding every function body (functions are call-graph nodes
    and get reachability-scoped treatment instead)."""
    out: list[ast.stmt] = []

    def collect(body: list[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.ClassDef):
                collect(stmt.body)
                continue
            out.append(stmt)

    collect(tree.body)
    return out


def walk_skipping_nested_functions(body: list[ast.stmt]):
    """Yield every node in ``body`` without descending into nested
    function/class definitions (their scopes are analyzed separately)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
