"""Storage-contract audit.

Every backend under ``data/storage/`` must structurally implement the full
abstract surface its base class declares in ``storage/base.py``. Runtime
``abc`` would catch this at instantiation — but backends with optional
dependencies (elasticsearch, hdfs, s3) may never be instantiated in CI, so
the drift shows up in production instead. This check is pure AST: it reads
``base.py`` next to the audited file, collects ``@abstractmethod`` names per
base class, then verifies each subclass (following ancestor chains defined
in the same file) defines every required method.
"""

from __future__ import annotations

import ast
import os

from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    register_checker,
    register_rule,
)

register_rule(
    "storage-missing-method",
    "storage-contract",
    Severity.ERROR,
    "storage backend class does not implement the full abstract surface "
    "of its storage/base.py base class",
)


def _base_name(expr: ast.expr) -> str | None:
    """Last dotted component of a base-class expression; handles
    ``Apps``, ``base.Apps`` and ``Generic[T]``-style subscripts."""
    if isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_abstract(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = _base_name(dec)
        if name in ("abstractmethod", "abstractproperty"):
            return True
    return False


def _abstract_surface(base_path: str) -> dict[str, set[str]]:
    """class name -> abstract method names declared in base.py."""
    with open(base_path, encoding="utf-8", errors="replace") as fh:
        tree = ast.parse(fh.read())
    surface: dict[str, set[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {
            sub.name
            for sub in node.body
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
            and _is_abstract(sub)
        }
        if methods:
            surface[node.name] = methods
    return surface


def _defined_methods(cls: ast.ClassDef) -> set[str]:
    out = set()
    for sub in cls.body:
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(sub.name)
        elif isinstance(sub, ast.Assign):
            # `find = _find_impl` style aliasing still satisfies the contract
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


@register_checker
def check_storage_contract(ctx: FileContext):
    if not ctx.path:
        return []
    directory, filename = os.path.split(ctx.path)
    if os.path.basename(directory) != "storage" or filename in (
        "base.py",
        "__init__.py",
    ):
        return []
    base_path = os.path.join(directory, "base.py")
    if not os.path.exists(base_path):
        return []
    cache_key = ("storage-abstract-surface", base_path)
    if cache_key not in ctx.cache:
        try:
            ctx.cache[cache_key] = _abstract_surface(base_path)
        except (OSError, SyntaxError):
            ctx.cache[cache_key] = {}
    surface: dict[str, set[str]] = ctx.cache[cache_key]
    if not surface:
        return []

    local_classes = {
        node.name: node
        for node in ctx.tree.body
        if isinstance(node, ast.ClassDef)
    }
    findings: list[Finding] = []
    for cls in local_classes.values():
        # walk the local ancestor chain collecting contract bases and
        # locally defined methods (an intermediate local base may provide
        # part of the surface)
        required: set[str] = set()
        defined = _defined_methods(cls)
        queue = list(cls.bases)
        visited: set[str] = {cls.name}
        while queue:
            base = queue.pop()
            name = _base_name(base)
            if name is None or name in visited:
                continue
            visited.add(name)
            if name in surface:
                required |= surface[name]
            elif name in local_classes:
                ancestor = local_classes[name]
                defined |= _defined_methods(ancestor)
                queue.extend(ancestor.bases)
        missing = sorted(required - defined)
        if missing:
            findings.append(
                ctx.finding(
                    "storage-missing-method",
                    cls,
                    f"{cls.name!r} is missing abstract method(s) "
                    f"{', '.join(missing)} required by storage/base.py",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# raw pickle deserialization
# ---------------------------------------------------------------------------

register_rule(
    "storage-raw-pickle",
    "storage-contract",
    Severity.ERROR,
    "pickle.load(s) outside the checksummed model-io boundary: "
    "deserializing unverified bytes silently turns storage corruption or "
    "tampering into arbitrary code execution",
)

# the only files allowed to unpickle: both sit behind the PIOTPU02
# sha256-verified framing (workflow/model_io.py) or serve verified
# registry artifacts (registry/store.py)
_PICKLE_ALLOWED_SUFFIXES = (
    os.path.join("workflow", "model_io.py"),
    os.path.join("registry", "store.py"),
)


@register_checker
def check_raw_pickle(ctx: FileContext):
    if "ickle" not in ctx.source:  # pickle / cPickle / _pickle
        return []
    path = (ctx.path or ctx.display_path).replace("/", os.sep)
    if any(path.endswith(suffix) for suffix in _PICKLE_ALLOWED_SUFFIXES):
        return []
    pickle_modules = {"pickle", "cPickle", "_pickle"}
    # module names the pickle modules are bound to (incl. `import pickle
    # as pkl` aliases) and bare `load`/`loads` names imported from them
    module_names = set(pickle_modules)
    bare: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in pickle_modules:
                    module_names.add(alias.asname or alias.name)
        elif isinstance(node, ast.ImportFrom) and node.module in pickle_modules:
            for alias in node.names:
                if alias.name in ("load", "loads"):
                    bare.add(alias.asname or alias.name)
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        hit = (
            isinstance(fn, ast.Attribute)
            and fn.attr in ("load", "loads")
            and isinstance(fn.value, ast.Name)
            and fn.value.id in module_names
        ) or (isinstance(fn, ast.Name) and fn.id in bare)
        if hit:
            findings.append(
                ctx.finding(
                    "storage-raw-pickle",
                    node,
                    "raw pickle deserialization; route model bytes through "
                    "workflow/model_io.py (sha256-verified PIOTPU02 framing) "
                    "or the registry store",
                )
            )
    return findings
