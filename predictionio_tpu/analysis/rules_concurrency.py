"""Concurrency rules: unlocked module-level mutable state in threaded files.

A module that spawns threads (``threading.Thread``, ``ThreadPoolExecutor``)
and mutates module-level dicts/lists/sets from function bodies without a
visible lock is a data race waiting for traffic: CPython's GIL makes single
bytecodes atomic, not read-modify-write sequences like ``d[k] = d.get(k)+1``
(the event-server stats pattern). The check is structural: the mutation must
happen lexically inside a ``with <lock>:`` block, where ``<lock>`` is a name
bound to ``threading.Lock()``/``RLock()``/... at module level or any dotted
name containing "lock".
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    register_checker,
    register_rule,
)

register_rule(
    "concurrency-unlocked-global",
    "concurrency",
    Severity.WARNING,
    "module-level mutable state mutated in a thread-spawning module "
    "without holding a visible lock",
)

_THREAD_FACTORIES = frozenset(
    {"Thread", "ThreadPoolExecutor", "Timer", "start_new_thread"}
)
_LOCK_FACTORIES = frozenset(
    {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
)


def _is_threaded_module(tree: ast.Module) -> bool:
    """Spawns threads — or imports threading at all: a module holding a
    lock advertises that its module state is reached from worker threads
    even when the Thread() call lives in a caller."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            last = astutil.last_component(node.func)
            if last in _THREAD_FACTORIES:
                return True
        elif isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "threading" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] in ("threading", "concurrent"):
                return True
    return False


def _module_state(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(mutable global names, lock names) bound at module level."""
    mutable: set[str] = set()
    locks: set[str] = set()
    for stmt in tree.body:
        targets: list[ast.expr] = []
        value: ast.expr | None = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        is_lock = (
            isinstance(value, ast.Call)
            and astutil.last_component(value.func) in _LOCK_FACTORIES
        )
        is_mutable = astutil.is_mutable_literal(value) or astutil.is_mutable_factory_call(
            value
        )
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if is_lock:
                locks.add(t.id)
            elif is_mutable:
                mutable.add(t.id)
    return mutable, locks


def _with_holds_lock(stmt: ast.With | ast.AsyncWith, locks: set[str]) -> bool:
    for item in stmt.items:
        expr = item.context_expr
        # `with lock:` / `with self._lock:` / `with _lock.acquire_timeout():`
        d = astutil.dotted(expr) or astutil.dotted(
            expr.func if isinstance(expr, ast.Call) else expr
        )
        if not d:
            continue
        parts = d.lower().split(".")
        if any(p in {l.lower() for l in locks} or "lock" in p for p in parts):
            return True
    return False


def _mutation_target(node: ast.AST, mutable: set[str]) -> str | None:
    """The mutated global name when ``node`` mutates one, else None."""
    if isinstance(node, ast.AugAssign):
        root = node.target
        while isinstance(root, (ast.Subscript, ast.Attribute)):
            root = root.value
        if isinstance(root, ast.Name) and root.id in mutable:
            return root.id
    elif isinstance(node, ast.Assign):
        for t in node.targets:
            root = t
            while isinstance(root, (ast.Subscript, ast.Attribute)):
                root = root.value
            if isinstance(root, ast.Name) and root.id in mutable and root is not t:
                # subscript/attribute store into the container; a bare
                # rebinding of the module name needs `global`, handled below
                return root.id
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                if t.value.id in mutable:
                    return t.value.id
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in astutil.MUTATING_METHODS and isinstance(
            node.func.value, ast.Name
        ):
            if node.func.value.id in mutable:
                return node.func.value.id
    return None


@register_checker
def check_unlocked_globals(ctx: FileContext):
    if not _is_threaded_module(ctx.tree):
        return []
    mutable, locks = _module_state(ctx.tree)
    if not mutable:
        return []
    findings: list[Finding] = []

    def visit(body: list[ast.stmt], held: bool, global_decls: set[str]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def starts with no lock held: it may be called
                # from anywhere, not just from under this `with`
                visit(stmt.body, False, set())
                continue
            if isinstance(stmt, ast.ClassDef):
                visit(stmt.body, held, set())
                continue
            if isinstance(stmt, ast.Global):
                global_decls.update(stmt.names)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                visit(
                    stmt.body,
                    held or _with_holds_lock(stmt, locks),
                    global_decls,
                )
                continue
            if isinstance(stmt, (ast.If, ast.While, ast.For, ast.AsyncFor)):
                visit(stmt.body, held, global_decls)
                visit(stmt.orelse, held, global_decls)
                continue
            if isinstance(stmt, ast.Try):
                visit(stmt.body, held, global_decls)
                for h in stmt.handlers:
                    visit(h.body, held, global_decls)
                visit(stmt.orelse, held, global_decls)
                visit(stmt.finalbody, held, global_decls)
                continue
            if held:
                continue
            name = None
            for node in astutil.walk_skipping_nested_functions([stmt]):
                name = _mutation_target(node, mutable)
                if name:
                    break
                # `global g; g = ...` rebinding races against readers too
                if (
                    isinstance(node, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id in global_decls
                        for t in node.targets
                    )
                ):
                    hits = [
                        t.id
                        for t in node.targets
                        if isinstance(t, ast.Name) and t.id in global_decls
                    ]
                    name = hits[0]
                    break
            if name:
                findings.append(
                    ctx.finding(
                        "concurrency-unlocked-global",
                        stmt,
                        f"module-level mutable {name!r} mutated without a "
                        f"visible lock in a module that spawns threads",
                    )
                )

    # module body itself runs single-threaded at import; only functions race
    for stmt in ctx.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit(stmt.body, False, set())
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit(sub.body, False, set())
    return findings
