"""``async-blocking-call``: blocking work parked on a fleet event loop.

The multi-host tier's p99 depends on three event loops never blocking: the
gateway (every in-flight proxy stalls together), the supervisor/autoscaler
loop (missed probes eject healthy replicas), and the event/serving HTTP
servers. This rule flags, inside any function *reachable from a declared
async entry point* (``LintConfig.entry_points``, category ``async-loop`` —
every ``async def`` in ``fleet/``, ``data/api/`` and the serving workflow):

- direct blocking primitives: ``time.sleep``, ``requests.*``,
  ``subprocess.run``/``check_*``, ``fcntl.flock``/``lockf``, builtin
  ``open()``, ``os.fsync``, ``socket.create_connection``;
- calls into project functions that are *transitively* blocking — the
  registry's flock'd file I/O three calls below an async handler is
  reported AT the call site in the async module, naming the primitive it
  bottoms out in.

The sanctioned pattern is the one the codebase already uses everywhere:
hand the blocking callable to ``loop.run_in_executor`` (the callable is an
*argument* there, not a call, so no edge forms — and async-loop
reachability deliberately does not flow into nested executor-delegate
defs).
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    ProjectState,
    Severity,
    matches_any_glob,
    register_checker,
    register_rule,
)
from predictionio_tpu.analysis.reachability import CATEGORY_ASYNC, short_path

register_rule(
    "async-blocking-call",
    "async",
    Severity.ERROR,
    "blocking call (time.sleep/requests/subprocess/flock/open/sync "
    "socket) on an event-loop path; hand it to loop.run_in_executor or "
    "suppress with a reason",
)

_BLOCKING_LAST2 = {
    ("time", "sleep"): "time.sleep()",
    ("subprocess", "run"): "subprocess.run()",
    ("subprocess", "call"): "subprocess.call()",
    ("subprocess", "check_call"): "subprocess.check_call()",
    ("subprocess", "check_output"): "subprocess.check_output()",
    ("subprocess", "getoutput"): "subprocess.getoutput()",
    ("subprocess", "getstatusoutput"): "subprocess.getstatusoutput()",
    ("fcntl", "flock"): "fcntl.flock()",
    ("fcntl", "lockf"): "fcntl.lockf()",
    ("os", "fsync"): "os.fsync()",
    ("os", "fdatasync"): "os.fdatasync()",
    ("socket", "create_connection"): "socket.create_connection()",
    ("io", "open"): "io.open()",
}
_REQUESTS_VERBS = frozenset(
    {"get", "post", "put", "delete", "head", "patch", "options", "request"}
)


def _blocking_primitive_label(
    call: ast.Call, expand
) -> str | None:
    """Label when ``call`` is a known blocking primitive; ``expand``
    rewrites a dotted chain's head through the file's import table, so
    ``from time import sleep; sleep(...)`` and ``import subprocess as
    sp; sp.run(...)`` both resolve."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "open":
            return "open()"
        chain = expand((func.id,))
        if len(chain) >= 2 and chain[-2:] in _BLOCKING_LAST2:
            return _BLOCKING_LAST2[chain[-2:]]
        return None
    d = astutil.dotted(func)
    if not d:
        return None
    chain = expand(tuple(d.split(".")))
    if len(chain) >= 2:
        last2 = chain[-2:]
        if last2 in _BLOCKING_LAST2:
            return _BLOCKING_LAST2[last2]
        if chain[0] == "requests" and chain[-1] in _REQUESTS_VERBS:
            return f"requests.{chain[-1]}()"
    return None


@dataclasses.dataclass(frozen=True)
class _BlockInfo:
    """Why a project function counts as blocking."""

    label: str  # the primitive it bottoms out in
    path: str  # file of the primitive call
    line: int
    via: str | None  # callee key it was inherited through (None = direct)


def _blocking_closure(
    ctx: FileContext, state: ProjectState
) -> dict[str, _BlockInfo]:
    """Every function that blocks, directly or through a call chain —
    reverse-propagated over CALL edges, computed once per graph."""
    if ctx.cache.get("_blocking_graph") is state.graph:
        return ctx.cache["_blocking"]
    graph = state.graph
    blocking: dict[str, _BlockInfo] = {}
    for fn in graph.functions.values():
        expand = lambda chain, path=fn.path: graph.expand_chain(path, chain)
        for node in astutil.walk_skipping_nested_functions(fn.node.body):
            if not isinstance(node, ast.Call):
                continue
            label = _blocking_primitive_label(node, expand)
            if label is not None:
                blocking[fn.key] = _BlockInfo(
                    label, fn.path, node.lineno, None
                )
                break
    callers = graph.callers()
    queue = deque(blocking)
    while queue:
        key = queue.popleft()
        info = blocking[key]
        for caller in callers.get(key, ()):
            if caller in blocking:
                continue
            blocking[caller] = _BlockInfo(
                info.label, info.path, info.line, key
            )
            queue.append(caller)
    ctx.cache["_blocking"] = blocking
    ctx.cache["_blocking_graph"] = state.graph
    return blocking


@register_checker
def check_async_blocking(ctx: FileContext):
    if not matches_any_glob(ctx.graph_path, ctx.config.async_globs):
        return []
    state = ctx.project()
    blocking = _blocking_closure(ctx, state)
    graph = state.graph
    findings: list[Finding] = []
    for fn, origin in state.reach.iter_reachable_in_file(
        ctx.graph_path, CATEGORY_ASYNC
    ):
        note = state.reach.reach_note(fn, origin)
        expand = lambda chain, path=fn.path: graph.expand_chain(path, chain)
        # direct primitives in this function's own body
        for node in astutil.walk_skipping_nested_functions(fn.node.body):
            if not isinstance(node, ast.Call):
                continue
            label = _blocking_primitive_label(node, expand)
            if label is not None:
                findings.append(
                    ctx.finding(
                        "async-blocking-call",
                        node,
                        f"{label} blocks the event loop in {fn.name!r}"
                        f"{note}; hand it to loop.run_in_executor",
                    )
                )
        # calls that bottom out in a blocking primitive elsewhere; callees
        # inside async-glob modules are skipped — they are async-reachable
        # themselves and the primitive is reported there, at its own line
        reported: set[int] = set()
        for node, callee_key in graph.call_sites.get(fn.key, ()):
            if id(node) in reported:
                continue
            info = blocking.get(callee_key)
            if info is None:
                continue
            callee = graph.functions.get(callee_key)
            if callee is None or matches_any_glob(
                callee.path, ctx.config.async_globs
            ):
                continue
            reported.add(id(node))
            findings.append(
                ctx.finding(
                    "async-blocking-call",
                    node,
                    f"call to {callee.qualname!r} does blocking "
                    f"{info.label} ({short_path(info.path)}:{info.line}) "
                    f"on the event loop in {fn.name!r}{note}; hand it to "
                    "loop.run_in_executor",
                )
            )
    return findings
