"""Cross-file call graph over the linted project.

The engine's whole-program layer: every ``def`` in every parsed file becomes
a node; edges are added only where a call target can be resolved *statically
and conservatively*:

  - bare-name calls to same-module functions and lexically enclosing nested
    defs;
  - dotted calls resolved through the file's import table
    (``from x import y``, ``import x.y as z``, relative imports) by
    dotted-suffix matching against the indexed modules — so the graph works
    both for the installed package and for test fixture trees rooted
    anywhere;
  - ``self.meth()`` / ``cls.meth()`` dispatched to the enclosing class, its
    project ancestors, and its project descendants;
  - ``self.attr.meth()`` and ``var.meth()`` where the attribute/variable's
    class is inferred from an annotated parameter, an ``self.attr =
    ClassName(...)`` assignment, or a local ``var = ClassName(...)``
    construction.

Anything dynamic — arbitrary ``obj.meth()``, callables passed as values,
getattr — produces NO edge. Reachability built on this graph therefore
under-approximates, never explodes: a missing edge costs a finding, a wrong
edge would cost a false positive, and the rules' contract is no false
positives.

Nested ``def``s are their own nodes, linked to the enclosing function by a
NESTED edge (lexical containment) distinct from CALL edges (explicit
invocation). Rule families choose per entry-point category whether
reachability flows through NESTED edges: serving/predict/train follow them
(the dispatch pattern returns ``finalize`` closures that run on the serving
path), the async-loop category does not (the executor-delegate pattern —
``def _work(): ...; await loop.run_in_executor(None, _work)`` — is exactly
a nested def that must NOT inherit the event-loop context).

No jax / numpy imports here: the linter must start fast and never touch an
accelerator runtime.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Iterator

__all__ = [
    "FunctionNode",
    "ClassInfo",
    "ProjectGraph",
    "build_project",
    "module_parts",
]


def module_parts(path: str) -> tuple[str, ...]:
    """Normalize a file path to dotted-module-ish parts for suffix matching.

    ``/root/repo/predictionio_tpu/ops/topk.py`` ->
    ``("root", "repo", "predictionio_tpu", "ops", "topk")``;
    ``pkg/__init__.py`` -> ``("pkg",)``.
    """
    norm = path.replace("\\", "/")
    if norm.endswith(".py"):
        norm = norm[:-3]
    parts = tuple(p for p in norm.split("/") if p and p != ".")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return parts


@dataclasses.dataclass
class FunctionNode:
    """One ``def`` (top-level, method, or nested) in the project."""

    key: str  # "<path>::<qualname>" — stable node id
    path: str  # the display/abs path the file was analyzed under
    parts: tuple[str, ...]  # module parts of that path
    qualname: str  # "fn", "Cls.meth", "fn.<locals>.inner"
    name: str
    lineno: int
    is_async: bool
    node: ast.FunctionDef | ast.AsyncFunctionDef
    class_name: str | None = None  # immediately enclosing class, if a method
    parent: str | None = None  # enclosing function's key, if nested

    @property
    def dotted(self) -> tuple[str, ...]:
        """Suffix-matchable tuple for import resolution. Nested functions
        are not importable and return ``()`` (never matched)."""
        if self.parent is not None:
            return ()
        if self.class_name is not None:
            return self.parts + (self.class_name, self.name)
        return self.parts + (self.name,)


@dataclasses.dataclass
class ClassInfo:
    name: str
    path: str
    parts: tuple[str, ...]
    bases: tuple[tuple[str, ...], ...]  # import-expanded dotted base names
    methods: dict[str, str] = dataclasses.field(default_factory=dict)
    # attribute name -> candidate class keys ("<path>::<name>")
    attr_types: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )

    @property
    def key(self) -> str:
        return f"{self.path}::{self.name}"

    @property
    def dotted(self) -> tuple[str, ...]:
        return self.parts + (self.name,)


_FN_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _dotted_chain(node: ast.AST) -> tuple[str, ...] | None:
    """``a.b.c`` -> ("a","b","c"); None when the chain bottoms out in a
    call, subscript, or other non-name expression."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _annotation_chains(ann: ast.AST | None) -> list[tuple[str, ...]]:
    """Every plausible class reference inside an annotation expression:
    handles ``X``, ``mod.X``, ``X | None``, ``Optional[X]``, and string
    annotations (parsed)."""
    if ann is None:
        return []
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return []
    out: list[tuple[str, ...]] = []
    for node in ast.walk(ann):
        if isinstance(node, ast.Attribute):
            chain = _dotted_chain(node)
            if chain:
                out.append(chain)
        elif isinstance(node, ast.Name):
            out.append((node.id,))
    # drop chains that are prefixes of longer collected chains (walking an
    # Attribute also yields its inner Name)
    longest = [
        c
        for c in out
        if not any(o != c and o[: len(c)] == c for o in out)
    ]
    return longest


class _FileIndex:
    """Per-file state gathered in pass 1."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.parts = module_parts(path)
        self.is_pkg = path.replace("\\", "/").endswith("/__init__.py")
        self.imports: dict[str, tuple[str, ...]] = {}
        self.top_defs: dict[str, str] = {}  # module-level fn name -> key
        self.classes: list[ClassInfo] = []

    def expand(self, chain: tuple[str, ...]) -> tuple[str, ...]:
        """Rewrite a dotted chain's head through the import table."""
        if chain and chain[0] in self.imports:
            return self.imports[chain[0]] + chain[1:]
        return chain


class ProjectGraph:
    """All functions + resolved CALL / NESTED edges across a set of files."""

    def __init__(self) -> None:
        self.functions: dict[str, FunctionNode] = {}
        self.calls: dict[str, set[str]] = {}
        # per-call-site resolution: caller key -> [(ast.Call, callee key)]
        # — rules that report AT the call site (async-blocking-call) need
        # the node, not just the edge
        self.call_sites: dict[str, list[tuple[ast.Call, str]]] = {}
        self.nested: dict[str, set[str]] = {}
        self.classes: dict[str, ClassInfo] = {}
        self._files: dict[str, _FileIndex] = {}
        # bare function name -> importable nodes (top-level fns + methods)
        self._fn_by_name: dict[str, list[FunctionNode]] = {}
        self._class_by_name: dict[str, list[ClassInfo]] = {}
        self._subclasses: dict[str, set[str]] = {}  # class key -> subclasses
        # function key -> keys of functions defined in the same file at
        # module level (bare-name resolution scope)
        self._callers_cache: dict[str, set[str]] | None = None

    # ------------------------------------------------------------ queries
    def has_file(self, path: str) -> bool:
        return path in self._files

    def file_trees(self) -> Iterator[tuple[str, ast.Module]]:
        for path, fi in self._files.items():
            yield path, fi.tree

    def file_imports(self, path: str) -> dict[str, tuple[str, ...]]:
        """The file's import table (alias -> dotted target)."""
        fi = self._files.get(path)
        return fi.imports if fi is not None else {}

    def expand_chain(
        self, path: str, chain: tuple[str, ...]
    ) -> tuple[str, ...]:
        """Rewrite a dotted chain's head through the file's imports."""
        fi = self._files.get(path)
        return fi.expand(chain) if fi is not None else chain

    def functions_in(self, path: str) -> Iterator[FunctionNode]:
        for fn in self.functions.values():
            if fn.path == path:
                yield fn

    def callees(self, key: str) -> set[str]:
        return self.calls.get(key, set())

    def callers(self) -> dict[str, set[str]]:
        """Reverse CALL edges, computed once."""
        if self._callers_cache is None:
            rev: dict[str, set[str]] = {}
            for src, dsts in self.calls.items():
                for dst in dsts:
                    rev.setdefault(dst, set()).add(src)
            self._callers_cache = rev
        return self._callers_cache

    def class_of(self, fn: FunctionNode) -> ClassInfo | None:
        if fn.class_name is None:
            return None
        return self.classes.get(f"{fn.path}::{fn.class_name}")

    # ----------------------------------------------------------- building
    def _index_file(self, path: str, tree: ast.Module) -> None:
        fi = _FileIndex(path, tree)
        self._files[path] = fi
        self._collect_imports(fi)
        self._collect_defs(fi, tree.body, qual=(), cls=None, parent=None)

    def _collect_imports(self, fi: _FileIndex) -> None:
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = tuple(alias.name.split("."))
                    if alias.asname:
                        fi.imports[alias.asname] = target
                    else:
                        fi.imports[target[0]] = target[:1]
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # relative: strip the module name plus (level-1)
                    # packages; a package __init__ IS its package, so its
                    # parts have nothing extra to strip at level 1
                    drop = node.level - (1 if fi.is_pkg else 0)
                    base = fi.parts[: len(fi.parts) - drop]
                else:
                    base = ()
                if node.module:
                    base = base + tuple(node.module.split("."))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    fi.imports[alias.asname or alias.name] = base + (
                        alias.name,
                    )

    def _collect_defs(
        self,
        fi: _FileIndex,
        body: Iterable[ast.stmt],
        qual: tuple[str, ...],
        cls: ClassInfo | None,
        parent: str | None,
    ) -> None:
        for stmt in body:
            if isinstance(stmt, _FN_DEFS):
                qn = ".".join(qual + (stmt.name,))
                key = f"{fi.path}::{qn}"
                fn = FunctionNode(
                    key=key,
                    path=fi.path,
                    parts=fi.parts,
                    qualname=qn,
                    name=stmt.name,
                    lineno=stmt.lineno,
                    is_async=isinstance(stmt, ast.AsyncFunctionDef),
                    node=stmt,
                    class_name=cls.name if cls is not None else None,
                    parent=parent,
                )
                self.functions[key] = fn
                if parent is None and cls is None:
                    fi.top_defs[stmt.name] = key
                if fn.dotted:
                    self._fn_by_name.setdefault(stmt.name, []).append(fn)
                if parent is not None:
                    self.nested.setdefault(parent, set()).add(key)
                if cls is not None:
                    cls.methods.setdefault(stmt.name, key)
                # nested defs inside this function
                self._collect_defs(
                    fi,
                    stmt.body,
                    qual + (stmt.name, "<locals>"),
                    cls=None,
                    parent=key,
                )
            elif isinstance(stmt, ast.ClassDef):
                bases = []
                for b in stmt.bases:
                    chain = _dotted_chain(b)
                    if chain:
                        bases.append(fi.expand(chain))
                info = ClassInfo(
                    name=stmt.name,
                    path=fi.path,
                    parts=fi.parts,
                    bases=tuple(bases),
                )
                fi.classes.append(info)
                self.classes[info.key] = info
                self._class_by_name.setdefault(stmt.name, []).append(info)
                self._collect_defs(
                    fi,
                    stmt.body,
                    qual + (stmt.name,),
                    cls=info,
                    parent=parent,
                )
            elif isinstance(
                stmt, (ast.If, ast.Try, ast.With, ast.For, ast.While)
            ):
                # defs behind guards (TYPE_CHECKING, try/except) still count
                for sub in ast.iter_child_nodes(stmt):
                    if isinstance(sub, ast.stmt):
                        self._collect_defs(fi, [sub], qual, cls, parent)

    # --------------------------------------------------------- resolution
    def _resolve_dotted_fn(self, chain: tuple[str, ...]) -> list[str]:
        """Suffix-match an import-expanded dotted chain against importable
        functions. ``("predictionio_tpu","ops","topk","fetch_topk")``
        matches the node whose dotted tuple ends with it."""
        if not chain:
            return []
        cands = self._fn_by_name.get(chain[-1], [])
        out = []
        for fn in cands:
            d = fn.dotted
            if len(chain) <= len(d) and d[-len(chain):] == chain:
                out.append(fn.key)
        return out

    def _resolve_dotted_class(
        self, chain: tuple[str, ...]
    ) -> list[ClassInfo]:
        if not chain:
            return []
        out = []
        for cls in self._class_by_name.get(chain[-1], []):
            d = cls.dotted
            if len(chain) <= len(d) and d[-len(chain):] == chain:
                out.append(cls)
        return out

    def _class_hierarchy(self, cls: ClassInfo) -> list[ClassInfo]:
        """The class plus its project ancestors and descendants."""
        seen: dict[str, ClassInfo] = {}
        stack = [cls]
        while stack:  # ancestors
            c = stack.pop()
            if c.key in seen:
                continue
            seen[c.key] = c
            for base in c.bases:
                stack.extend(self._resolve_dotted_class(base))
        stack = [cls]
        visited = set()
        while stack:  # descendants
            c = stack.pop()
            if c.key in visited:
                continue
            visited.add(c.key)
            for sub_key in self._subclasses.get(c.key, ()):
                sub = self.classes.get(sub_key)
                if sub is not None and sub.key not in seen:
                    seen[sub.key] = sub
                    stack.append(sub)
        return list(seen.values())

    def _method_candidates(self, cls: ClassInfo, meth: str) -> list[str]:
        return [
            c.methods[meth]
            for c in self._class_hierarchy(cls)
            if meth in c.methods
        ]

    def _infer_attr_types(self) -> None:
        """Populate ClassInfo.attr_types from ``self.X = <typed thing>``
        assignments and annotated ``__init__`` parameters."""
        for fi in self._files.values():
            for cls in fi.classes:
                for meth_key in cls.methods.values():
                    fn = self.functions[meth_key]
                    params: dict[str, ast.AST | None] = {}
                    args = fn.node.args
                    for a in (
                        list(args.posonlyargs)
                        + list(args.args)
                        + list(args.kwonlyargs)
                    ):
                        params[a.arg] = a.annotation
                    for stmt in ast.walk(fn.node):
                        attr_name, value, ann = None, None, None
                        if isinstance(stmt, ast.Assign):
                            for tgt in stmt.targets:
                                if (
                                    isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"
                                ):
                                    attr_name, value = tgt.attr, stmt.value
                        elif isinstance(stmt, ast.AnnAssign):
                            tgt = stmt.target
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                attr_name, value = tgt.attr, stmt.value
                                ann = stmt.annotation
                        if attr_name is None:
                            continue
                        types: list[str] = []
                        chains: list[tuple[str, ...]] = []
                        if ann is not None:
                            chains.extend(_annotation_chains(ann))
                        if isinstance(value, ast.Call):
                            chain = _dotted_chain(value.func)
                            if chain:
                                chains.append(chain)
                        elif isinstance(value, ast.Name) and value.id in params:
                            chains.extend(
                                _annotation_chains(params[value.id])
                            )
                        elif (
                            isinstance(value, ast.BoolOp)
                            or isinstance(value, ast.IfExp)
                        ):
                            # `x or Default()` / conditional defaults
                            for sub in ast.walk(value):
                                if isinstance(sub, ast.Call):
                                    chain = _dotted_chain(sub.func)
                                    if chain:
                                        chains.append(chain)
                                elif (
                                    isinstance(sub, ast.Name)
                                    and sub.id in params
                                ):
                                    chains.extend(
                                        _annotation_chains(params[sub.id])
                                    )
                        for chain in chains:
                            for c in self._resolve_dotted_class(
                                fi.expand(chain)
                            ):
                                types.append(c.key)
                        if types:
                            merged = tuple(
                                dict.fromkeys(
                                    cls.attr_types.get(attr_name, ())
                                    + tuple(types)
                                )
                            )
                            cls.attr_types[attr_name] = merged

    def _local_var_types(
        self, fn: FunctionNode, fi: _FileIndex
    ) -> dict[str, list[ClassInfo]]:
        """``store = ArtifactStore(d)`` style local constructions, plus
        annotated parameters of the function itself."""
        out: dict[str, list[ClassInfo]] = {}
        args = fn.node.args
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ):
            classes = []
            for chain in _annotation_chains(a.annotation):
                classes.extend(self._resolve_dotted_class(fi.expand(chain)))
            if classes:
                out[a.arg] = classes
        for stmt in _own_body_walk(fn.node):
            if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call
            ):
                chain = _dotted_chain(stmt.value.func)
                if not chain:
                    continue
                classes = self._resolve_dotted_class(fi.expand(chain))
                if not classes:
                    continue
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = classes
        return out

    def _add_call_edges(self, fn: FunctionNode, fi: _FileIndex) -> None:
        edges = self.calls.setdefault(fn.key, set())
        sites = self.call_sites.setdefault(fn.key, [])
        own_cls = self.class_of(fn)
        local_types = self._local_var_types(fn, fi)
        # lexical scope chain of nested-def names
        scope: dict[str, str] = {}
        anc = fn
        while True:
            for k in self.nested.get(anc.key, ()):
                nested_fn = self.functions[k]
                scope.setdefault(nested_fn.name, k)
            if anc.parent is None:
                break
            anc = self.functions[anc.parent]
        for node in _own_body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue

            def add(callees: Iterable[str], node: ast.Call = node) -> None:
                for c in callees:
                    edges.add(c)
                    sites.append((node, c))

            func = node.func
            if isinstance(func, ast.Name):
                name = func.id
                if name in scope:
                    add((scope[name],))
                elif name in fi.top_defs:
                    add((fi.top_defs[name],))
                elif name in fi.imports:
                    add(self._resolve_dotted_fn(fi.imports[name]))
                continue
            chain = _dotted_chain(func)
            if not chain or len(chain) < 2:
                continue
            head, meth = chain[0], chain[-1]
            if head in ("self", "cls") and own_cls is not None:
                if len(chain) == 2:
                    add(self._method_candidates(own_cls, meth))
                elif len(chain) == 3:
                    for cls_key in own_cls.attr_types.get(chain[1], ()):
                        cls = self.classes.get(cls_key)
                        if cls is not None:
                            add(self._method_candidates(cls, meth))
                continue
            if len(chain) == 2 and head in local_types:
                for cls in local_types[head]:
                    add(self._method_candidates(cls, meth))
                continue
            add(self._resolve_dotted_fn(fi.expand(chain)))

    def _link_subclasses(self) -> None:
        for cls in self.classes.values():
            for base in cls.bases:
                for parent in self._resolve_dotted_class(base):
                    self._subclasses.setdefault(parent.key, set()).add(
                        cls.key
                    )


def _own_body_walk(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> Iterator[ast.AST]:
    """Walk a function's own statements without descending into nested
    defs, classes, or lambdas — those are separate graph nodes (or, for
    lambdas, deliberately unresolved)."""
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda),
            ):
                continue
            stack.append(child)


def build_project(
    files: Iterable[tuple[str, ast.Module]],
) -> ProjectGraph:
    """Index every (path, parsed tree) pair and resolve call edges."""
    graph = ProjectGraph()
    for path, tree in files:
        graph._index_file(path, tree)
    graph._link_subclasses()
    graph._infer_attr_types()
    for fi in graph._files.values():
        for fn in graph.functions_in(fi.path):
            graph._add_call_edges(fn, fi)
    return graph
