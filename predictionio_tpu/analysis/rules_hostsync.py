"""Host-sync rules for serving-path modules.

``block_until_ready``, ``jax.device_get`` and ``np.asarray`` on a device
array all stall the caller until the device round-trip completes. In a
training script that's a benchmark tool; in the asyncio serving hot path
(`controller/serving.py`, `workflow/create_server.py`, `data/api/`) it
parks the event loop behind TPU latency and the p99 collapses under load.
Legitimate syncs (startup warm-up, final response materialization) get an
inline suppression with a reason, or live in a function named in
``LintConfig.hostsync_allow_functions``.
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    matches_any_glob,
    register_checker,
    register_rule,
)

register_rule(
    "hostsync-serving-path",
    "hostsync",
    Severity.ERROR,
    "blocking device->host sync (block_until_ready/device_get/np.asarray) "
    "in a serving-path module; move it off the request path or suppress "
    "with a reason",
)

_SYNC_METHODS = frozenset({"block_until_ready"})
_SYNC_DOTTED_LAST2 = frozenset(
    {
        ("jax", "device_get"),
        ("jax", "block_until_ready"),
        ("np", "asarray"),
        ("numpy", "asarray"),
        ("onp", "asarray"),
    }
)


def _sync_call_label(call: ast.Call) -> str | None:
    """A human label when ``call`` is a blocking sync, else None."""
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr in _SYNC_METHODS:
            return f".{func.attr}()"
        d = astutil.dotted(func)
        if d:
            parts = tuple(d.split("."))
            if len(parts) >= 2 and parts[-2:] in _SYNC_DOTTED_LAST2:
                return d + "()"
    elif isinstance(func, ast.Name) and func.id in (
        "device_get",
        "block_until_ready",
    ):
        return func.id + "()"
    return None


register_rule(
    "serving-host-roundtrip",
    "hostsync",
    Severity.ERROR,
    "corpus-sized device fetch (one-arg np.asarray / jax.device_get / "
    ".block_until_ready) or host argsort/argpartition on an engine "
    "predict path; fuse score+select on device via ops/topk (host-born "
    "scores end through topk.host_top_k)",
)

# one-arg np.asarray(x) on a predict path is the materialize-a-device-array
# smell; the two-arg np.asarray(x, dtype) host idiom (converting a Python
# list with an explicit dtype) is exempt — same contract as the
# train-unaccounted-sync rule.
_ROUNDTRIP_ASARRAY_LAST2 = frozenset(
    {("np", "asarray"), ("numpy", "asarray"), ("onp", "asarray")}
)
_ROUNDTRIP_ALWAYS_LAST2 = frozenset(
    {
        ("np", "argsort"),
        ("numpy", "argsort"),
        ("np", "argpartition"),
        ("numpy", "argpartition"),
        ("jax", "device_get"),
    }
)


def _roundtrip_label(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr == "block_until_ready":
            return ".block_until_ready()"
        d = astutil.dotted(func)
        if d:
            parts = tuple(d.split("."))
            if len(parts) >= 2:
                last2 = parts[-2:]
                if last2 in _ROUNDTRIP_ALWAYS_LAST2:
                    return d + "()"
                if (
                    last2 in _ROUNDTRIP_ASARRAY_LAST2
                    and len(call.args) == 1
                    and not call.keywords
                ):
                    return d + "()"
    elif isinstance(func, ast.Name) and func.id == "device_get":
        return "device_get()"
    return None


@register_checker
def check_serving_roundtrip(ctx: FileContext):
    """The engines' predict paths must route score+select through the
    fused top-k helper: flag the full-fetch/host-sort endings inside the
    predict-path functions (LintConfig.serving_predict_functions),
    including their nested helpers (a dispatch's ``finalize``)."""
    cfg = ctx.config
    if not matches_any_glob(
        ctx.path or ctx.display_path, cfg.serving_predict_globs
    ):
        return []
    predict_names = set(cfg.serving_predict_functions)
    findings: list[Finding] = []
    seen: set[int] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in predict_names:
            continue
        for sub in ast.walk(node):  # includes nested functions by design
            if not isinstance(sub, ast.Call) or id(sub) in seen:
                continue
            seen.add(id(sub))
            label = _roundtrip_label(sub)
            if label:
                findings.append(
                    ctx.finding(
                        "serving-host-roundtrip",
                        sub,
                        f"{label} in {node.name!r} round-trips host-side; "
                        "route score+select through ops/topk "
                        "(fused top-k / host_top_k)",
                    )
                )
    return findings


register_rule(
    "eval-per-query-predict",
    "hostsync",
    Severity.ERROR,
    "per-query .predict() call on the evaluation grid's cell scoring "
    "path; held-out queries must go through Engine.dispatch_batch "
    "mega-batches (tuning/cells.dispatch_scores) — a predict loop costs "
    "one device round-trip per held-out query per cell",
)


@register_checker
def check_eval_per_query_predict(ctx: FileContext):
    """The grid's whole reason to exist is deleting the sequential
    MetricEvaluator's per-query device round-trips; hold that property
    statically: inside the cell-scoring functions (and their nested
    helpers), any ``X.predict(...)`` attribute call is an error.
    ``predict_batch``/``predict_batch_dispatch``/``batch_predict`` (the
    batched entries dispatch_batch composes) are the sanctioned
    spellings."""
    cfg = ctx.config
    if not matches_any_glob(ctx.path or ctx.display_path, cfg.tuning_globs):
        return []
    scoring_names = set(cfg.eval_scoring_functions)
    findings: list[Finding] = []
    seen: set[int] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in scoring_names:
            continue
        for sub in ast.walk(node):  # nested helpers covered by design
            if not isinstance(sub, ast.Call) or id(sub) in seen:
                continue
            seen.add(id(sub))
            func = sub.func
            if isinstance(func, ast.Attribute) and func.attr == "predict":
                findings.append(
                    ctx.finding(
                        "eval-per-query-predict",
                        sub,
                        f".predict() inside {node.name!r} scores one query "
                        "per device round-trip; route the batch through "
                        "Engine.dispatch_batch (tuning/cells."
                        "dispatch_scores)",
                    )
                )
    return findings


@register_checker
def check_hostsync(ctx: FileContext):
    cfg = ctx.config
    # match on the absolute path when we have one: the display path is
    # cwd-relative and would silently miss the globs when linting from
    # inside the package tree
    if not matches_any_glob(ctx.path or ctx.display_path, cfg.serving_globs):
        return []
    findings: list[Finding] = []
    allow = set(cfg.hostsync_allow_functions)

    def visit(body: list[ast.stmt], fn_stack: tuple[str, ...]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(stmt.body, fn_stack + (stmt.name,))
                continue
            if isinstance(stmt, ast.ClassDef):
                visit(stmt.body, fn_stack)
                continue
            if fn_stack and fn_stack[-1] in allow:
                continue
            for node in astutil.walk_skipping_nested_functions([stmt]):
                if isinstance(node, ast.Call):
                    label = _sync_call_label(node)
                    if label:
                        where = (
                            f" in {fn_stack[-1]!r}" if fn_stack else " at module level"
                        )
                        findings.append(
                            ctx.finding(
                                "hostsync-serving-path",
                                node,
                                f"{label} blocks on a device->host sync"
                                f"{where} on the serving path",
                            )
                        )
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit(node.body, fn_stack + (node.name,))
                elif isinstance(node, ast.ClassDef):
                    visit(node.body, fn_stack)

    visit(ctx.tree.body, ())
    return findings
