"""Host-sync rules for the serving / predict / eval reachability scopes.

``block_until_ready``, ``jax.device_get`` and ``np.asarray`` on a device
array all stall the caller until the device round-trip completes. In a
training script that's a benchmark tool; on the asyncio serving hot path it
parks the event loop behind TPU latency and the p99 collapses under load.

Since ISSUE 16 these rules are reachability-targeted: they fire in ANY
function the call graph can reach from a declared entry point of the
matching category (``LintConfig.entry_points``) — a helper three calls
below ``predict_batch_dispatch`` in a module no glob names is in scope.
Legitimate syncs (startup warm-up, final response materialization) get an
inline suppression with a reason.
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    matches_any_glob,
    register_checker,
    register_rule,
)
from predictionio_tpu.analysis.reachability import (
    CATEGORY_EVAL,
    CATEGORY_PREDICT,
    CATEGORY_SERVING,
)

register_rule(
    "hostsync-serving-path",
    "hostsync",
    Severity.ERROR,
    "blocking device->host sync (block_until_ready/device_get/np.asarray) "
    "in a function reachable from a serving entry point; move it off the "
    "request path or suppress with a reason",
)

_SYNC_METHODS = frozenset({"block_until_ready"})
_SYNC_DOTTED_LAST2 = frozenset(
    {
        ("jax", "device_get"),
        ("jax", "block_until_ready"),
        ("np", "asarray"),
        ("numpy", "asarray"),
        ("onp", "asarray"),
    }
)


def _sync_call_label(call: ast.Call) -> str | None:
    """A human label when ``call`` is a blocking sync, else None."""
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr in _SYNC_METHODS:
            return f".{func.attr}()"
        d = astutil.dotted(func)
        if d:
            parts = tuple(d.split("."))
            if len(parts) >= 2 and parts[-2:] in _SYNC_DOTTED_LAST2:
                return d + "()"
    elif isinstance(func, ast.Name) and func.id in (
        "device_get",
        "block_until_ready",
    ):
        return func.id + "()"
    return None


register_rule(
    "serving-host-roundtrip",
    "hostsync",
    Severity.ERROR,
    "corpus-sized device fetch (one-arg np.asarray / jax.device_get / "
    ".block_until_ready) or host argsort/argpartition on an engine "
    "predict path; fuse score+select on device via ops/topk (host-born "
    "scores end through topk.host_top_k)",
)

# one-arg np.asarray(x) on a predict path is the materialize-a-device-array
# smell; the two-arg np.asarray(x, dtype) host idiom (converting a Python
# list with an explicit dtype) is exempt — same contract as the
# train-unaccounted-sync rule.
_ROUNDTRIP_ASARRAY_LAST2 = frozenset(
    {("np", "asarray"), ("numpy", "asarray"), ("onp", "asarray")}
)
_ROUNDTRIP_ALWAYS_LAST2 = frozenset(
    {
        ("np", "argsort"),
        ("numpy", "argsort"),
        ("np", "argpartition"),
        ("numpy", "argpartition"),
        ("jax", "device_get"),
    }
)


def _roundtrip_label(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr == "block_until_ready":
            return ".block_until_ready()"
        d = astutil.dotted(func)
        if d:
            parts = tuple(d.split("."))
            if len(parts) >= 2:
                last2 = parts[-2:]
                if last2 in _ROUNDTRIP_ALWAYS_LAST2:
                    return d + "()"
                if (
                    last2 in _ROUNDTRIP_ASARRAY_LAST2
                    and len(call.args) == 1
                    and not call.keywords
                ):
                    return d + "()"
    elif isinstance(func, ast.Name) and func.id == "device_get":
        return "device_get()"
    return None


@register_checker
def check_serving_roundtrip(ctx: FileContext):
    """The engines' predict paths must route score+select through the
    fused top-k helper: flag the full-fetch/host-sort endings in every
    function reachable from a predict entry point (the declared roots —
    ``Engine.dispatch_batch``, the batchpredict drain, the ann search
    path, the eval-grid cell scorers — plus everything they call,
    including nested ``finalize`` helpers)."""
    state = ctx.project()
    findings: list[Finding] = []
    for fn, origin in state.reach.iter_reachable_in_file(
        ctx.graph_path, CATEGORY_PREDICT
    ):
        note = state.reach.reach_note(fn, origin)
        for sub in astutil.walk_skipping_nested_functions(fn.node.body):
            if not isinstance(sub, ast.Call):
                continue
            label = _roundtrip_label(sub)
            if label:
                findings.append(
                    ctx.finding(
                        "serving-host-roundtrip",
                        sub,
                        f"{label} in {fn.name!r} round-trips host-side; "
                        "route score+select through ops/topk "
                        f"(fused top-k / host_top_k){note}",
                    )
                )
    return findings


register_rule(
    "eval-per-query-predict",
    "hostsync",
    Severity.ERROR,
    "per-query .predict() call on the evaluation grid's cell scoring "
    "path; held-out queries must go through Engine.dispatch_batch "
    "mega-batches (tuning/cells.dispatch_scores) — a predict loop costs "
    "one device round-trip per held-out query per cell",
)


@register_checker
def check_eval_per_query_predict(ctx: FileContext):
    """The grid's whole reason to exist is deleting the sequential
    MetricEvaluator's per-query device round-trips; hold that property
    statically: in any function reachable from a declared cell-scoring
    entry (``dispatch_scores``/``score_cell``), a ``X.predict(...)``
    attribute call is an error. ``predict_batch``/
    ``predict_batch_dispatch``/``batch_predict`` (the batched entries
    dispatch_batch composes) are the sanctioned spellings."""
    state = ctx.project()
    findings: list[Finding] = []
    for fn, origin in state.reach.iter_reachable_in_file(
        ctx.graph_path, CATEGORY_EVAL
    ):
        note = state.reach.reach_note(fn, origin)
        for sub in astutil.walk_skipping_nested_functions(fn.node.body):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if isinstance(func, ast.Attribute) and func.attr == "predict":
                findings.append(
                    ctx.finding(
                        "eval-per-query-predict",
                        sub,
                        f".predict() inside {fn.name!r} scores one query "
                        "per device round-trip; route the batch through "
                        "Engine.dispatch_batch (tuning/cells."
                        f"dispatch_scores){note}",
                    )
                )
    return findings


@register_checker
def check_hostsync(ctx: FileContext):
    """Serving-path host syncs: module-level statements in the declared
    serving entry modules, plus every function reachable from a serving
    entry point — wherever it lives."""
    state = ctx.project()
    findings: list[Finding] = []
    serving_globs = state.reach.entry_module_globs(CATEGORY_SERVING)
    if matches_any_glob(ctx.graph_path, serving_globs):
        for node in astutil.walk_skipping_nested_functions(
            astutil.module_level_statements(ctx.tree)
        ):
            if isinstance(node, ast.Call):
                label = _sync_call_label(node)
                if label:
                    findings.append(
                        ctx.finding(
                            "hostsync-serving-path",
                            node,
                            f"{label} blocks on a device->host sync at "
                            "module level on the serving path",
                        )
                    )
    for fn, origin in state.reach.iter_reachable_in_file(
        ctx.graph_path, CATEGORY_SERVING
    ):
        note = state.reach.reach_note(fn, origin)
        for node in astutil.walk_skipping_nested_functions(fn.node.body):
            if not isinstance(node, ast.Call):
                continue
            label = _sync_call_label(node)
            if label:
                findings.append(
                    ctx.finding(
                        "hostsync-serving-path",
                        node,
                        f"{label} blocks on a device->host sync in "
                        f"{fn.name!r} on the serving path{note}",
                    )
                )
    return findings
