"""Training-path rules.

``train-unaccounted-sync``: a bare device->host sync inside a
training-loop module. The xray step profiler's contract is that the
per-phase timeline **tiles the train wall clock** and that device time is
explicitly accounted (``pio_train_device_seconds_total``, the
``deviceTimeFrac`` every manifest carries). A raw
``jax.block_until_ready`` / ``jax.device_get`` / one-arg ``np.asarray`` /
``.item()`` on a device value stalls the host for a device round-trip
that *no instrument sees* — the profile under-reports device time and the
roofline math in docs/PERF.md silently rots. Sanctioned forms:

- ``obs.jaxprof.timed_block_until_ready(x, registry, where=…)``
- ``obs.xray.device_fetch(x, where=…)`` / ``TrainProfile.device_barrier``
- an inline suppression with a reason, for syncs that ARE the instrument
  (e.g. ``ops/als.fetch_barrier``) or host-side ``np.asarray`` the
  heuristic can't prove harmless.

Scope (since ISSUE 16): every function REACHABLE from a declared train
entry point (``LintConfig.entry_points``, category ``train`` — the
training-loop modules seed every def), plus module-level statements in
those modules. A sync inside a helper another module provides to the train
loop is in scope even though no glob names it. ``np.asarray`` is only
flagged in its one-argument form — the two-argument
``np.asarray(x, np.float32)`` idiom is how this codebase converts *host*
inputs (a dtype on a device fetch would be a copy anyway), while the bare
one-argument form is exactly the device-readback idiom.
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    matches_any_glob,
    register_checker,
    register_rule,
)
from predictionio_tpu.analysis.reachability import CATEGORY_TRAIN

register_rule(
    "train-unaccounted-sync",
    "hostsync",
    Severity.ERROR,
    "bare device->host sync (block_until_ready/device_get/one-arg "
    "np.asarray/.item()) in a training-loop module; route it through "
    "obs.jaxprof.timed_block_until_ready or obs.xray.device_fetch so the "
    "stall lands in the train profile, or suppress with a reason",
)

_SYNC_DOTTED_LAST2 = frozenset(
    {
        ("jax", "device_get"),
        ("jax", "block_until_ready"),
    }
)
_ASARRAY_LAST2 = frozenset(
    {
        ("np", "asarray"),
        ("numpy", "asarray"),
        ("onp", "asarray"),
    }
)


def _sync_label(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr == "block_until_ready":
            return ".block_until_ready()"
        if func.attr == "item" and not call.args and not call.keywords:
            return ".item()"
        d = astutil.dotted(func)
        if d:
            parts = tuple(d.split("."))
            if len(parts) >= 2:
                if parts[-2:] in _SYNC_DOTTED_LAST2:
                    return d + "()"
                if (
                    parts[-2:] in _ASARRAY_LAST2
                    and len(call.args) == 1
                    and not call.keywords
                ):
                    return d + "(x)"
    elif isinstance(func, ast.Name) and func.id in (
        "device_get",
        "block_until_ready",
    ):
        return func.id + "()"
    return None


_MESSAGE = (
    "is an unaccounted device->host sync on the "
    "training path; device time leaks out of the train "
    "profile — use timed_block_until_ready / "
    "obs.xray.device_fetch (or suppress with a reason)"
)


@register_checker
def check_train_unaccounted_sync(ctx: FileContext):
    state = ctx.project()
    findings: list[Finding] = []
    train_globs = state.reach.entry_module_globs(CATEGORY_TRAIN)
    if matches_any_glob(ctx.graph_path, train_globs):
        for node in astutil.walk_skipping_nested_functions(
            astutil.module_level_statements(ctx.tree)
        ):
            if isinstance(node, ast.Call):
                label = _sync_label(node)
                if label:
                    findings.append(
                        ctx.finding(
                            "train-unaccounted-sync",
                            node,
                            f"{label} {_MESSAGE}",
                        )
                    )
    for fn, origin in state.reach.iter_reachable_in_file(
        ctx.graph_path, CATEGORY_TRAIN
    ):
        note = state.reach.reach_note(fn, origin)
        for node in astutil.walk_skipping_nested_functions(fn.node.body):
            if not isinstance(node, ast.Call):
                continue
            label = _sync_label(node)
            if label:
                findings.append(
                    ctx.finding(
                        "train-unaccounted-sync",
                        node,
                        f"{label} {_MESSAGE}{note}",
                    )
                )
    return findings
