"""Tracer-safety rules: Python control flow and host casts on traced values.

Inside a ``jit``/``pjit``/``shard_map``-staged function the arguments are
tracers — abstract values with a shape and dtype but no data. Any Python
construct that needs the *data* either crashes at trace time
(``TracerBoolConversionError``) or, worse, silently bakes the first call's
value into the compiled program. Both are deploy-time landmines this rule
family surfaces at review time.
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    register_checker,
    register_rule,
)

register_rule(
    "tracer-python-branch",
    "tracer",
    Severity.ERROR,
    "Python if/while/assert branches on a traced value inside a jitted "
    "function; use lax.cond/lax.while_loop/jnp.where or declare the "
    "argument static",
)
register_rule(
    "tracer-host-cast",
    "tracer",
    Severity.ERROR,
    "int()/float()/bool()/.item() forces a traced value to a host scalar "
    "inside a jitted function; keep the computation on-device",
)

_CAST_BUILTINS = frozenset({"int", "float", "bool"})
_CAST_METHODS = frozenset({"item", "tolist"})


def _check_jitted_function(
    ctx: FileContext, fn: ast.FunctionDef | ast.AsyncFunctionDef, traced: set[str]
) -> list[Finding]:
    findings: list[Finding] = []

    def visit_expr_for_casts(expr: ast.AST, traced: set[str]):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in _CAST_BUILTINS
                and node.args
            ):
                hit = astutil.dynamic_names(node.args[0]) & traced
                if hit:
                    findings.append(
                        ctx.finding(
                            "tracer-host-cast",
                            node,
                            f"{node.func.id}() on traced value "
                            f"{'/'.join(sorted(hit))!r} inside jitted "
                            f"function {fn.name!r}",
                        )
                    )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _CAST_METHODS
                and not node.args
            ):
                hit = astutil.dynamic_names(node.func.value) & traced
                if hit:
                    findings.append(
                        ctx.finding(
                            "tracer-host-cast",
                            node,
                            f".{node.func.attr}() on traced value "
                            f"{'/'.join(sorted(hit))!r} inside jitted "
                            f"function {fn.name!r}",
                        )
                    )

    def visit_stmts(body: list[ast.stmt], traced: set[str]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested scopes get their own decoration check
            if isinstance(stmt, (ast.If, ast.While)):
                hit = astutil.dynamic_names(stmt.test) & traced
                if hit:
                    kw = "while" if isinstance(stmt, ast.While) else "if"
                    findings.append(
                        ctx.finding(
                            "tracer-python-branch",
                            stmt,
                            f"Python `{kw}` on traced value "
                            f"{'/'.join(sorted(hit))!r} inside jitted "
                            f"function {fn.name!r}; use lax.cond/"
                            f"lax.while_loop or jnp.where",
                        )
                    )
                visit_expr_for_casts(stmt.test, traced)
                visit_stmts(stmt.body, set(traced))
                visit_stmts(stmt.orelse, set(traced))
                continue
            if isinstance(stmt, ast.Assert):
                hit = astutil.dynamic_names(stmt.test) & traced
                if hit:
                    findings.append(
                        ctx.finding(
                            "tracer-python-branch",
                            stmt,
                            f"`assert` on traced value "
                            f"{'/'.join(sorted(hit))!r} inside jitted "
                            f"function {fn.name!r}; use checkify or assert "
                            f"on static shape/dtype only",
                        )
                    )
                visit_expr_for_casts(stmt.test, traced)
                continue
            if isinstance(stmt, ast.Assign):
                visit_expr_for_casts(stmt.value, traced)
                tainted = bool(astutil.dynamic_names(stmt.value) & traced)
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        if tainted:
                            traced.add(target.id)
                        else:
                            traced.discard(target.id)
                continue
            if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    visit_expr_for_casts(stmt.value, traced)
                    if isinstance(stmt.target, ast.Name) and (
                        astutil.dynamic_names(stmt.value) & traced
                    ):
                        traced.add(stmt.target.id)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                visit_expr_for_casts(stmt.iter, traced)
                inner = set(traced)
                if astutil.dynamic_names(stmt.iter) & traced and isinstance(
                    stmt.target, ast.Name
                ):
                    inner.add(stmt.target.id)
                visit_stmts(stmt.body, inner)
                visit_stmts(stmt.orelse, set(traced))
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                visit_stmts(stmt.body, traced)
                continue
            if isinstance(stmt, ast.Try):
                visit_stmts(stmt.body, set(traced))
                for handler in stmt.handlers:
                    visit_stmts(handler.body, set(traced))
                visit_stmts(stmt.orelse, set(traced))
                visit_stmts(stmt.finalbody, set(traced))
                continue
            # leaf statements: scan any embedded expressions for casts
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    visit_expr_for_casts(child, traced)

    visit_stmts(fn.body, set(traced))
    return findings


@register_checker
def check_tracer_safety(ctx: FileContext):
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info = astutil.jit_decorator_info(node)
        if info is None:
            continue
        traced = astutil.traced_param_names(node, info)
        if traced:
            findings.extend(_check_jitted_function(ctx, node, traced))
    return findings
