"""Stream-path rules.

``stream-unbounded-drain``: an event-store read on the stream path
without a ``limit=`` bound. The speed layer tails the event store
continuously; after downtime the backlog can be the WHOLE store, so an
unbounded ``find``/``find_after`` materializes millions of events in one
list and OOMs the host exactly when it is trying to catch up. Every read
on the stream path must carry an explicit bound (the tailer's
``batch_limit`` is the backpressure unit).

Heuristic scope: files matching ``LintConfig.stream_globs`` (the
``stream/`` package by default). To avoid flagging ``str.find`` and
other unrelated ``.find`` methods, ``find`` calls are only flagged when
the receiver looks like an event DAO (name ends with ``events`` /
``levents`` / ``pevents``) or the call carries an event-find keyword
(``app_id``/``channel_id``/``event_names``/...); ``find_after`` is
unambiguous and always checked.
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    matches_any_glob,
    register_checker,
    register_rule,
)

register_rule(
    "stream-unbounded-drain",
    "stream",
    Severity.ERROR,
    "event-store read on the stream path without a limit= bound; an "
    "unbounded drain after downtime can materialize the whole store "
    "and OOM the host",
)

_FIND_KWARGS = frozenset(
    {
        "app_id",
        "channel_id",
        "start_time",
        "until_time",
        "entity_type",
        "entity_id",
        "event_names",
        "target_entity_type",
        "target_entity_id",
        "cursor",
    }
)

_DAO_RECEIVER_SUFFIXES = ("events", "levents", "pevents", "tailer")


def _receiver_name(func: ast.Attribute) -> str:
    node = func.value
    if isinstance(node, ast.Attribute):
        return node.attr.lower()
    if isinstance(node, ast.Name):
        return node.id.lower()
    return ""


def _has_bound(call: ast.Call, positional_limit_at: int) -> bool:
    for kw in call.keywords:
        if kw.arg == "limit":
            return not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            )
        if kw.arg is None:  # **kwargs may carry a limit; don't guess
            return True
    return len(call.args) > positional_limit_at


@register_checker
def check_unbounded_drain(ctx: FileContext):
    path = ctx.path or ctx.display_path
    if not matches_any_glob(path, ctx.config.stream_globs):
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        name = node.func.attr
        if name == "find_after":
            # positional layout: (app_id, channel_id, cursor, limit)
            if not _has_bound(node, positional_limit_at=3):
                findings.append(
                    ctx.finding(
                        "stream-unbounded-drain",
                        node,
                        "find_after without limit=; bound the drain "
                        "(the tailer's batch_limit is the backpressure unit)",
                    )
                )
        elif name == "find":
            receiver = _receiver_name(node.func)
            dao_like = receiver.endswith(_DAO_RECEIVER_SUFFIXES)
            kw_names = {kw.arg for kw in node.keywords if kw.arg}
            if not dao_like and not (kw_names & _FIND_KWARGS):
                continue
            if "limit" not in kw_names:
                findings.append(
                    ctx.finding(
                        "stream-unbounded-drain",
                        node,
                        "event-store find without limit= on the stream "
                        "path; an unbounded read can OOM a catch-up drain",
                    )
                )
    return findings
