"""Fleet attribution rule: no unattributed proxies or state transitions.

The fleet gateway/supervisor are the observability plane's *last*
blind spot: an outbound replica call that bypasses the span helpers is a
hop ``/traces/recent`` can never assemble into a waterfall, and a health
or lifecycle transition (eject/readmit/park/restart) that only hits a
bare logger is evidence the incident flight recorder never sees. The
``fleet-unattributed-proxy`` rule holds both to the telemetry funnel:

- an aiohttp client call (``.request(...)``/``.get(...)``/``.post(...)``
  on a session-ish receiver) must live in a function that also records a
  span (``Tracer.span``/``record_span``), routes through a ``_note_*``
  telemetry helper, or fires the incident recorder — otherwise the
  forward is invisible to the trace plane;
- an assignment to replica/worker state attributes (``healthy``,
  ``parked``) must live in a function that attributes the transition the
  same way (span helper, ``_note_*``, or a metric ``.inc(...)``) —
  ``__init__`` construction is exempt (initial state is not a
  transition).

The telemetry plane's own fetches (metric federation, trace fan-in,
health probes) are the sanctioned exceptions — suppressed inline with
reasons at the three call sites, because tracing the instrument's own
traffic would recurse it into its own data.
"""

from __future__ import annotations

import ast

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.core import (
    FileContext,
    Finding,
    Severity,
    matches_any_glob,
    register_checker,
    register_rule,
)

register_rule(
    "fleet-unattributed-proxy",
    "fleet",
    Severity.ERROR,
    "outbound replica call or replica state transition in a fleet module "
    "without span/telemetry attribution; route it through the tracer "
    "(span/record_span), a _note_* helper, or the incident recorder so "
    "the gateway hop and the eject/park timeline stay observable",
)

# HTTP verb methods that make an outbound call when invoked on a client
# session (aiohttp.ClientSession surface)
_HTTP_VERBS = frozenset({"request", "get", "post", "put", "delete", "head"})

# receiver spellings that identify an HTTP client session in these
# modules: self._http()... , session.... , self._session....
_SESSION_MARKERS = ("_http", "session")

# calls that count as telemetry attribution inside the same function
_SPAN_HELPERS = frozenset({"span", "record_span"})
# replica/worker state attributes whose assignment IS a fleet transition:
# health (eject/readmit), park (crash-loop budget), and retire (scale-in
# drain) all change what the routable set means
_TRANSITION_ATTRS = frozenset({"healthy", "parked", "retiring"})


def _is_session_receiver(node: ast.AST) -> bool:
    """True when the attribute chain under an HTTP-verb call smells like
    a client session (``self._http()``, ``self._session``, ``session``)."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name and any(marker in name for marker in _SESSION_MARKERS):
            return True
    return False


def _attributes_telemetry(fn: ast.AST) -> bool:
    """Does this function route through the telemetry funnel in its OWN
    body? Span helpers, ``_note_*`` helpers, metric ``.inc``, or an
    incident ``trigger`` count — but attribution inside a *nested*
    function def does not vouch for the enclosing one (each function is
    judged alone, symmetrically with how violations are scanned)."""
    for node in _own_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        attr = astutil.last_component(node.func)
        if attr is None:
            continue
        if (
            attr in _SPAN_HELPERS
            or attr.startswith("_note_")
            or attr == "inc"
            or attr == "trigger"
        ):
            return True
    return False


def _function_nodes(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _own_nodes(fn: ast.AST):
    """Walk a function's body WITHOUT descending into nested function
    defs (a nested helper is attributed — or not — on its own)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@register_checker
def check_fleet_attribution(ctx: FileContext):
    cfg = ctx.config
    if not matches_any_glob(ctx.path or ctx.display_path, cfg.fleet_globs):
        return []
    findings: list[Finding] = []
    for fn in _function_nodes(ctx.tree):
        if fn.name == "__init__":
            continue  # constructing initial state is not a transition
        attributed = _attributes_telemetry(fn)
        if attributed:
            continue
        for node in _own_nodes(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _HTTP_VERBS
                and _is_session_receiver(node.func.value)
            ):
                findings.append(
                    ctx.finding(
                        "fleet-unattributed-proxy",
                        node,
                        f"outbound .{node.func.attr}() in {fn.name}() has no "
                        "span/telemetry attribution; this hop is invisible "
                        "to /traces/recent — wrap it in a gateway.proxy "
                        "span or a _note_* helper",
                    )
                )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    if (
                        isinstance(target, ast.Attribute)
                        and target.attr in _TRANSITION_ATTRS
                    ):
                        findings.append(
                            ctx.finding(
                                "fleet-unattributed-proxy",
                                node,
                                f"state transition .{target.attr} = ... in "
                                f"{fn.name}() has no telemetry attribution; "
                                "eject/readmit/park must route through a "
                                "_note_* helper, a span, or a counter so "
                                "incident bundles can replay the timeline",
                            )
                        )
    return findings
