"""The durable trial ledger: append-only JSONL of finished grid cells.

One line per *finished* cell (scored or failed-with-error), flushed and
fsynced before the append returns — a SIGKILL between cells loses nothing,
a SIGKILL mid-append leaves at most one torn tail line, which the loader
skips (the same crash-safe resume contract as the telemetry ring's segment
files). Resume is a pure set-difference: cells whose content-addressed id
already has a ledger line are never retrained.

The completed ledger's sha256 rides the winner's registry manifest as the
grid evidence's integrity anchor: the scores table in the manifest can be
re-derived from (and audited against) the exact ledger that produced it.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any

logger = logging.getLogger(__name__)


class TrialLedger:
    """Append-only JSONL cell records under one grid workdir."""

    def __init__(self, path: str):
        self.path = path
        self._fh = None

    # ------------------------------------------------------------- read
    def load(self) -> dict[str, dict[str, Any]]:
        """Finished cells by cell id. Torn tail lines (a crash mid-append)
        are skipped with a warning; a torn line means the cell never
        finished, so skipping it is exactly the resume semantics."""
        records: dict[str, dict[str, Any]] = {}
        if not os.path.exists(self.path):
            return records
        with open(self.path, encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    cell_id = rec["cellId"]
                except (ValueError, KeyError, TypeError):
                    logger.warning(
                        "ledger %s: skipping torn/malformed line %d",
                        self.path,
                        lineno,
                    )
                    continue
                records[cell_id] = rec
        return records

    # ------------------------------------------------------------ write
    def append(self, record: dict[str, Any]) -> None:
        """Durably append one finished cell (single writer: the grid
        runner parent). flush + fsync before returning — the record
        either survives a kill or was never promised."""
        if "cellId" not in record:
            raise ValueError("ledger records need a cellId")
        if self._fh is None:
            directory = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(directory, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        self._fh.write(json.dumps(record, sort_keys=True) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "TrialLedger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --------------------------------------------------------- evidence
    def sha256(self) -> str:
        """Content hash of the ledger file (empty-file hash when absent);
        computed AFTER close/flush — the evidence anchor in the winner's
        manifest."""
        digest = hashlib.sha256()
        if os.path.exists(self.path):
            with open(self.path, "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 16), b""):
                    digest.update(chunk)
        return digest.hexdigest()
