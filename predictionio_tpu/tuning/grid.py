"""Grid construction: EngineParamsGenerator × k-fold splits → content-
addressed cells.

A *cell* is one (engine-params, fold) pair — the unit of work the scheduler
trains and scores independently, and the unit of resume in the trial
ledger. Cell ids are content-addressed: sha256 over (canonical params JSON,
fold index, fold count, data-span identity), so re-running the same grid
over the same data always names the same cells (the ledger can vouch for
them across process lifetimes) while any change to params, fold layout or
data span re-keys the affected cells instead of silently reusing stale
scores.

Fold sources:

- **Data-source parity** (default): the engine's own ``read_eval`` decides
  the folds — every template already parameterizes k there (e.g. the
  recommendation template's ``EvalParams.k_fold``).
- **In-memory records**: :func:`~predictionio_tpu.e2.cross_validation.
  k_fold_split` over a record list (reference ``CommonHelperFunctions.
  splitData`` parity). ``k > len(data)`` raises there; grid callers clamp
  first via :func:`clamp_folds` (empty test folds score as degenerate
  0/NaN cells — the failure mode the guard exists for).
- **Event store**: :class:`EventStoreSplitter` folds *users* by sticky hash
  (:func:`~predictionio_tpu.registry.router.sticky_bucket` — the same
  fleet-stable assignment the canary router uses) over the PR-5
  ``find_after`` ordering, so held-out queries/actuals stream off bounded
  pages without materializing the store: only the held-out fold's
  user→items map ever lives on the host (~1/k of users).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
from typing import Any, Callable, Iterator, Sequence

from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.registry.router import sticky_bucket

logger = logging.getLogger(__name__)

DEFAULT_FOLD_SALT = "pio-eval"


def clamp_folds(k: int, n_records: int, what: str = "records") -> int:
    """Clamp a requested fold count to the data size, warning when it
    moves — the grid-side companion of ``e2.k_fold_split``'s hard error:
    a CLI ``--folds 10`` over a 6-user corpus should degrade loudly to
    6 folds, not crash or (worse) score empty test folds as 0/NaN."""
    if k <= 0:
        raise ValueError(f"fold count must be positive, got {k}")
    if n_records <= 0:
        raise ValueError(f"cannot fold zero {what}")
    if k > n_records:
        logger.warning(
            "clamping k=%d folds to %d (only %d %s; empty test folds "
            "would score as degenerate cells)",
            k,
            n_records,
            n_records,
            what,
        )
        return n_records
    return k


def _canonical(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"), default=str)


def params_json_of(ep: EngineParams) -> dict[str, str]:
    """The canonical flat params JSON (the same shape the registry's
    ``params_hash_of`` consumes for manifests — one hash vocabulary)."""
    return Engine.engine_params_to_json(ep)


def cell_id_of(
    ep: EngineParams, fold: int, n_folds: int, data_span: dict[str, Any] | None
) -> str:
    """Content-addressed cell id: params × fold × data span.

    The flat params JSON carries algorithm names but NOT the other three
    component names — two params sets differing only in, say, the serving
    component would otherwise collide to one id and silently share ledger
    records (one of them scored on the other's cells). The component
    names are part of the identity."""
    payload = _canonical(
        {
            "components": {
                "dataSource": ep.data_source[0],
                "preparator": ep.preparator[0],
                "serving": ep.serving[0],
            },
            "params": params_json_of(ep),
            "fold": fold,
            "folds": n_folds,
            "dataSpan": data_span or {},
        }
    )
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class CellKey:
    """One grid cell's identity."""

    cell_id: str
    params_index: int
    fold: int


@dataclasses.dataclass
class GridSpec:
    """The whole search: candidate params × folds × data identity.

    ``folds`` is the fold count the cells are enumerated against; ``None``
    means "discover from the data source's ``read_eval``" (the runner
    probes once). ``data_span`` is any JSON-able identity of the data the
    folds are cut from (app name, event span, snapshot id) — it only
    feeds the cell ids, so two grids over different spans never share
    ledger entries.
    """

    params_list: list[EngineParams]
    folds: int | None = None
    data_span: dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if not self.params_list:
            raise ValueError("grid needs at least one EngineParams")
        if self.folds is not None and self.folds <= 0:
            raise ValueError(f"folds must be positive, got {self.folds}")


def build_cells(spec: GridSpec, n_folds: int) -> list[CellKey]:
    """Enumerate the grid's cells, params-major (fold-minor) so cells that
    share an algorithm-params prefix run adjacently — the order the
    worker-side model cache is bounded around (cells.py clears the model
    cache between params groups)."""
    cells: list[CellKey] = []
    for pi, ep in enumerate(spec.params_list):
        for fold in range(n_folds):
            cells.append(
                CellKey(cell_id_of(ep, fold, n_folds, spec.data_span), pi, fold)
            )
    return cells


# ---------------------------------------------------------------------------
# event-store splitter
# ---------------------------------------------------------------------------


class EventStoreSplitter:
    """Fold users by sticky hash over the event store's ``find_after``
    ordering.

    Assignment is ``int(sticky_bucket(user, salt) * k)`` — deterministic
    across processes and restarts (sha256, not ``hash()``), so a resumed
    grid and every pool worker agree on fold membership without any
    shared state. Training-side consumers get a user *predicate*
    (``keep_for_training(fold)``) to filter whatever representation they
    read; held-out (query, actual) pairs stream off bounded
    ``find_after`` pages — the only host-side materialization is the
    held-out fold's user→items map (~1/k of users).
    """

    def __init__(
        self,
        levents: Any,
        app_id: int,
        k: int,
        channel_id: int | None = None,
        *,
        num: int = 10,
        entity_type: str = "user",
        event_names: Sequence[str] | None = None,
        salt: str = DEFAULT_FOLD_SALT,
        page: int = 2048,
    ):
        if k <= 0:
            raise ValueError(f"fold count must be positive, got {k}")
        self.levents = levents
        self.app_id = app_id
        self.channel_id = channel_id
        self.k = k
        self.num = num
        self.entity_type = entity_type
        self.event_names = frozenset(event_names) if event_names else None
        self.salt = salt
        self.page = page

    def fold_of(self, user_id: str) -> int:
        return int(sticky_bucket(str(user_id), self.salt) * self.k)

    def keep_for_training(self, fold: int) -> Callable[[str], bool]:
        """Predicate over user ids: True when the user trains in ``fold``
        (i.e. is NOT held out there)."""
        return lambda user_id: self.fold_of(user_id) != fold

    def _iter_events(self) -> Iterator[Any]:
        from predictionio_tpu.data.storage.base import event_seq_key

        head = self.levents.seq_head(self.app_id, self.channel_id)
        if head is None:
            return
        cursor: tuple[int, str] | None = None
        while True:
            events = self.levents.find_after(
                self.app_id,
                channel_id=self.channel_id,
                cursor=cursor,
                limit=self.page,
            )
            if not events:
                return
            cursor = event_seq_key(events[-1])
            for e in events:
                if event_seq_key(e) > head:
                    # bound at the head as of iteration start: a grid run
                    # next to a live ingest means "users known when the
                    # split was cut", not a moving target
                    return
                yield e

    def iter_ordered(self) -> Iterator[Any]:
        """Public ordered pass over the split's event window: the same
        head-bounded ``find_after`` pager the fold views use, exposed for
        sequence-aware consumers (the sequential engine's eval reader
        needs ORDERED per-user sessions, which the set-valued
        :meth:`iter_heldout` deliberately discards)."""
        return self._iter_events()

    def iter_heldout(
        self, fold: int
    ) -> Iterator[tuple[dict[str, Any], set[str]]]:
        """Stream ``({"user", "num"}, actual_item_set)`` pairs for the
        held-out users of ``fold``. Pages are bounded; the accumulated
        state is the held-out fold's user→items map only."""
        if not 0 <= fold < self.k:
            raise ValueError(f"fold {fold} out of range [0, {self.k})")
        actuals: dict[str, set[str]] = {}
        for e in self._iter_events():
            if e.entity_type != self.entity_type or not e.entity_id:
                continue
            if self.event_names is not None and e.event not in self.event_names:
                continue
            if self.fold_of(e.entity_id) != fold:
                continue
            items = actuals.setdefault(e.entity_id, set())
            if e.target_entity_id:
                items.add(str(e.target_entity_id))
        for user_id in sorted(actuals):
            yield {"user": user_id, "num": self.num}, actuals[user_id]

    def heldout_fold(
        self, fold: int
    ) -> tuple[list[dict[str, Any]], list[set[str]]]:
        """Materialized convenience view of :meth:`iter_heldout`."""
        queries: list[dict[str, Any]] = []
        actual_sets: list[set[str]] = []
        for q, a in self.iter_heldout(fold):
            queries.append(q)
            actual_sets.append(a)
        return queries, actual_sets

    def fold_sizes(self) -> list[int]:
        """Distinct held-out users per fold (one streaming pass; only the
        dedup id set on the host — the ``--from-events`` idiom)."""
        seen: set[str] = set()
        sizes = [0] * self.k
        for e in self._iter_events():
            if e.entity_type != self.entity_type or not e.entity_id:
                continue
            if self.event_names is not None and e.event not in self.event_names:
                continue
            if e.entity_id in seen:
                continue
            seen.add(e.entity_id)
            sizes[self.fold_of(e.entity_id)] += 1
        return sizes
