"""The grid runner: schedule cells over workers, persist the ledger,
pick the winner, publish it through the registry.

Flow (docs/evaluation.md)::

    GridSpec ──build_cells──▶ cells ──minus ledger──▶ pending
        pending ──process pool (or in-process)──▶ CellScorer.score_cell
            each finished cell ──append──▶ ledger.jsonl   (fsync'd)
    all cells ──aggregate per params──▶ winner
        winner ──full-data refit (run_train)──▶ registry publish
            + attach_eval_evidence (scores table, folds, metric, ledger sha)
            + stage as CANDIDATE ──▶ the PR-4 bake gates promote or reject

Resume: cells are content-addressed (params × fold × data span), finished
cells live in the JSONL ledger; a killed run restarted with ``resume=True``
retrains exactly the cells with no ledger line. The scheduler is the only
ledger writer — workers return records, the parent appends.

Parallelism: a ``spawn`` process pool (CPU sandbox). The scheduler is
deliberately indifferent to *where* a cell runs — a mesh-aware dispatcher
(ROADMAP item 1: cells as per-device programs) replaces the pool behind
the same submit/collect seam.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import math
import os
import time
from concurrent.futures import FIRST_COMPLETED, ProcessPoolExecutor, wait
from typing import Any, Sequence

from predictionio_tpu.controller.engine import EngineParams
from predictionio_tpu.eval.evaluator import MetricEvaluatorResult, MetricScores
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.tuning.cells import (
    DEFAULT_CELL_BATCH,
    CellScorer,
    GridJob,
    init_worker,
    resolve_evaluation,
    run_cell,
)
from predictionio_tpu.tuning.grid import CellKey, GridSpec, build_cells
from predictionio_tpu.tuning.ledger import TrialLedger

logger = logging.getLogger(__name__)

UTC = _dt.timezone.utc
LEDGER_NAME = "ledger.jsonl"

# Mirrors fleet.supervisor.REPLICA_CLASS_CPU without importing the fleet
# package (tuning stays import-light); the contract test pins the two
# strings together.
WORKER_CLASS_CPU_FALLBACK = "cpu-fallback"
# a cpu-fallback grid is a background citizen: bounded worker count so a
# wide grid can't starve the serving host of cores
CPU_FALLBACK_MAX_WORKERS = 4


def grid_worker_env(
    worker_class: str, env: dict[str, str] | None = None
) -> dict[str, str]:
    """The env grid workers boot with for a replica class. Requesting the
    cpu-fallback class pins ``JAX_PLATFORMS=cpu`` (setdefault: an explicit
    caller override wins) — the same pin the fleet launcher applies to
    cpu-fallback serving replicas, so a background retune never initializes
    the accelerator runtime out from under the serving path."""
    merged = dict(env or {})
    if worker_class == WORKER_CLASS_CPU_FALLBACK:
        merged.setdefault("JAX_PLATFORMS", "cpu")
    return merged


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------


def register_eval_metrics(registry: MetricsRegistry) -> dict[str, Any]:
    """Get-or-create the ``pio_eval_*`` family (idempotent) — exported
    through the run's status file and any registry a caller shares in."""
    return {
        "cells": registry.counter(
            "pio_eval_cells_total",
            "grid cells finished this run (scored or failed)",
        ),
        "failed": registry.counter(
            "pio_eval_cells_failed_total",
            "grid cells whose train/score raised (recorded in the ledger "
            "as NaN-scored error cells; never retried on resume)",
        ),
        "skipped": registry.counter(
            "pio_eval_cells_skipped_total",
            "cells skipped on resume because the ledger already holds them",
        ),
        "queries": registry.counter(
            "pio_eval_queries_total",
            "held-out queries scored through the mega-batch path",
        ),
        "active": registry.gauge(
            "pio_eval_active", "1 while an evaluation grid run is executing"
        ),
        "workers": registry.gauge(
            "pio_eval_workers", "parallel cell workers of the active run"
        ),
        "best_score": registry.gauge(
            "pio_eval_best_score",
            "best per-params aggregate score seen so far (primary metric)",
        ),
    }


class EvalGridInstruments:
    """Counter bundle for one grid run (own registry by default)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or MetricsRegistry()
        m = register_eval_metrics(self.registry)
        self.cells = m["cells"]
        self.failed = m["failed"]
        self.skipped = m["skipped"]
        self.queries = m["queries"]
        self.active = m["active"]
        self.workers = m["workers"]
        self.best_score = m["best_score"]


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ParamsScore:
    """One engine-params' aggregate over its folds."""

    params_index: int
    score: float  # query-weighted mean over finite fold scores
    fold_scores: list[float]
    other_scores: list[float]
    queries: int
    failed_cells: int


def params_score_of(
    recs: Sequence[dict[str, Any]], params_index: int
) -> ParamsScore:
    """One params' aggregate from its (finished) fold records.

    Fold scores combine by a held-out-query-count-weighted mean. For
    per-query-average metrics where every query counts, that EQUALS the
    pooled calculate over the concatenated folds; for metrics that skip
    queries (Option*/ranking metrics with unratable actuals) or pool
    non-linearly (stdev) it is an approximation — the fold weights are
    the folds' total held-out queries, not the metric's internal counts,
    which a per-fold scalar cannot recover. Exact-pooled scoring remains
    available via the sequential ``MetricEvaluator``. NaN/failed cells
    are excluded from the mean but counted; an all-NaN params aggregates
    to NaN (the evaluator's NaN guard keeps it from winning)."""
    recs = sorted(recs, key=lambda r: r.get("fold", 0))
    fold_scores = [float(r.get("score", float("nan"))) for r in recs]
    weights = [max(1, int(r.get("queries", 0) or 0)) for r in recs]
    finite = [
        (s, w) for s, w in zip(fold_scores, weights) if not math.isnan(s)
    ]
    if finite:
        total_w = sum(w for _, w in finite)
        score = sum(s * w for s, w in finite) / total_w
    else:
        score = float("nan")
    n_other = max((len(r.get("otherScores", [])) for r in recs), default=0)
    other: list[float] = []
    for j in range(n_other):
        vals = [
            (float(r["otherScores"][j]), w)
            for r, w in zip(recs, weights)
            if len(r.get("otherScores", [])) > j
            and not math.isnan(float(r["otherScores"][j]))
        ]
        other.append(
            sum(s * w for s, w in vals) / sum(w for _, w in vals)
            if vals
            else float("nan")
        )
    return ParamsScore(
        params_index=params_index,
        score=score,
        fold_scores=fold_scores,
        other_scores=other,
        queries=sum(int(r.get("queries", 0) or 0) for r in recs),
        failed_cells=sum(1 for r in recs if r.get("error")),
    )


def aggregate_params(
    records: dict[str, dict[str, Any]],
    cells: Sequence[CellKey],
    n_params: int,
) -> list[ParamsScore]:
    """Fold cell records up to per-params scores (see
    :func:`params_score_of` for the weighting semantics)."""
    by_params: dict[int, list[dict[str, Any]]] = {i: [] for i in range(n_params)}
    for key in cells:
        rec = records.get(key.cell_id)
        if rec is not None:
            by_params[key.params_index].append(rec)
    return [params_score_of(by_params[pi], pi) for pi in range(n_params)]


def pick_best(scores: list[ParamsScore], metric) -> int:
    """Best params index under the metric's ordering. NaN never wins;
    ties keep the FIRST-seen index (strict compare > 0 to displace), so
    the winner is stable across runs and resumes."""
    best = 0
    for i in range(1, len(scores)):
        best_nan = math.isnan(scores[best].score)
        cur = scores[i].score
        if math.isnan(cur):
            continue
        if best_nan or metric.compare(cur, scores[best].score) > 0:
            best = i
    return best


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GridReport:
    """One grid run's evidence — the JSON ``pio eval --out`` writes and
    the programmatic return value."""

    metric: str = ""
    other_metrics: list[str] = dataclasses.field(default_factory=list)
    folds: int = 0
    cells_total: int = 0
    cells_run: int = 0
    cells_skipped: int = 0
    cells_failed: int = 0
    best_params_index: int = 0
    best_score: float = float("nan")
    scores: list[dict[str, Any]] = dataclasses.field(default_factory=list)
    ledger_path: str = ""
    ledger_sha256: str = ""
    wall_s: float = 0.0
    cells_per_hour: float = 0.0
    workers: int = 0
    published_version: str = ""
    engine_id: str = ""
    evaluator_result: MetricEvaluatorResult | None = None

    def to_json_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d.pop("evaluator_result", None)
        return d

    def one_liner(self) -> str:
        pub = (
            f", winner staged as {self.published_version}"
            if self.published_version
            else ""
        )
        return (
            f"[{self.metric}] best: {self.best_score:.6f} "
            f"(params set {self.best_params_index} of {len(self.scores)}; "
            f"{self.cells_total} cells = {len(self.scores)} params x "
            f"{self.folds} folds, {self.cells_skipped} resumed, "
            f"{self.cells_failed} failed{pub})"
        )


def grid_evidence(report: GridReport, records: dict[str, dict[str, Any]]) -> dict:
    """The eval-evidence block the winner's manifest carries
    (docs/model_registry.md): enough to audit the search without the
    workdir — scores table, fold layout, metric, and the ledger's
    content hash as the integrity anchor."""
    return {
        "metric": report.metric,
        "otherMetrics": report.other_metrics,
        "folds": report.folds,
        "cellsTotal": report.cells_total,
        "cellsFailed": report.cells_failed,
        "bestParamsIndex": report.best_params_index,
        "bestScore": report.best_score,
        "scoresTable": report.scores,
        "ledgerSha256": report.ledger_sha256,
        "gridWallS": report.wall_s,
        "workers": report.workers,
        "cells": [
            {
                "cellId": r["cellId"],
                "paramsIndex": r.get("paramsIndex"),
                "fold": r.get("fold"),
                "score": r.get("score"),
                "queries": r.get("queries"),
                "wallS": r.get("wallS"),
                **({"error": r["error"]} if r.get("error") else {}),
            }
            for r in sorted(
                records.values(),
                key=lambda r: (r.get("paramsIndex", 0), r.get("fold", 0)),
            )
        ],
        "evaluatedAt": _dt.datetime.now(tz=UTC).isoformat(),
    }


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------


def run_grid(
    source: Any,
    *,
    workdir: str,
    workers: int = 0,
    folds: int | None = None,
    resume: bool = False,
    batch_size: int = DEFAULT_CELL_BATCH,
    data_span: dict[str, Any] | None = None,
    publish: bool = False,
    registry_dir: str | None = None,
    engine_manifest: Any = None,
    storage: Any = None,
    stage_mode: str = "canary",
    stage_fraction: float = 0.1,
    keep_versions: int = 5,
    status_path: str | None = None,
    instruments: EvalGridInstruments | None = None,
    cwd: str = "",
    env: dict[str, str] | None = None,
    nice: int = 0,
    worker_class: str = "",
    ctx: Any = None,
    evaluation: Any = None,
    on_validated: Any = None,
) -> GridReport:
    """Run (or resume) one evaluation grid end to end.

    ``source`` is a dotted ``module.attr`` path to an Evaluation (the
    ``pio eval`` contract), a picklable zero-arg factory, or — with
    ``workers=0`` only — a live Evaluation instance (process workers must
    rebuild it by name). A caller that already resolved the source may
    pass the instance via ``evaluation`` to skip re-construction.
    ``publish=True`` refits the winning params on the full training data
    and ships it to the registry as a CANDIDATE carrying the grid
    evidence; it requires ``engine_manifest`` (the engine identity) and
    a resolvable registry dir. ``on_validated`` (zero-arg) fires after
    every argument/ledger validation passed, just before cells start —
    the hook bookkeeping callers use to avoid recording runs that never
    validated.

    ``nice`` > 0 re-nices every pool worker (a background retune must
    lose scheduling contests against serving); ``worker_class`` names the
    fleet replica class the workers should behave as — requesting the
    cpu-fallback class pins workers to ``JAX_PLATFORMS=cpu`` and bounds
    ``workers`` at :data:`CPU_FALLBACK_MAX_WORKERS` so a grid can never
    grab the device out from under the serving path.
    """
    from predictionio_tpu.workflow.batch_predict import StatusFile

    evaluation = evaluation if evaluation is not None else resolve_evaluation(source)
    scorer = CellScorer.from_evaluation(evaluation, ctx=ctx, batch_size=batch_size)
    params_list: list[EngineParams] = scorer.params_list
    metric = scorer.metric
    if workers < 0:
        raise ValueError(f"workers must be >= 0, got {workers}")
    if nice < 0:
        raise ValueError(f"nice must be >= 0 (priority only drops), got {nice}")
    env = grid_worker_env(worker_class, env)
    if (
        worker_class == WORKER_CLASS_CPU_FALLBACK
        and workers > CPU_FALLBACK_MAX_WORKERS
    ):
        logger.info(
            "cpu-fallback grid: clamping workers %d -> %d",
            workers,
            CPU_FALLBACK_MAX_WORKERS,
        )
        workers = CPU_FALLBACK_MAX_WORKERS
    if workers > 0 and not (
        isinstance(source, str) or (callable(source) and not hasattr(source, "run"))
    ):
        raise ValueError(
            "process workers rebuild the evaluation by name: pass a dotted "
            "path or a picklable factory as source (got a live instance); "
            "use workers=0 for in-process scoring"
        )
    if publish:
        if engine_manifest is None:
            raise ValueError(
                "publish needs the engine identity (engine_manifest) — "
                "pass --engine-dir to `pio eval`"
            )
        registry_dir = registry_dir or os.environ.get("PIO_REGISTRY_DIR")
        if not registry_dir:
            raise ValueError(
                "publish needs a registry dir (--registry-dir or "
                "$PIO_REGISTRY_DIR)"
            )

    # fold count: explicit, or probed ONCE from the data source (the
    # probe's read stays warm in the parent's cache and is reused when
    # workers=0)
    n_folds = folds if folds is not None else scorer.n_folds()
    if n_folds <= 0:
        raise ValueError("data source yielded zero eval folds")
    spec = GridSpec(params_list, folds=n_folds, data_span=data_span or {})
    cells = build_cells(spec, n_folds)

    os.makedirs(workdir, exist_ok=True)
    ledger = TrialLedger(os.path.join(workdir, LEDGER_NAME))
    if os.path.exists(ledger.path) and not resume:
        raise ValueError(
            f"workdir already holds a trial ledger ({ledger.path}); pass "
            "resume=True (--resume) to continue it or use a fresh workdir"
        )
    finished = ledger.load() if resume else {}
    known = {c.cell_id for c in cells}
    stale = set(finished) - known
    if stale:
        # content addressing at work: a ledger from a different grid
        # (other params/folds/span) can share the workdir without being
        # trusted — its cells simply don't match
        logger.warning(
            "ledger holds %d cell(s) not in this grid (different "
            "params/folds/data span); ignoring them",
            len(stale),
        )
    pending = [c for c in cells if c.cell_id not in finished]
    skipped = len(cells) - len(pending)

    if on_validated is not None:
        on_validated()
    instruments = instruments or EvalGridInstruments()
    instruments.skipped.inc(skipped)
    instruments.workers.set(float(workers))
    status = StatusFile(status_path) if status_path else None
    records: dict[str, dict[str, Any]] = {
        cid: rec for cid, rec in finished.items() if cid in known
    }
    report = GridReport(
        metric=metric.header(),
        other_metrics=[m.header() for m in scorer.other_metrics],
        folds=n_folds,
        cells_total=len(cells),
        cells_skipped=skipped,
        workers=workers,
        ledger_path=ledger.path,
        engine_id=getattr(engine_manifest, "engine_id", ""),
    )

    # incremental per-params aggregation: a finished cell re-scores ONLY
    # its own params set (O(folds)), and best-so-far is a pick over the
    # cached per-params scores (O(params)) — re-aggregating the whole
    # grid per cell made parent bookkeeping O(cells²)
    recs_by_params: dict[int, list[dict[str, Any]]] = {
        i: [] for i in range(len(params_list))
    }
    for key in cells:
        rec = records.get(key.cell_id)
        if rec is not None:
            recs_by_params[key.params_index].append(rec)
    agg_cache: list[ParamsScore] = [
        params_score_of(recs_by_params[i], i) for i in range(len(params_list))
    ]

    def best_so_far() -> tuple[int, float]:
        bi = pick_best(agg_cache, metric)
        return bi, agg_cache[bi].score

    cell_walls: list[float] = []

    def push_status(state: str, running: int = 0, force: bool = False) -> None:
        if status is None:
            return
        done = len(records)
        eta = (
            round((len(cells) - done) * (sum(cell_walls) / len(cell_walls))
                  / max(1, workers or 1), 1)
            if cell_walls and done < len(cells)
            else 0.0
        )
        bi, bs = best_so_far() if records else (0, float("nan"))
        status.update(
            force=force,
            state=state,
            cellsDone=done,
            cellsTotal=len(cells),
            cellsSkipped=skipped,
            cellsFailed=report.cells_failed,
            running=running,
            workers=workers,
            bestScore=None if math.isnan(bs) else bs,
            bestParams=bi,
            metric=report.metric,
            folds=n_folds,
            etaS=eta,
        )

    def take(rec: dict[str, Any]) -> None:
        records[rec["cellId"]] = rec
        ledger.append(rec)
        pi = int(rec.get("paramsIndex", 0))
        recs_by_params[pi].append(rec)
        agg_cache[pi] = params_score_of(recs_by_params[pi], pi)
        cell_walls.append(float(rec.get("wallS", 0.0)))
        report.cells_run += 1
        instruments.cells.inc()
        instruments.queries.inc(int(rec.get("queries", 0) or 0))
        if rec.get("error"):
            report.cells_failed += 1
            instruments.failed.inc()
            logger.warning(
                "cell %s (params %s, fold %s) failed: %s",
                rec["cellId"],
                rec.get("paramsIndex"),
                rec.get("fold"),
                rec["error"],
            )
        _, bs = best_so_far()
        if not math.isnan(bs):
            instruments.best_score.set(bs)

    t0 = time.perf_counter()
    instruments.active.set(1.0)
    push_status("running", force=True)
    try:
        with ledger:
            if workers == 0:
                for key in pending:
                    take(scorer.score_cell(key))
                    push_status("running")
            else:
                import multiprocessing

                job = GridJob(
                    source=source,
                    cwd=cwd,
                    env=dict(env or {}),
                    batch_size=batch_size,
                    nice=nice,
                )
                # spawn, never fork: workers import jax (and the user's
                # evaluation module); forking a jax-initialized parent is
                # undefined behavior
                mp_ctx = multiprocessing.get_context("spawn")
                with ProcessPoolExecutor(
                    max_workers=workers,
                    mp_context=mp_ctx,
                    initializer=init_worker,
                    initargs=(job,),
                ) as pool:
                    # params-major submission order is preserved by the
                    # pool, so each worker sees params groups mostly
                    # adjacently and its model-cache clearing bounds memory
                    futures = {pool.submit(run_cell, key): key for key in pending}
                    not_done = set(futures)
                    while not_done:
                        done, not_done = wait(
                            not_done, timeout=1.0, return_when=FIRST_COMPLETED
                        )
                        for fut in done:
                            take(fut.result())
                        push_status("running", running=len(not_done))
        report.wall_s = round(time.perf_counter() - t0, 4)
        report.ledger_sha256 = ledger.sha256()

        missing = [c for c in cells if c.cell_id not in records]
        if missing:
            raise RuntimeError(
                f"{len(missing)} cell(s) never produced a record "
                "(scheduler bug or worker pool died)"
            )
        agg = aggregate_params(records, cells, len(params_list))
        best = pick_best(agg, metric)
        report.best_params_index = best
        report.best_score = agg[best].score
        report.scores = [
            {
                "paramsIndex": s.params_index,
                "score": s.score,
                "foldScores": s.fold_scores,
                "otherScores": s.other_scores,
                "queries": s.queries,
                "failedCells": s.failed_cells,
            }
            for s in agg
        ]
        report.cells_per_hour = (
            round(report.cells_run / (report.wall_s / 3600.0), 1)
            if report.wall_s > 0 and report.cells_run
            else 0.0
        )
        report.evaluator_result = MetricEvaluatorResult(
            best_score=report.best_score,
            best_engine_params=params_list[best],
            best_index=best,
            metric_header=report.metric,
            other_metric_headers=report.other_metrics,
            engine_params_scores=[
                MetricScores(params_list[s.params_index], s.score, s.other_scores)
                for s in agg
            ],
        )
        if not math.isnan(report.best_score):
            instruments.best_score.set(report.best_score)
        # reference parity (MetricEvaluator.scala outputPath): an
        # Evaluation carrying output_path still gets its best-params JSON
        # — downstream scripts consume this file
        output_path = getattr(evaluation, "output_path", None)
        if output_path:
            from predictionio_tpu.eval.evaluator import _params_json

            os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
            with open(output_path, "w") as fh:
                json.dump(
                    {
                        "score": report.best_score,
                        "engineParams": _params_json(params_list[best]),
                    },
                    fh,
                    indent=2,
                    sort_keys=True,
                )
            logger.info("best engine params written to %s", output_path)

        if publish:
            if math.isnan(report.best_score):
                logger.warning(
                    "every params aggregated to NaN — refusing to publish "
                    "a degenerate winner"
                )
            else:
                report.published_version = _publish_winner(
                    evaluation,
                    params_list[best],
                    engine_manifest,
                    registry_dir,
                    grid_evidence(report, records),
                    storage=storage,
                    stage_mode=stage_mode,
                    stage_fraction=stage_fraction,
                    keep_versions=keep_versions,
                )
        push_status("done", force=True)
        return report
    except BaseException:
        report.wall_s = round(time.perf_counter() - t0, 4)
        push_status("failed", force=True)
        raise
    finally:
        instruments.active.set(0.0)
        instruments.workers.set(0.0)


def _publish_winner(
    evaluation: Any,
    winner: EngineParams,
    engine_manifest: Any,
    registry_dir: str,
    evidence: dict[str, Any],
    *,
    storage: Any = None,
    stage_mode: str = "canary",
    stage_fraction: float = 0.1,
    keep_versions: int = 5,
) -> str:
    """Refit the winning params on the FULL training data and ship it as
    a registry CANDIDATE carrying the grid evidence. The refit goes
    through ``run_train`` — the same metadata-ledger + publish + train-
    profile path every other trained version takes — then the manifest
    gains the evidence block and the version is staged so the PR-4 bake
    gates (or an operator) decide promotion. Hyperparameter search never
    hot-swaps the stable."""
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.registry import ArtifactStore
    from predictionio_tpu.workflow.core_workflow import run_train

    storage = storage or Storage.instance()
    instance_id = run_train(
        evaluation.engine,
        engine_manifest,
        winner,
        storage=storage,
        batch="evalgrid",
        registry_dir=registry_dir,
        keep_versions=keep_versions,
    )
    store = ArtifactStore(registry_dir)
    engine_id = engine_manifest.engine_id
    published = [
        m for m in store.list_versions(engine_id) if m.instance_id == instance_id
    ]
    if not published:
        raise RuntimeError(
            "winner refit trained (instance %s) but never reached the "
            "registry — publish failed, metadata store remains "
            "authoritative" % instance_id
        )
    version = published[-1].version
    store.attach_eval_evidence(engine_id, version, evidence)
    state = store.get_state(engine_id)
    if state.stable and state.stable != version:
        store.stage_candidate(
            engine_id, version, mode=stage_mode, fraction=stage_fraction
        )
        logger.info(
            "grid winner %s staged as %s candidate (fraction %g) — bake "
            "gates decide promotion",
            version,
            stage_mode,
            stage_fraction,
        )
    else:
        # first version of a fresh engine auto-stabilizes on publish;
        # there is nothing to canary against
        logger.info("grid winner %s is the first stable version", version)
    return version
