"""Cell execution: train one (params, fold) cell and score its held-out
queries through the offline mega-batch path.

The scoring path is the point: held-out queries go through
:meth:`Engine.dispatch_batch` in fixed mega-batches (two-slot overlapped —
dispatch batch N, then drain batch N-1 while the device computes N, the
``pio batchpredict`` idiom), which routes every algorithm's pipelined
``predict_batch_dispatch`` into the fused ``ops/topk`` kernels. There is
deliberately **no per-query ``predict`` loop here** — the sequential
``MetricEvaluator`` it replaces paid one device round-trip per held-out
query; the grid pays one per mega-batch. The ``eval-per-query-predict``
lint rule holds that property by static analysis.

Prefix caching: each worker wraps the evaluation's engine in a
:class:`~predictionio_tpu.eval.fast_eval.FastEvalEngine`, so cells sharing
a data_source params prefix read eval folds once per worker, cells sharing
(data_source, preparator) prepare once, and repeated algorithm params
reuse trained models. Between params *groups* (cells run params-major) the
model cache is cleared (``clear_caches(keep_data=True)``) to bound worker
memory — data caches survive, models don't.

Workers are plain processes (CPU sandbox process pool). ``init_worker`` /
``run_cell`` are the pool entry points; a mesh-aware scheduler (ROADMAP
item 1: cells as per-device programs over a jax mesh) plugs in at the same
seam — the cell contract (CellKey in, ledger record out) doesn't change.
"""

from __future__ import annotations

import dataclasses
import importlib
import os
import sys
import time
from typing import Any, Callable, Sequence

from predictionio_tpu.controller.base import BaseAlgorithm, BaseServing, Doer
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.eval.fast_eval import FastEvalEngine, _key
from predictionio_tpu.eval.metric import Metric
from predictionio_tpu.obs import xray
from predictionio_tpu.tuning.grid import CellKey, params_json_of
from predictionio_tpu.workflow.context import WorkflowContext

DEFAULT_CELL_BATCH = 512


class FoldRangeError(ValueError):
    """The cell's fold index exceeds what the data source yields — a
    config error (e.g. ``--folds 5`` against a 3-fold read_eval), not a
    data error: it must FAIL THE RUN rather than be ledgered as a
    durable never-retried failed cell."""


def resolve_evaluation(source: Any) -> Any:
    """An Evaluation from a dotted ``module.attr`` path (instance, class,
    or zero-arg factory — the ``pio eval`` contract) or a callable."""
    if isinstance(source, str):
        module_name, _, attr = source.rpartition(".")
        obj = getattr(importlib.import_module(module_name), attr)
    else:
        obj = source
    if isinstance(obj, type) or (callable(obj) and not hasattr(obj, "run")):
        obj = obj()
    return obj


def caching_engine(engine: Engine) -> FastEvalEngine:
    """Wrap (or pass through) the evaluation's engine as a FastEvalEngine
    so the grid gets the stage-memoization caches."""
    if isinstance(engine, FastEvalEngine):
        return engine
    return FastEvalEngine(
        engine.data_source_classes,
        engine.preparator_classes,
        engine.algorithm_classes,
        engine.serving_classes,
        query_class=engine.query_class,
    )


def dispatch_scores(
    engine: Engine,
    algorithms: Sequence[BaseAlgorithm],
    serving: BaseServing,
    models: Sequence[Any],
    queries: Sequence[Any],
    batch_size: int = DEFAULT_CELL_BATCH,
) -> list[Any]:
    """Score ``queries`` in fixed mega-batches through
    ``Engine.dispatch_batch``, two-slot overlapped: batch N's device work
    is dispatched before batch N-1's finalize fetches — the device never
    waits on host-side decode. Returns served results, query-aligned."""
    served: list[Any] = []
    pending: Callable[[], list[Any]] | None = None
    for start in range(0, len(queries), batch_size):
        chunk = queries[start : start + batch_size]
        fin = engine.dispatch_batch(algorithms, serving, models, chunk)
        if pending is not None:
            served.extend(pending())
        pending = fin
    if pending is not None:
        served.extend(pending())
    return served


@dataclasses.dataclass
class GridJob:
    """Picklable bootstrap for a pool worker: how to rebuild the
    evaluation (dotted path or picklable factory), where user modules
    live, and any env the worker's storage selection needs."""

    source: Any
    cwd: str = ""
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    batch_size: int = DEFAULT_CELL_BATCH
    # worker niceness: a background retune (lifecycle controller) must
    # lose every scheduling contest against serving; 0 = inherit
    nice: int = 0


class CellScorer:
    """Per-worker cell executor: prefix-cached fold data + mega-batch
    scoring. One instance per worker process (or one in-process for
    ``workers=0``)."""

    def __init__(
        self,
        engine: Engine,
        metric: Metric,
        params_list: Sequence[EngineParams],
        other_metrics: Sequence[Metric] = (),
        ctx: WorkflowContext | None = None,
        batch_size: int = DEFAULT_CELL_BATCH,
    ):
        self.engine = caching_engine(engine)
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.params_list = list(params_list)
        self.ctx = ctx or WorkflowContext(mode="evaluation")
        self.batch_size = batch_size
        self._group_key: str | None = None

    @classmethod
    def from_evaluation(
        cls,
        evaluation: Any,
        ctx: WorkflowContext | None = None,
        batch_size: int = DEFAULT_CELL_BATCH,
    ) -> "CellScorer":
        # getattr, not attribute access: Evaluation-shaped objects without
        # the fields (FakeRun) must get the clean ValueError the CLI
        # routes on, never an AttributeError
        if (
            getattr(evaluation, "engine", None) is None
            or getattr(evaluation, "metric", None) is None
        ):
            raise ValueError("evaluation must define engine and metric")
        return cls(
            evaluation.engine,
            evaluation.metric,
            list(evaluation.params_list()),
            other_metrics=list(evaluation.other_metrics or ()),
            ctx=ctx,
            batch_size=batch_size,
        )

    # ----------------------------------------------------------- caching
    def _maybe_new_group(self, ep: EngineParams) -> None:
        """Bound worker memory: entering a new params group (different
        data_source/preparator/algorithm params) drops cached MODELS;
        the data caches survive so later groups still share the
        read/prepare prefix."""
        group = _key(
            ep.data_source[0],
            ep.data_source[1],
            ep.preparator[0],
            ep.preparator[1],
            [(n, p) for n, p in (ep.algorithms or [("", None)])],
        )
        if self._group_key is not None and group != self._group_key:
            self.engine.clear_caches(keep_data=True)
        self._group_key = group

    def n_folds(self, params_index: int = 0) -> int:
        return len(self.engine._eval_folds(self.ctx, self.params_list[params_index]))

    # ----------------------------------------------------------- scoring
    def score_cell(self, key: CellKey) -> dict[str, Any]:
        """Train + score one cell; returns the ledger record. A failing
        cell returns an ``error`` record (the grid survives; the cell is
        NOT retried on resume — its failure is a durable result)."""
        t0 = time.perf_counter()
        ep = self.params_list[key.params_index]
        record: dict[str, Any] = {
            "cellId": key.cell_id,
            "paramsIndex": key.params_index,
            "fold": key.fold,
            "paramsHash": _cell_params_hash(ep),
            "pid": os.getpid(),
        }
        try:
            self._maybe_new_group(ep)
            profile = xray.TrainProfile(trainer=f"evalgrid:{key.cell_id}")
            with xray.use_profile(profile), profile.measure():
                with xray.phase(xray.PHASE_HOST_ETL):
                    folds = self.engine._eval_folds(self.ctx, ep)
                    if key.fold >= len(folds):
                        raise FoldRangeError(
                            f"fold {key.fold} out of range: data source "
                            f"yields {len(folds)} folds (check --folds)"
                        )
                    _td, ei, qa_list = folds[key.fold]
                    # touch the prepared cache before training so the
                    # prepare stage accounts as host_etl, not solve
                    self.engine._prepared(self.ctx, ep)
                algo_list = ep.algorithms or [("", None)]
                with xray.phase(xray.PHASE_SOLVE):
                    models = [
                        self.engine._trained_model(self.ctx, ep, i, key.fold)
                        for i in range(len(algo_list))
                    ]
                algorithms = [
                    Doer.apply(
                        self.engine._pick(
                            self.engine.algorithm_classes, name, "algorithm"
                        ),
                        p,
                    )
                    for name, p in algo_list
                ]
                serving = Doer.apply(
                    self.engine._pick(
                        self.engine.serving_classes, ep.serving[0], "serving"
                    ),
                    ep.serving[1],
                )
                with xray.phase(xray.PHASE_EVAL):
                    queries = [q for q, _ in qa_list]
                    served = dispatch_scores(
                        self.engine,
                        algorithms,
                        serving,
                        models,
                        queries,
                        self.batch_size,
                    )
                profile.add_rows(len(qa_list))
            profile.finish()
            if len(served) != len(qa_list):
                # a silent zip truncation here would score the cell on a
                # prefix and look healthy
                raise RuntimeError(
                    f"dispatch_batch returned {len(served)} results for "
                    f"{len(qa_list)} held-out queries"
                )
            eval_data = [
                (ei, [(q, p, a) for (q, a), p in zip(qa_list, served)])
            ]
            record.update(
                score=self.metric.calculate(eval_data),
                otherScores=[m.calculate(eval_data) for m in self.other_metrics],
                queries=len(qa_list),
                trainProfile=profile.to_json_dict(),
            )
        except FoldRangeError:
            raise  # config error: fail the run, never the ledger
        except Exception as exc:  # noqa: BLE001 - a failed cell is a result
            record.update(
                score=float("nan"),
                otherScores=[],
                queries=0,
                error=f"{type(exc).__name__}: {exc}",
            )
        record["wallS"] = round(time.perf_counter() - t0, 4)
        return record


def _cell_params_hash(ep: EngineParams) -> str:
    from predictionio_tpu.registry.manifest import params_hash_of

    return params_hash_of(params_json_of(ep))


# ---------------------------------------------------------------------------
# process-pool entry points (must be module-level: spawn pickles by name)
# ---------------------------------------------------------------------------

_SCORER: CellScorer | None = None


def init_worker(job: GridJob) -> None:
    """Pool initializer: env first (storage selection must precede any
    Storage.instance()), then the user's cwd on sys.path (evaluations
    live in engine project dirs), then build this worker's scorer."""
    global _SCORER
    if job.nice > 0:
        try:
            os.nice(job.nice)
        except OSError:  # pragma: no cover - privilege-restricted hosts
            pass
    os.environ.update(job.env)
    if job.cwd and job.cwd not in sys.path:
        sys.path.insert(0, job.cwd)
    evaluation = resolve_evaluation(job.source)
    _SCORER = CellScorer.from_evaluation(evaluation, batch_size=job.batch_size)


def run_cell(key: CellKey) -> dict[str, Any]:
    if _SCORER is None:
        raise RuntimeError("worker not initialized (init_worker must run)")
    return _SCORER.score_cell(key)
