"""The evaluation grid: parallel, resumable fold×params hyperparameter
search whose winner ships through the model registry (docs/evaluation.md).

MLlib made CrossValidator-style grid search a first-class pipeline stage of
the Spark substrate this project replaces (PAPERS.md 1505.06807); DrJAX
gives the map-reduce shape for fanning independent fold×params cells over
workers. Here the grid is built from an EngineParamsGenerator × k-fold
splits (:mod:`~predictionio_tpu.tuning.grid`), each cell is trained and
scored through the offline mega-batch path
(:meth:`Engine.dispatch_batch` → the fused ``ops/topk`` kernels,
:mod:`~predictionio_tpu.tuning.cells`), finished cells land in a durable
JSONL trial ledger (:mod:`~predictionio_tpu.tuning.ledger`) so a killed run
resumes retraining zero finished cells, and the winning params' full-data
refit is published to the registry as a CANDIDATE carrying the full grid
evidence — hyperparameter search under the same bake-gate discipline as
every other model change (:mod:`~predictionio_tpu.tuning.runner`).
"""

from predictionio_tpu.tuning.grid import (
    CellKey,
    EventStoreSplitter,
    GridSpec,
    build_cells,
    cell_id_of,
    clamp_folds,
)
from predictionio_tpu.tuning.ledger import TrialLedger
from predictionio_tpu.tuning.metrics import (
    NDCGAtK,
    PrecisionAtK,
    RecallAtK,
)
from predictionio_tpu.tuning.runner import (
    CPU_FALLBACK_MAX_WORKERS,
    WORKER_CLASS_CPU_FALLBACK,
    EvalGridInstruments,
    GridReport,
    grid_worker_env,
    register_eval_metrics,
    run_grid,
)

__all__ = [
    "CPU_FALLBACK_MAX_WORKERS",
    "CellKey",
    "EvalGridInstruments",
    "EventStoreSplitter",
    "GridReport",
    "GridSpec",
    "NDCGAtK",
    "PrecisionAtK",
    "RecallAtK",
    "TrialLedger",
    "WORKER_CLASS_CPU_FALLBACK",
    "build_cells",
    "cell_id_of",
    "clamp_folds",
    "grid_worker_env",
    "register_eval_metrics",
    "run_grid",
]
