"""Batched ranking metrics: precision@k / recall@k / ndcg@k.

These score the *ranked lists* the fused top-k serving path produces (the
``ops/topk`` pack format, decoded by each engine's finalize into
``item_scores``-shaped results) against held-out actuals — the metric
vocabulary a fold×params grid search optimizes. Unlike the per-query
``calculate_score`` metrics in :mod:`predictionio_tpu.eval.metric`, one
``calculate`` call vectorizes the whole evaluation set through numpy: the
hit matrix for every (query, rank) pair is built once and reduced in one
pass — no per-query Python scoring loop on a path that sees one row per
held-out user per cell.

They remain :class:`~predictionio_tpu.eval.metric.Metric` subclasses, so
they drop into ``MetricEvaluator`` and the grid runner interchangeably.
Queries with no actuals are excluded (``OptionAverageMetric`` semantics:
an unratable query must not dilute the mean); an empty evaluation set
scores NaN, which the evaluator's NaN guard keeps out of the best slot.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from predictionio_tpu.eval.metric import EvalDataSet, Metric


def predicted_items(p: Any) -> list[str]:
    """Ranked item ids from a prediction — the decoded pack-format shapes:
    ``item_scores`` tuples (engine dataclasses), ``itemScores`` dicts
    (wire JSON), or a plain id sequence."""
    scores = getattr(p, "item_scores", None)
    if scores is None and isinstance(p, dict):
        scores = p.get("itemScores")
    if scores is None:
        scores = p
    out: list[str] = []
    for s in scores or ():
        item = getattr(s, "item", None)
        if item is None and isinstance(s, dict):
            item = s.get("item")
        out.append(str(s if item is None else item))
    return out


def actual_items(a: Any) -> set[str]:
    """Relevant item ids from an actual: ``ratings`` tuples (the
    recommendation template's ``ActualResult``), dicts, or id iterables."""
    ratings = getattr(a, "ratings", None)
    if ratings is None and isinstance(a, dict):
        ratings = a.get("ratings", a.get("items"))
    if ratings is None:
        ratings = a
    out: set[str] = set()
    for r in ratings or ():
        item = getattr(r, "item", None)
        if item is None and isinstance(r, dict):
            item = r.get("item")
        out.add(str(r if item is None else item))
    return out


class RankingMetric(Metric):
    """Shared batched scaffolding: pool every fold's (q, p, a), build one
    [n_queries, k] boolean hit matrix, reduce in the subclass."""

    def __init__(self, k: int = 10):
        if k <= 0:
            raise ValueError(f"k must be positive, got {k}")
        self.k = k

    def header(self) -> str:
        return f"{type(self).__name__.replace('AtK', '').lower()}@{self.k}"

    def _reduce(self, hits: np.ndarray, n_actuals: np.ndarray) -> float:
        raise NotImplementedError

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        # one flat pass building plain-bool rows, ONE numpy materialization
        # at the end: per-row array allocs dominated this loop at 100k+
        # held-out queries per grid
        pad = [False] * self.k
        hit_rows: list[list[bool]] = []
        n_actuals: list[int] = []
        for _ei, qpas in eval_data_set:
            for _q, p, a in qpas:
                actual = actual_items(a)
                if not actual:
                    continue  # None-actual filtering: unratable query
                ranked = predicted_items(p)[: self.k]
                row = [item in actual for item in ranked]
                if len(row) < self.k:
                    row += pad[len(row) :]
                hit_rows.append(row)
                n_actuals.append(len(actual))
        if not hit_rows:
            return float("nan")
        return float(
            self._reduce(
                np.asarray(hit_rows, dtype=bool),
                np.asarray(n_actuals, dtype=np.float64),
            )
        )


class PrecisionAtK(RankingMetric):
    """Mean fraction of the top-k that is relevant."""

    def _reduce(self, hits: np.ndarray, n_actuals: np.ndarray) -> float:
        return hits.sum(axis=1).mean() / self.k


class RecallAtK(RankingMetric):
    """Mean fraction of each query's relevant set retrieved in the top-k."""

    def _reduce(self, hits: np.ndarray, n_actuals: np.ndarray) -> float:
        return (hits.sum(axis=1) / n_actuals).mean()


class NDCGAtK(RankingMetric):
    """Mean normalized discounted cumulative gain at k (binary gains):
    DCG over the hit matrix with the standard log2 rank discount,
    normalized per query by the ideal DCG of min(|actual|, k) hits."""

    def _reduce(self, hits: np.ndarray, n_actuals: np.ndarray) -> float:
        discounts = 1.0 / np.log2(np.arange(2, self.k + 2, dtype=np.float64))
        dcg = (hits * discounts).sum(axis=1)
        ideal_hits = np.minimum(n_actuals, self.k).astype(np.int64)
        cum_ideal = np.concatenate(([0.0], np.cumsum(discounts)))
        idcg = cum_ideal[ideal_hits]
        return (dcg / np.where(idcg > 0, idcg, 1.0)).mean()


def ranking_eval_set(
    queries: Sequence[Any],
    served: Sequence[Any],
    actuals: Sequence[Any],
    eval_info: Any = None,
) -> EvalDataSet:
    """Zip a scored mega-batch back into the ``Engine.eval`` data-set
    shape the Metric contract consumes (one synthetic fold)."""
    if not (len(queries) == len(served) == len(actuals)):
        raise ValueError(
            f"queries/served/actuals length mismatch: "
            f"{len(queries)}/{len(served)}/{len(actuals)}"
        )
    return [(eval_info, list(zip(queries, served, actuals)))]
