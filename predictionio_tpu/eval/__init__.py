"""Evaluation stack: metrics, evaluator, grid search, fast-eval memoization.

Reference parity: ``core/.../controller/Metric.scala``,
``Evaluation.scala``, ``MetricEvaluator.scala``,
``EngineParamsGenerator.scala``, ``FastEvalEngine.scala``.
"""

from predictionio_tpu.eval.metric import (
    AverageMetric,
    Metric,
    OptionAverageMetric,
    OptionStdevMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
)
from predictionio_tpu.eval.evaluator import (
    Evaluation,
    MetricEvaluator,
    MetricEvaluatorResult,
)
from predictionio_tpu.eval.generator import EngineParamsGenerator, grid_search
from predictionio_tpu.eval.fast_eval import FastEvalEngine

__all__ = [
    "AverageMetric",
    "EngineParamsGenerator",
    "Evaluation",
    "FastEvalEngine",
    "Metric",
    "MetricEvaluator",
    "MetricEvaluatorResult",
    "OptionAverageMetric",
    "OptionStdevMetric",
    "StdevMetric",
    "SumMetric",
    "ZeroMetric",
    "grid_search",
]
