"""Metric base classes.

Reference parity: ``core/.../controller/Metric.scala:39-269`` —
``Metric[EI, Q, P, A, R]`` with a ``compare`` ordering, plus the stock
subclasses ``AverageMetric``, ``OptionAverageMetric`` (None scores excluded),
``StdevMetric``, ``OptionStdevMetric``, ``SumMetric``, ``ZeroMetric``.

``calculate`` receives the evaluation dataset as
``[(EI, [(Q, P, A), ...]), ...]`` — one entry per fold — exactly the shape
``Engine.eval`` produces.
"""

from __future__ import annotations

import math
from typing import Any, Generic, Sequence, TypeVar

EI = TypeVar("EI")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")

EvalDataSet = Sequence[tuple[Any, Sequence[tuple[Any, Any, Any]]]]


class Metric(Generic[EI, Q, P, A]):
    def header(self) -> str:
        return type(self).__name__

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        raise NotImplementedError

    def compare(self, r0: float, r1: float) -> int:
        """Default ordering: bigger is better (ref Metric.scala:56-66)."""
        if r0 == r1:
            return 0
        return 1 if r0 > r1 else -1


class AverageMetric(Metric[EI, Q, P, A]):
    """Mean of per-(q,p,a) scores pooled over all folds."""

    def calculate_score(self, ei: Any, q: Any, p: Any, a: Any) -> float:
        raise NotImplementedError

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        scores = [
            self.calculate_score(ei, q, p, a)
            for ei, qpas in eval_data_set
            for q, p, a in qpas
        ]
        return sum(scores) / len(scores) if scores else float("nan")


class OptionAverageMetric(Metric[EI, Q, P, A]):
    """Mean over scores that are not None (ref OptionAverageMetric)."""

    def calculate_score(self, ei: Any, q: Any, p: Any, a: Any) -> float | None:
        raise NotImplementedError

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        scores = [
            s
            for ei, qpas in eval_data_set
            for q, p, a in qpas
            if (s := self.calculate_score(ei, q, p, a)) is not None
        ]
        return sum(scores) / len(scores) if scores else float("nan")


class StdevMetric(Metric[EI, Q, P, A]):
    """Population standard deviation of scores (ref StdevMetric)."""

    def calculate_score(self, ei: Any, q: Any, p: Any, a: Any) -> float:
        raise NotImplementedError

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        scores = [
            self.calculate_score(ei, q, p, a)
            for ei, qpas in eval_data_set
            for q, p, a in qpas
        ]
        if not scores:
            return float("nan")
        mean = sum(scores) / len(scores)
        return math.sqrt(sum((s - mean) ** 2 for s in scores) / len(scores))


class OptionStdevMetric(Metric[EI, Q, P, A]):
    def calculate_score(self, ei: Any, q: Any, p: Any, a: Any) -> float | None:
        raise NotImplementedError

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        scores = [
            s
            for ei, qpas in eval_data_set
            for q, p, a in qpas
            if (s := self.calculate_score(ei, q, p, a)) is not None
        ]
        if not scores:
            return float("nan")
        mean = sum(scores) / len(scores)
        return math.sqrt(sum((s - mean) ** 2 for s in scores) / len(scores))


class SumMetric(Metric[EI, Q, P, A]):
    def calculate_score(self, ei: Any, q: Any, p: Any, a: Any) -> float:
        raise NotImplementedError

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        return sum(
            self.calculate_score(ei, q, p, a)
            for ei, qpas in eval_data_set
            for q, p, a in qpas
        )


class ZeroMetric(Metric[EI, Q, P, A]):
    """Always 0 — placeholder for secondary metric slots (ref ZeroMetric)."""

    def calculate(self, eval_data_set: EvalDataSet) -> float:
        return 0.0
