"""Evaluation + MetricEvaluator.

Reference parity: ``core/.../controller/Evaluation.scala:34-125`` (binds an
engine with metrics), ``MetricEvaluator.scala:48-263`` (scores every
EngineParams in the candidate list, tracks the best, writes ``best.json``,
renders one-liner / JSON / HTML results).
"""

from __future__ import annotations

import dataclasses
import html as _html
import json
import logging
import os
from typing import Any, Sequence

from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.eval.generator import EngineParamsGenerator
from predictionio_tpu.eval.metric import Metric
from predictionio_tpu.workflow.context import WorkflowContext

logger = logging.getLogger(__name__)


def _params_json(ep: EngineParams) -> dict[str, Any]:
    """Decoded (non-double-encoded) JSON view of EngineParams."""
    flat = Engine.engine_params_to_json(ep)
    return {k: json.loads(v) for k, v in flat.items()}


@dataclasses.dataclass
class MetricScores:
    engine_params: EngineParams
    score: float
    other_scores: list[float]


@dataclasses.dataclass
class MetricEvaluatorResult:
    best_score: float
    best_engine_params: EngineParams
    best_index: int
    metric_header: str
    other_metric_headers: list[str]
    engine_params_scores: list[MetricScores]

    def one_liner(self) -> str:
        return (
            f"[{self.metric_header}] best: {self.best_score:.6f} "
            f"(params set {self.best_index} of {len(self.engine_params_scores)})"
        )

    def to_json_dict(self) -> dict[str, Any]:
        params_json = _params_json
        return {
            "metricHeader": self.metric_header,
            "otherMetricHeaders": self.other_metric_headers,
            "bestScore": self.best_score,
            "bestIndex": self.best_index,
            "bestEngineParams": params_json(self.best_engine_params),
            "engineParamsScores": [
                {
                    "score": s.score,
                    "otherScores": s.other_scores,
                    "engineParams": params_json(s.engine_params),
                }
                for s in self.engine_params_scores
            ],
        }

    def to_html(self) -> str:
        rows = "".join(
            f"<tr><td>{i}</td><td>{s.score:.6f}</td>"
            f"<td>{', '.join(f'{x:.6f}' for x in s.other_scores)}</td>"
            f"<td><pre>{_html.escape(json.dumps(_params_json(s.engine_params), indent=1))}</pre></td></tr>"
            for i, s in enumerate(self.engine_params_scores)
        )
        return (
            f"<h2>{_html.escape(self.metric_header)}</h2>"
            f"<p>Best score: {self.best_score:.6f} (index {self.best_index})</p>"
            f"<table border=1><tr><th>#</th><th>{_html.escape(self.metric_header)}</th>"
            f"<th>{_html.escape(', '.join(self.other_metric_headers))}</th>"
            f"<th>Engine Params</th></tr>{rows}</table>"
        )


class MetricEvaluator:
    """Scores each candidate EngineParams with the primary metric
    (+ optional secondary metrics); optionally writes best.json
    (ref MetricEvaluator.scala ``outputPath``)."""

    def __init__(
        self,
        metric: Metric,
        other_metrics: Sequence[Metric] = (),
        output_path: str | None = None,
    ):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path

    def evaluate_base(
        self,
        ctx: WorkflowContext,
        engine: Engine,
        engine_params_list: Sequence[EngineParams],
    ) -> MetricEvaluatorResult:
        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        scores: list[MetricScores] = []
        best_idx = 0
        for i, ep in enumerate(engine_params_list):
            logger.info("evaluating engine params %d/%d", i + 1, len(engine_params_list))
            eval_data = engine.eval(ctx, ep)
            score = self.metric.calculate(eval_data)
            others = [m.calculate(eval_data) for m in self.other_metrics]
            logger.info("  %s = %s", self.metric.header(), score)
            scores.append(MetricScores(ep, score, others))
            # NaN guard: compare() uses ordering operators, for which NaN
            # answers False both ways — a NaN score in slot 0 (e.g. a grid
            # point whose folds produced no valid queries) could never be
            # displaced and would be persisted as "best". Any finite score
            # beats NaN; NaN never beats anything.
            best_is_nan = scores[best_idx].score != scores[best_idx].score
            score_is_nan = score != score
            if score_is_nan:
                continue
            if best_is_nan or self.metric.compare(score, scores[best_idx].score) > 0:
                best_idx = i
        result = MetricEvaluatorResult(
            best_score=scores[best_idx].score,
            best_engine_params=scores[best_idx].engine_params,
            best_index=best_idx,
            metric_header=self.metric.header(),
            other_metric_headers=[m.header() for m in self.other_metrics],
            engine_params_scores=scores,
        )
        if self.output_path:
            best = {
                "score": result.best_score,
                "engineParams": _params_json(result.best_engine_params),
            }
            os.makedirs(os.path.dirname(self.output_path) or ".", exist_ok=True)
            with open(self.output_path, "w") as f:
                json.dump(best, f, indent=2, sort_keys=True)
            logger.info("best engine params written to %s", self.output_path)
        return result


class Evaluation:
    """Binds an engine, a candidate params source and a metric
    (ref Evaluation.scala). Subclass and set the class attributes, or pass
    everything to the constructor."""

    engine: Engine | None = None
    metric: Metric | None = None
    other_metrics: Sequence[Metric] = ()
    engine_params_generator: EngineParamsGenerator | Sequence[EngineParams] | None = None
    output_path: str | None = None

    def __init__(
        self,
        engine: Engine | None = None,
        metric: Metric | None = None,
        engine_params_generator=None,
        other_metrics: Sequence[Metric] | None = None,
        output_path: str | None = None,
    ):
        if engine is not None:
            self.engine = engine
        if metric is not None:
            self.metric = metric
        if engine_params_generator is not None:
            self.engine_params_generator = engine_params_generator
        if other_metrics is not None:
            self.other_metrics = other_metrics
        if output_path is not None:
            self.output_path = output_path

    def params_list(self) -> Sequence[EngineParams]:
        gen = self.engine_params_generator
        if gen is None:
            raise ValueError("evaluation has no engine_params_generator")
        if isinstance(gen, EngineParamsGenerator):
            return gen.engine_params_list
        return list(gen)

    def run(self, ctx: WorkflowContext) -> MetricEvaluatorResult:
        if self.engine is None or self.metric is None:
            raise ValueError("evaluation must define engine and metric")
        evaluator = MetricEvaluator(
            self.metric, self.other_metrics, self.output_path
        )
        return evaluator.evaluate_base(ctx, self.engine, self.params_list())
