"""EngineParamsGenerator — hyperparameter search spaces.

Reference parity: ``core/.../controller/EngineParamsGenerator.scala:46``
(a trait holding ``engineParamsList``); ``grid_search`` builds the cartesian
product the reference's examples assembled by hand.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Iterable, Mapping, Sequence

from predictionio_tpu.controller.engine import EngineParams


class EngineParamsGenerator:
    """Subclass and set ``engine_params_list``."""

    engine_params_list: Sequence[EngineParams] = ()

    def __init__(self, engine_params_list: Sequence[EngineParams] | None = None):
        if engine_params_list is not None:
            self.engine_params_list = list(engine_params_list)


def grid_search(
    base: EngineParams,
    algorithm_grid: Mapping[str, Iterable[Any]],
    algorithm_index: int = 0,
) -> EngineParamsGenerator:
    """Vary fields of one algorithm's params over a cartesian grid.

    ``algorithm_grid`` maps param field name -> iterable of values, e.g.
    ``{"rank": [8, 16], "lambda_": [0.01, 0.1]}``.
    """
    name, params = base.algorithms[algorithm_index]
    keys = list(algorithm_grid)
    out: list[EngineParams] = []
    for combo in itertools.product(*(list(algorithm_grid[k]) for k in keys)):
        new_params = dataclasses.replace(params, **dict(zip(keys, combo)))
        algorithms = list(base.algorithms)
        algorithms[algorithm_index] = (name, new_params)
        out.append(
            EngineParams(
                data_source=base.data_source,
                preparator=base.preparator,
                algorithms=algorithms,
                serving=base.serving,
            )
        )
    return EngineParamsGenerator(out)
