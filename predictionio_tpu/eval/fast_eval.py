"""FastEvalEngine — grid-search memoization by parameter prefix.

Reference parity: ``core/.../controller/FastEvalEngine.scala:46-346`` —
during grid search, candidate EngineParams often share a prefix
(same datasource -> same folds; same datasource+preparator -> same prepared
data; same +algorithm params -> same trained models). The reference caches
each pipeline stage keyed by its param prefix; this does the same with
plain dicts keyed on params JSON.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Sequence

from predictionio_tpu.controller.base import BaseDataSource, BasePreparator, Doer
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.workflow.context import WorkflowContext

logger = logging.getLogger(__name__)


def _key(*parts: Any) -> str:
    def enc(p):
        if p is None:
            return "null"
        if hasattr(p, "to_json"):
            return p.to_json()
        return json.dumps(p, sort_keys=True, default=str)

    return "|".join(enc(p) for p in parts)


class FastEvalEngine(Engine):
    """Drop-in Engine whose ``eval`` memoizes shared stages across calls.

    Use with MetricEvaluator over a params grid: data is read once per
    distinct datasource params, prepared once per (ds, prep) pair, and each
    algorithm is trained once per (ds, prep, algo-params, fold) tuple.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._eval_data_cache: dict[str, list] = {}
        self._prepared_cache: dict[str, list] = {}
        self._model_cache: dict[str, Any] = {}
        # hit/miss accounting per stage — the evaluation grid's worker
        # asserts its prefix sharing on these (docs/evaluation.md), and
        # they make "did the cache actually help" a measurable question
        self.cache_stats: dict[str, int] = {
            "read_hits": 0,
            "read_misses": 0,
            "prepare_hits": 0,
            "prepare_misses": 0,
            "train_hits": 0,
            "train_misses": 0,
            "model_clears": 0,
        }

    def clear_caches(self, keep_data: bool = False) -> None:
        """Drop memoized stages. ``keep_data=True`` clears only the model
        cache — the grid scheduler calls this between params groups to
        bound worker memory (trained models are the big objects) while
        cells in later groups still share the data_source/preparator
        prefix reads."""
        if not keep_data:
            self._eval_data_cache.clear()
            self._prepared_cache.clear()
        if self._model_cache:
            self.cache_stats["model_clears"] += 1
        self._model_cache.clear()

    def _eval_folds(self, ctx: WorkflowContext, ep: EngineParams) -> list:
        key = _key("ds", ep.data_source[0], ep.data_source[1])
        if key not in self._eval_data_cache:
            self.cache_stats["read_misses"] += 1
            ds: BaseDataSource = Doer.apply(
                self._pick(self.data_source_classes, ep.data_source[0], "datasource"),
                ep.data_source[1],
            )
            self._eval_data_cache[key] = [
                (td, ei, list(qa)) for td, ei, qa in ds.read_eval(ctx)
            ]
            logger.debug("fast-eval: read_eval MISS %s", key[:80])
        else:
            self.cache_stats["read_hits"] += 1
        return self._eval_data_cache[key]

    def _prepared(self, ctx: WorkflowContext, ep: EngineParams) -> list:
        key = _key(
            "prep", ep.data_source[0], ep.data_source[1], ep.preparator[0], ep.preparator[1]
        )
        if key not in self._prepared_cache:
            self.cache_stats["prepare_misses"] += 1
            prep: BasePreparator = Doer.apply(
                self._pick(self.preparator_classes, ep.preparator[0], "preparator"),
                ep.preparator[1],
            )
            folds = self._eval_folds(ctx, ep)
            self._prepared_cache[key] = [prep.prepare(ctx, td) for td, _, _ in folds]
        else:
            self.cache_stats["prepare_hits"] += 1
        return self._prepared_cache[key]

    def _trained_model(
        self, ctx: WorkflowContext, ep: EngineParams, algo_idx: int, fold_idx: int
    ):
        name, params = (ep.algorithms or [("", None)])[algo_idx]
        key = _key(
            "model",
            ep.data_source[0],
            ep.data_source[1],
            ep.preparator[0],
            ep.preparator[1],
            name,
            params,
            fold_idx,
        )
        if key not in self._model_cache:
            self.cache_stats["train_misses"] += 1
            algo = Doer.apply(
                self._pick(self.algorithm_classes, name, "algorithm"), params
            )
            pd = self._prepared(ctx, ep)[fold_idx]
            self._model_cache[key] = algo.train(ctx, pd)
        else:
            self.cache_stats["train_hits"] += 1
        return self._model_cache[key]

    def eval(
        self, ctx: WorkflowContext, engine_params: EngineParams
    ) -> list[tuple[Any, list[tuple[Any, Any, Any]]]]:
        folds = self._eval_folds(ctx, engine_params)
        algo_list = engine_params.algorithms or [("", None)]
        algorithms = [
            Doer.apply(self._pick(self.algorithm_classes, name, "algorithm"), p)
            for name, p in algo_list
        ]
        serving = Doer.apply(
            self._pick(self.serving_classes, engine_params.serving[0], "serving"),
            engine_params.serving[1],
        )
        results = []
        for fold_idx, (td, ei, qa_list) in enumerate(folds):
            models = [
                self._trained_model(ctx, engine_params, i, fold_idx)
                for i in range(len(algorithms))
            ]
            supplemented = [
                (i, serving.supplement(q)) for i, (q, _) in enumerate(qa_list)
            ]
            per_query: list[list] = [[] for _ in qa_list]
            for algo, model in zip(algorithms, models):
                for i, p in algo.batch_predict(model, supplemented):
                    per_query[i].append(p)
            joined = [
                (qa_list[i][0], serving.serve(qa_list[i][0], preds), qa_list[i][1])
                for i, preds in enumerate(per_query)
            ]
            results.append((ei, joined))
        return results
