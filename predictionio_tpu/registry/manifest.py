"""Lineage manifests: the self-describing identity of one model version.

MLlib's persistence paper (1505.06807) motivates portable, self-describing
model artifacts; the TensorFlow paper (1605.08695) treats versioned
checkpoint lineage as a first-class system concern. A manifest records
everything needed to answer "what exactly is this blob and where did it
come from" without loading it: engine identity, a canonical hash of the
training params, the parent version it superseded, metrics known at train
time, and the blob's sha256 + length (verified on every read by
:mod:`predictionio_tpu.registry.store`).

Stdlib-only: ``pio models`` must start without jax/numpy.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import hashlib
import json
from typing import Any

UTC = _dt.timezone.utc


def params_hash_of(params: Any) -> str:
    """Canonical sha256 of an engine-params JSON structure (sorted keys,
    compact separators) so semantically identical params always hash
    identically regardless of dict ordering."""
    canonical = json.dumps(params, sort_keys=True, separators=(",", ":"), default=str)
    return hashlib.sha256(canonical.encode("utf-8")).hexdigest()


@dataclasses.dataclass
class ModelManifest:
    """One versioned model artifact's lineage record."""

    version: str  # registry version id, e.g. "v000007" ("" until published)
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str = ""
    instance_id: str = ""  # metadata-store EngineInstance this came from
    params_hash: str = ""  # params_hash_of(engine params json)
    parent_version: str = ""  # stable version at publish time ("" for first)
    created_at: str = ""  # ISO-8601 UTC
    data_span: dict[str, Any] = dataclasses.field(default_factory=dict)
    metrics: dict[str, Any] = dataclasses.field(default_factory=dict)
    # training evidence: the obs/xray TrainProfile JSON of the run that
    # produced this blob (step timeline, phase/device timings, memory
    # peaks, capacity estimate) — `pio models show` renders it, `diff`
    # compares wall clock and memory between versions. Empty for versions
    # published before the profiler existed (or with PIO_XRAY=0).
    train_profile: dict[str, Any] = dataclasses.field(default_factory=dict)
    # evaluation-grid evidence (predictionio_tpu/tuning, docs/evaluation.md):
    # when this version is a grid search's winning refit, the full search
    # record rides here — metric, fold layout, per-params scores table,
    # per-cell results, and the trial ledger's sha256 as the integrity
    # anchor — so "why did this version ship" is answerable from the
    # manifest alone. Empty for versions trained outside a grid.
    eval_evidence: dict[str, Any] = dataclasses.field(default_factory=dict)
    # the version's ANN retrieval index (predictionio_tpu/ann, docs/ann.md):
    # a second content-addressed blob in the same engine's blob store,
    # recorded here with its sha256/bytes plus layout metadata (items,
    # clusters, bucketCap, nprobe, quantized, builtFrom). Empty when no
    # index was built (small corpus, or a model type ANN doesn't apply to)
    # — serving then stays on exact scoring.
    ann_index: dict[str, Any] = dataclasses.field(default_factory=dict)
    blob_sha256: str = ""  # filled by the store on publish
    blob_size: int = 0

    def to_json_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json_dict(cls, data: dict[str, Any]) -> "ModelManifest":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})

    @staticmethod
    def now_iso() -> str:
        return _dt.datetime.now(tz=UTC).isoformat()

    def summary_row(self) -> dict[str, Any]:
        """The compact row ``pio models list`` prints."""
        return {
            "version": self.version,
            "created": self.created_at,
            "instance": self.instance_id,
            "paramsHash": self.params_hash[:12],
            "sha256": self.blob_sha256[:12],
            "bytes": self.blob_size,
            "parent": self.parent_version,
        }
