"""Lease-based mutex for registry state on shared storage.

PR 9's cross-process story was an advisory ``flock`` on a lock file —
correct on one box, useless the moment two hosts mount the registry over
NFS/SMB (many network filesystems map ``flock`` to a no-op, and even
where mapped, the lock dies with the NFS client, not the holder). The
multi-host fleet needs the classic distributed-lease shape instead:

- The lease is a small JSON file next to the state it guards, paired
  with an ``O_EXCL`` **claim file** that is the actual exclusion
  primitive: exactly one process can create it, and only the claimant
  writes the lease record (tmp+rename, atomic on POSIX). Rename+re-read
  alone is NOT mutual exclusion — two racers can each confirm on their
  own re-read before the other's rename lands (the two-process hammer
  reproduces this) — so the claim gates the write and the post-write
  re-read stays as a cheap second check for the steal-vs-steal edge.
- ``generation`` is the **fencing token**: monotonic across owners,
  bumped on every acquisition, *including* steals, and never reset —
  release writes an ``owner=""`` tombstone that keeps the counter, so a
  token can never be reissued. ``ArtifactStore._save_state`` re-checks
  the token before persisting a transition; a holder that lost its lease
  mid-critical-section gets :class:`LeaseLostError` instead of
  clobbering the thief's writes (Lamport's fencing discipline).
- Liveness: a holder that dies keeps the lease until its **TTL**
  expires, then any waiter steals it. Same-host deaths are detected
  faster: the lease records ``host:pid``, and a waiter on the same host
  whose kill-0 shows the pid gone steals immediately — preserving the
  instant-recovery property ``flock`` gave single-box deploys.

The ``flock`` fast path **stays**: ``_state_mutex`` takes the flock
first (serializing same-host processes at kernel speed, zero polling),
then the lease (serializing hosts). ``PIO_REGISTRY_LEASE=0`` disables
the lease layer entirely for strictly-local deployments.

Clock injectable; the TTL/steal machinery is unit-tested on a fake
clock and hammered across two real processes (tests/test_lease.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
import tempfile
import threading
import time
import uuid
from typing import Any, Callable

logger = logging.getLogger(__name__)

DEFAULT_TTL_S = 30.0


class LeaseLostError(RuntimeError):
    """The held lease expired (or was stolen) mid-critical-section; the
    transition MUST NOT persist — a newer fencing token exists."""


class LeaseTimeoutError(TimeoutError):
    """Could not acquire the lease inside the wait budget."""


# module-level telemetry: sampled by register_lease_metrics collectors so
# every store instance in the process feeds one exposition
_COUNTS = {
    "acquires": 0,
    "steals": 0,
    "lost": 0,
    "waits": 0,
}
_GENERATIONS: dict[str, int] = {}  # lease path -> last token seen here
_COUNTS_LOCK = threading.Lock()


def _bump(key: str, n: int = 1) -> None:
    with _COUNTS_LOCK:
        _COUNTS[key] += n


@dataclasses.dataclass
class LeaseRecord:
    owner: str
    generation: int
    acquired_at: float  # wall clock (cross-host comparable enough for TTLs)
    ttl_s: float
    host: str = ""
    pid: int = 0

    def expired(self, now: float) -> bool:
        return bool(self.owner) and now >= self.acquired_at + self.ttl_s

    def free(self) -> bool:
        return not self.owner

    def to_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: dict[str, Any]) -> "LeaseRecord":
        return cls(
            owner=str(obj.get("owner", "")),
            generation=int(obj.get("generation", 0)),
            acquired_at=float(obj.get("acquired_at", 0.0)),
            ttl_s=float(obj.get("ttl_s", DEFAULT_TTL_S)),
            host=str(obj.get("host", "")),
            pid=int(obj.get("pid", 0)),
        )


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        pass  # EPERM etc: it exists
    return True


class LeaseMutex:
    """One lease file. NOT reentrant and NOT thread-safe by itself — the
    store holds its own process-level locks above this (flock serializes
    same-host processes; ``ArtifactStore._lock`` serializes threads)."""

    def __init__(
        self,
        path: str,
        owner: str | None = None,
        ttl_s: float = DEFAULT_TTL_S,
        clock: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
        poll_interval_s: float | None = None,
    ):
        self.path = path
        self.host = socket.gethostname()
        self.pid = os.getpid()
        self.owner = owner or f"{self.host}:{self.pid}:{uuid.uuid4().hex[:8]}"
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._sleep = sleep
        self.poll_interval_s = (
            poll_interval_s
            if poll_interval_s is not None
            else min(1.0, max(0.05, self.ttl_s / 20.0))
        )
        self.generation = 0  # token from OUR last successful acquire
        self._held = False
        # (claim content, first seen at) — how long the same orphan claim
        # has sat over a free record; past ttl_s it is droppable
        self._claim_seen: tuple[str, float] | None = None

    # ----------------------------------------------------------------- file
    def read(self) -> LeaseRecord | None:
        """Current lease record, or None when no lease file exists yet.
        A torn/corrupt read (mid-rename on a sloppy filesystem) is
        retried once, then treated as contention — never as 'free'."""
        for attempt in (0, 1):
            try:
                with open(self.path, encoding="utf-8") as fh:
                    return LeaseRecord.from_json(json.load(fh))
            except FileNotFoundError:
                return None
            except (OSError, ValueError):
                if attempt == 0:
                    self._sleep(0.01)
        # unreadable twice: report a synthetic held-forever record so the
        # caller waits (and eventually times out loudly) instead of
        # acquiring on top of garbage
        return LeaseRecord(
            owner="<unreadable>",
            generation=0,
            acquired_at=self._clock(),
            ttl_s=self.ttl_s,
        )

    def _write(self, rec: LeaseRecord) -> None:
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".lease-")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(rec.to_json(), fh)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # ------------------------------------------------------------- protocol
    def _stealable(self, cur: LeaseRecord, now: float) -> bool:
        if cur.free() or cur.owner == self.owner:
            return True
        if cur.expired(now):
            return True
        # same-host fast steal: the holder's pid is visibly gone — the
        # instant-recovery property flock gave single-box deploys
        if cur.host == self.host and cur.pid and not _pid_alive(cur.pid):
            return True
        return False

    @property
    def claim_path(self) -> str:
        return self.path + ".claim"

    def _try_claim(self) -> bool:
        """Atomically create the claim file (O_EXCL). True = we hold the
        exclusion primitive and may write the lease record."""
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        try:
            fd = os.open(
                self.claim_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY
            )
        except FileExistsError:
            return False
        try:
            os.write(
                fd,
                json.dumps(
                    {"owner": self.owner, "host": self.host, "pid": self.pid}
                ).encode("utf-8"),
            )
        finally:
            os.close(fd)
        return True

    def _drop_claim(self) -> None:
        try:
            os.unlink(self.claim_path)
        except OSError:
            pass

    def _read_claim(self) -> dict[str, Any]:
        try:
            with open(self.claim_path, encoding="utf-8") as fh:
                obj = json.load(fh)
            return obj if isinstance(obj, dict) else {}
        except (OSError, ValueError):
            return {}

    def _claim_is_stale(self, cur: LeaseRecord | None, now: float) -> bool:
        """May the existing claim be unlinked? ONLY when it provably
        belongs to a dead or expired holder — never on a hunch, because
        unlinking a LIVE claimant's file would hand exclusion to two
        processes at once (the very race the claim exists to close):

        - claim owner == the record's owner and the record is stealable
          (expired TTL / dead pid): the holder died holding both;
        - claim's own host:pid is this host and the pid is gone: a
          claimant that crashed between claiming and writing the record;
        - the same claim content has sat over a free record for a full
          TTL of OUR observation: a foreign-host claimant crashed
          mid-acquire (judged on the injected clock, so fake-clock tests
          and real deployments agree on the rule)."""
        claim = self._read_claim()
        if not claim:
            # unreadable/half-written: judge it by observation time below
            claim = {"owner": "<unreadable>"}
        c_owner = str(claim.get("owner", ""))
        if cur is not None and not cur.free():
            self._claim_seen = None  # record owned: observation over
            if c_owner == cur.owner:
                return self._stealable(cur, now)
            return False  # someone else's live hold is in flight: wait
        if (
            str(claim.get("host", "")) == self.host
            and int(claim.get("pid", 0) or 0)
            and not _pid_alive(int(claim["pid"]))
        ):
            return True
        seen = self._claim_seen
        if seen is not None and seen[0] == c_owner:
            return now - seen[1] >= self.ttl_s
        self._claim_seen = (c_owner, now)
        return False

    def acquire(self, timeout_s: float = 60.0) -> int:
        """Block until held (or :class:`LeaseTimeoutError`); returns the
        fencing token. Two layers: the O_EXCL claim file serializes
        writers; the lease record decides liveness (TTL / dead-pid
        steals) and carries the token."""
        deadline = self._clock() + timeout_s
        waited = False
        claimed = False
        try:
            while True:
                now = self._clock()
                cur = self.read()
                if not claimed:
                    claimed = self._try_claim()
                    if not claimed:
                        if self._read_claim().get("owner") == self.owner:
                            # our own leftover (a lost confirm edge, an
                            # aborted acquire): it is already exclusion
                            claimed = True
                        elif self._claim_is_stale(cur, now):
                            # a claimant that died holding the claim
                            # (with or without having written its
                            # record): clear the orphan and race O_EXCL
                            # — exactly one waiter wins
                            self._drop_claim()
                            self._claim_seen = None
                            claimed = self._try_claim()
                if claimed:
                    cur = self.read()
                    if cur is None or self._stealable(cur, now):
                        stolen = (
                            cur is not None
                            and not cur.free()
                            and cur.owner != self.owner
                        )
                        cand = LeaseRecord(
                            owner=self.owner,
                            generation=(cur.generation if cur else 0) + 1,
                            acquired_at=now,
                            ttl_s=self.ttl_s,
                            host=self.host,
                            pid=self.pid,
                        )
                        self._write(cand)
                        # steal-vs-steal edge (a racer whose claim was
                        # cleared underneath it): re-read to confirm our
                        # record actually survived
                        back = self.read()
                        if (
                            back is not None
                            and back.owner == self.owner
                            and back.generation == cand.generation
                        ):
                            self.generation = cand.generation
                            self._held = True
                            claimed = False  # ours now; keep the file
                            _bump("acquires")
                            if stolen:
                                _bump("steals")
                                logger.warning(
                                    "lease %s stolen from %s (token %d)",
                                    self.path,
                                    cur.owner,
                                    cand.generation,
                                )
                            with _COUNTS_LOCK:
                                _GENERATIONS[self.path] = cand.generation
                            return cand.generation
                        claimed = False  # lost the edge; start over
                    # else: live foreign record under our claim (we raced
                    # a release in progress) — hold the claim and poll
                if self._clock() >= deadline:
                    holder = cur.owner if cur else "?"
                    raise LeaseTimeoutError(
                        f"lease {self.path} held by {holder!r} past "
                        f"{timeout_s:.1f}s wait budget"
                    )
                if not waited:
                    waited = True
                    _bump("waits")
                self._sleep(self.poll_interval_s)
        except BaseException:
            if claimed and self._read_claim().get("owner") == self.owner:
                self._drop_claim()
            raise

    def verify(self) -> int:
        """Fencing check: still ours? Returns the token, or raises
        :class:`LeaseLostError`. Called by the store immediately before
        every persisted transition."""
        cur = self.read()
        if (
            cur is None
            or cur.owner != self.owner
            or cur.generation != self.generation
        ):
            self._held = False
            _bump("lost")
            raise LeaseLostError(
                f"lease {self.path} no longer held (token {self.generation}; "
                f"current: {cur.to_json() if cur else 'missing'})"
            )
        return self.generation

    def renew(self) -> int:
        """Re-stamp acquired_at (long critical sections); fencing token
        unchanged. Raises :class:`LeaseLostError` when already lost."""
        self.verify()
        self._write(
            LeaseRecord(
                owner=self.owner,
                generation=self.generation,
                acquired_at=self._clock(),
                ttl_s=self.ttl_s,
                host=self.host,
                pid=self.pid,
            )
        )
        return self.generation

    def release(self) -> None:
        """Write the free tombstone (generation preserved — tokens are
        never reissued). Releasing a lease someone already stole is a
        no-op: their record must survive."""
        if not self._held:
            return
        self._held = False
        cur = self.read()
        if (
            cur is None
            or cur.owner != self.owner
            or cur.generation != self.generation
        ):
            return  # stolen: the thief's record AND claim must survive
        self._write(
            LeaseRecord(
                owner="",
                generation=self.generation,
                acquired_at=self._clock(),
                ttl_s=self.ttl_s,
            )
        )
        # ownership-checked: after the release/steal interleave the claim
        # file may already be a waiter's — unlinking theirs would hand
        # exclusion to two processes at once
        if self._read_claim().get("owner") == self.owner:
            self._drop_claim()

    @property
    def held(self) -> bool:
        return self._held

    def __enter__(self) -> "LeaseMutex":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


def lease_enabled() -> bool:
    """``PIO_REGISTRY_LEASE=0`` opts strictly-local deployments out of
    the lease layer (flock alone, the pre-PR-17 behavior)."""
    return os.environ.get("PIO_REGISTRY_LEASE", "1") not in ("0", "false", "no")


def lease_ttl_s() -> float:
    try:
        return float(os.environ.get("PIO_REGISTRY_LEASE_TTL", DEFAULT_TTL_S))
    except ValueError:
        return DEFAULT_TTL_S


def register_lease_metrics(metrics: Any) -> None:
    """Export the process-wide lease counters as ``pio_registry_lease_*``
    (docs/observability.md §Registry). Idempotent per registry — the
    MetricsRegistry returns the existing instrument on re-registration."""
    m_acquires = metrics.counter(
        "pio_registry_lease_acquires_total",
        "registry lease acquisitions by this process (steals included)",
    )
    m_steals = metrics.counter(
        "pio_registry_lease_steals_total",
        "leases taken over from a dead/expired holder (TTL expiry or "
        "same-host pid-gone fast path)",
    )
    m_lost = metrics.counter(
        "pio_registry_lease_lost_total",
        "fencing-token rejections: transitions aborted because the lease "
        "was stolen mid-critical-section",
    )
    m_waits = metrics.counter(
        "pio_registry_lease_waits_total",
        "acquire calls that had to wait on another holder",
    )
    m_gen = metrics.gauge(
        "pio_registry_lease_generation",
        "current fencing token per lease file (monotonic across owners; "
        "a persisted transition always carries the token that wrote it)",
        labelnames=("lease",),
    )

    def collect() -> None:
        with _COUNTS_LOCK:
            counts = dict(_COUNTS)
            gens = dict(_GENERATIONS)
        m_acquires.set_total(float(counts["acquires"]))
        m_steals.set_total(float(counts["steals"]))
        m_lost.set_total(float(counts["lost"]))
        m_waits.set_total(float(counts["waits"]))
        for path, gen in gens.items():
            m_gen.set(
                float(gen),
                lease=os.path.basename(os.path.dirname(path)) or path,
            )

    metrics.register_collector(collect)


__all__ = [
    "DEFAULT_TTL_S",
    "LeaseLostError",
    "LeaseMutex",
    "LeaseRecord",
    "LeaseTimeoutError",
    "lease_enabled",
    "lease_ttl_s",
    "register_lease_metrics",
]
