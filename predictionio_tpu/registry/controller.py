"""Rollout controller: metric-gated promote / rollback decisions.

The controller turns the per-version serving metrics
(:class:`~predictionio_tpu.registry.router.RolloutInstruments`) into one
of four verdicts for the active candidate:

- ``wait``     — bake window or minimum sample size not reached yet;
- ``promote``  — candidate matched or beat stable across every gate;
- ``rollback`` — candidate breached a gate (error rate, p95 latency, or
  shadow divergence), with the breached gate in the reason;
- ``ready``    — gates passed but auto-promotion is disabled (an operator
  promotes via ``pio models promote`` / ``POST /models/promote``).

It is deliberately *pure decision logic*: the QueryServer owns applying
the verdict (swapping lanes, persisting registry state) and the candidate
lane's circuit breaker provides the fast path — a breaker trip forces an
instant rollback without waiting for the next evaluation tick.

Stable-lane counters accumulate across rollouts, so every comparison uses
deltas since the candidate was staged — the two models are judged on the
same traffic window.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

from predictionio_tpu.registry.router import RolloutInstruments

VERDICT_IDLE = "idle"
VERDICT_WAIT = "wait"
VERDICT_PROMOTE = "promote"
VERDICT_ROLLBACK = "rollback"
VERDICT_READY = "ready"


@dataclasses.dataclass(frozen=True)
class PromotionCriteria:
    """The promotion-gate knobs (docs/model_registry.md)."""

    # candidate must bake at least this long AND serve at least this many
    # queries (shadow: score this many) before any verdict
    bake_window_s: float = 60.0
    min_requests: int = 20
    # error-rate gate: candidate rate may not exceed
    # stable_rate * max_error_ratio + error_rate_floor (the floor keeps a
    # perfect stable lane from making a single candidate error fatal)
    max_error_ratio: float = 2.0
    error_rate_floor: float = 0.02
    # latency gate: candidate predict p95 may not exceed stable's by this
    # factor (only enforced once both versions have predict samples)
    max_p95_ratio: float = 1.5
    # shadow gate: fraction of shadow-scored queries whose result diverged
    max_divergence_rate: float = 0.25
    auto_promote: bool = True


@dataclasses.dataclass
class _Baseline:
    stable_version: str
    candidate_version: str
    mode: str
    staged_at: float
    stable_requests: float
    stable_errors: float
    cand_requests: float
    cand_errors: float
    shadow_scored: float
    divergence: float
    stable_predict_counts: list
    cand_predict_counts: list


class RolloutController:
    def __init__(
        self,
        instruments: RolloutInstruments,
        criteria: PromotionCriteria | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.instruments = instruments
        self.criteria = criteria or PromotionCriteria()
        self._clock = clock
        self._baseline: _Baseline | None = None

    # ----------------------------------------------------------- lifecycle
    def begin(self, stable_version: str, candidate_version: str, mode: str) -> None:
        """Snapshot both lanes' counters at stage time; every later
        comparison is a delta against this point."""
        stable = self.instruments.lane_counts(stable_version)
        cand = self.instruments.lane_counts(candidate_version)
        self._baseline = _Baseline(
            stable_version=stable_version,
            candidate_version=candidate_version,
            mode=mode,
            staged_at=self._clock(),
            stable_requests=stable["requests"],
            stable_errors=stable["errors"],
            cand_requests=cand["requests"],
            cand_errors=cand["errors"],
            shadow_scored=cand["shadow_scored"],
            divergence=cand["divergence"],
            stable_predict_counts=self.instruments.predict_bucket_counts(
                stable_version
            ),
            cand_predict_counts=self.instruments.predict_bucket_counts(
                candidate_version
            ),
        )

    def end(self) -> None:
        self._baseline = None

    @property
    def active(self) -> bool:
        return self._baseline is not None

    # ------------------------------------------------------------ verdicts
    def evaluate(self) -> tuple[str, str]:
        """One (verdict, reason) pair; call on a timer or on demand."""
        b = self._baseline
        c = self.criteria
        if b is None:
            return VERDICT_IDLE, "no rollout active"
        stable = self.instruments.lane_counts(b.stable_version)
        cand = self.instruments.lane_counts(b.candidate_version)
        stable_n = stable["requests"] - b.stable_requests
        stable_err = stable["errors"] - b.stable_errors
        cand_n = cand["requests"] - b.cand_requests
        cand_err = cand["errors"] - b.cand_errors
        scored = cand["shadow_scored"] - b.shadow_scored
        diverged = cand["divergence"] - b.divergence
        # the candidate's sample is real traffic in canary mode, async
        # shadow scores in shadow mode
        sample_n = scored if b.mode == "shadow" else cand_n
        sample_err = cand_err
        elapsed = self._clock() - b.staged_at
        if elapsed < c.bake_window_s or sample_n < c.min_requests:
            return (
                VERDICT_WAIT,
                f"baking: {sample_n:.0f}/{c.min_requests} queries, "
                f"{elapsed:.1f}/{c.bake_window_s:.1f}s",
            )
        # -- error-rate gate ------------------------------------------------
        cand_rate = sample_err / sample_n if sample_n else 0.0
        stable_rate = stable_err / stable_n if stable_n else 0.0
        allowed = stable_rate * c.max_error_ratio + c.error_rate_floor
        if cand_rate > allowed:
            return (
                VERDICT_ROLLBACK,
                f"error-rate gate: candidate {cand_rate:.3f} > allowed "
                f"{allowed:.3f} (stable {stable_rate:.3f})",
            )
        # -- latency gate (windowed: this bake's samples only — a re-staged
        # candidate must not be judged on a previous bake's latency) -------
        cand_p95 = self.instruments.p95_since(
            b.candidate_version, b.cand_predict_counts
        )
        stable_p95 = self.instruments.p95_since(
            b.stable_version, b.stable_predict_counts
        )
        if cand_p95 > 0 and stable_p95 > 0 and cand_p95 > stable_p95 * c.max_p95_ratio:
            return (
                VERDICT_ROLLBACK,
                f"latency gate: candidate p95 {cand_p95 * 1e3:.1f}ms > "
                f"{c.max_p95_ratio:.2f}x stable {stable_p95 * 1e3:.1f}ms",
            )
        # -- divergence gate (shadow only) ----------------------------------
        if b.mode == "shadow" and scored > 0:
            div_rate = diverged / scored
            if div_rate > c.max_divergence_rate:
                return (
                    VERDICT_ROLLBACK,
                    f"divergence gate: {div_rate:.3f} of shadow traffic "
                    f"diverged (> {c.max_divergence_rate:.3f})",
                )
        reason = (
            f"gates passed over {sample_n:.0f} queries "
            f"(err {cand_rate:.3f} vs stable {stable_rate:.3f})"
        )
        if not c.auto_promote:
            return VERDICT_READY, reason
        return VERDICT_PROMOTE, reason

    def snapshot(self) -> dict:
        """JSON-ready controller state for /models and `pio models show`."""
        b = self._baseline
        out: dict = {"active": b is not None, "criteria": dataclasses.asdict(self.criteria)}
        if b is not None:
            verdict, reason = self.evaluate()
            out.update(
                {
                    "stable": b.stable_version,
                    "candidate": b.candidate_version,
                    "mode": b.mode,
                    "elapsed_s": round(self._clock() - b.staged_at, 3),
                    "verdict": verdict,
                    "reason": reason,
                }
            )
        return out
