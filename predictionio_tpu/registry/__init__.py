"""Model registry + progressive rollout.

The reference's lifecycle stops at "persist the trained blob, reload the
latest COMPLETED instance" (``CreateServer.scala`` MasterActor reload).
This package is the subsystem that makes a bad train unable to take down
serving:

- :mod:`.manifest` — self-describing lineage manifests (engine identity,
  params hash, parent version, train metrics, blob checksum);
- :mod:`.store` — content-addressed, sha256-verified artifact store with
  a rollout state machine (stable/candidate/history) and GC;
- :mod:`.router` — serving-lane snapshots: a pinned *stable* version plus
  a *candidate* taking a sticky-hashed canary fraction or shadow traffic;
- :mod:`.controller` — compares candidate vs stable over a bake window
  using the obs metrics registry and auto-promotes or auto-rolls-back.

Import-light by design: ``manifest``/``store`` are stdlib-only so the
CLI's ``pio models`` verbs start without jax/numpy.
"""

from predictionio_tpu.registry.controller import (
    PromotionCriteria,
    RolloutController,
)
from predictionio_tpu.registry.manifest import (
    ModelManifest,
    params_hash_of,
)
from predictionio_tpu.registry.probe import registry_rollout_probe
from predictionio_tpu.registry.router import (
    Lane,
    RolloutInstruments,
    RolloutPlan,
    sticky_bucket,
)
from predictionio_tpu.registry.store import (
    ArtifactIntegrityError,
    ArtifactStore,
    RolloutState,
    default_registry_dir,
)

__all__ = [
    "ArtifactIntegrityError",
    "ArtifactStore",
    "Lane",
    "ModelManifest",
    "PromotionCriteria",
    "RolloutController",
    "RolloutInstruments",
    "RolloutPlan",
    "RolloutState",
    "default_registry_dir",
    "params_hash_of",
    "registry_rollout_probe",
    "sticky_bucket",
]
