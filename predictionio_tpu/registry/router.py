"""Rollout routing: serving lanes, sticky canary hashing, per-version
instruments.

The QueryServer serves a pinned *stable* lane and, during a rollout, a
*candidate* lane. Routing must be:

- **Sticky per user.** A user sees ONE model for the whole bake — flapping
  between recommendation models per request reads as a broken product and
  poisons divergence metrics. :func:`sticky_bucket` hashes the routing key
  (plus a salt so successive rollouts resample different users) into
  ``[0, 1)``; a request goes candidate iff its bucket falls under the
  canary fraction.
- **Consistent per batch.** A lane is an immutable :class:`Lane` tuple
  (algorithms, serving, models, version, instance) snapshotted in a single
  attribute read, so an in-flight micro-batch is immune to concurrent
  promote/rollback — the same contract the server already gives /reload.

Per-version metrics carry the ``version`` label on the existing /metrics
surface (``pio_model_requests_total``, ``pio_model_errors_total``,
``pio_model_predict_seconds``, ``pio_shadow_divergence_total``) — the
inputs the rollout controller gates promotion on.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, NamedTuple

from predictionio_tpu.obs.metrics import MetricsRegistry

LANE_STABLE = "stable"
LANE_CANDIDATE = "candidate"
LANE_SHADOW = "shadow"


class Lane(NamedTuple):
    """One servable model version. Immutable: the dispatch thread snapshots
    the whole quadruple-plus-instance in one attribute read."""

    algorithms: list[Any]
    serving: Any
    models: list[Any]
    version: str
    instance_id: str
    engine_params: Any = None  # carried so promote can adopt them wholesale


class RolloutPlan(NamedTuple):
    """The routing decision inputs, snapshotted together. ``mode`` is
    off|canary|shadow; ``salt`` varies per staged rollout so consecutive
    canaries sample different user populations."""

    mode: str
    fraction: float
    salt: str


PLAN_OFF = RolloutPlan("off", 0.0, "")


def sticky_bucket(key: str, salt: str = "") -> float:
    """Deterministically map a routing key to ``[0, 1)``. sha256 (not
    ``hash()``) so the assignment is stable across processes and restarts —
    a replica fleet must agree on which users are canaried."""
    digest = hashlib.sha256(f"{salt}:{key}".encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64)


def routing_key(payload: Any, field: str) -> str:
    """Extract the sticky key from a query payload: the configured field
    when present (``user`` by default), else a canonical hash of the whole
    payload — still deterministic, so identical queries route identically
    even without a user id."""
    if isinstance(payload, dict):
        value = payload.get(field)
        if value is not None:
            return str(value)
    try:
        return json.dumps(payload, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return repr(payload)


def choose_lane(plan: RolloutPlan, key: str) -> str:
    """stable | candidate for one request under the given plan. Shadow
    mode always answers from stable (the candidate is scored async)."""
    if plan.mode != "canary" or plan.fraction <= 0.0:
        return LANE_STABLE
    if sticky_bucket(key, plan.salt) < plan.fraction:
        return LANE_CANDIDATE
    return LANE_STABLE


class RolloutInstruments:
    """Per-version serving metrics on the server's existing registry.

    Label cardinality is bounded by GC: only versions actually serving
    (stable + one candidate at a time) produce series.
    """

    def __init__(self, registry: MetricsRegistry):
        self.requests = registry.counter(
            "pio_model_requests_total",
            "queries served, by model version and lane",
            labelnames=("version", "lane"),
        )
        self.errors = registry.counter(
            "pio_model_errors_total",
            "per-query predict/serve failures, by model version and lane",
            labelnames=("version", "lane"),
        )
        self.predict_seconds = registry.histogram(
            "pio_model_predict_seconds",
            "per-batch predict wall time, by model version",
            labelnames=("version",),
        )
        self.divergence = registry.counter(
            "pio_shadow_divergence_total",
            "shadow-scored queries whose candidate result differed from stable",
            labelnames=("version",),
        )
        self.shadow_scored = registry.counter(
            "pio_shadow_scored_total",
            "queries shadow-scored against the candidate",
            labelnames=("version",),
        )
        self.shadow_dropped = registry.counter(
            "pio_shadow_dropped_total",
            "queries skipped by shadow scoring because the backlog was full "
            "(shadow is sampling, not accounting)",
            labelnames=("version",),
        )
        self.rollbacks = registry.counter(
            "pio_rollbacks_total",
            "candidate rollbacks, by trigger",
            labelnames=("reason",),
        )
        self.promotions = registry.counter(
            "pio_promotions_total",
            "candidate promotions to stable",
        )
        self.fraction_gauge = registry.gauge(
            "pio_rollout_fraction",
            "current canary fraction (0 when no rollout is active)",
        )
        self.mode_gauge = registry.gauge(
            "pio_rollout_mode",
            "rollout mode (0=off, 1=canary, 2=shadow)",
        )

    MODE_VALUES = {"off": 0.0, "canary": 1.0, "shadow": 2.0}

    def set_plan(self, plan: RolloutPlan) -> None:
        self.fraction_gauge.set(plan.fraction)
        self.mode_gauge.set(self.MODE_VALUES.get(plan.mode, -1.0))

    # -- controller inputs --------------------------------------------------
    def lane_counts(self, version: str) -> dict[str, float]:
        """requests/errors totals for one version across lanes, plus the
        shadow tallies — the raw inputs PromotionCriteria compares."""
        req = 0.0
        err = 0.0
        for lane in (LANE_STABLE, LANE_CANDIDATE, LANE_SHADOW):
            req += self.requests.value(version=version, lane=lane)
            err += self.errors.value(version=version, lane=lane)
        return {
            "requests": req,
            "errors": err,
            "shadow_scored": self.shadow_scored.value(version=version),
            "divergence": self.divergence.value(version=version),
        }

    def p95_seconds(self, version: str) -> float:
        summary = self.predict_seconds.summary(version=version)
        return float(summary.get("p95", 0.0)) if summary.get("count") else 0.0

    def predict_bucket_counts(self, version: str) -> list[int]:
        """Baseline snapshot for :meth:`p95_since`."""
        return self.predict_seconds.bucket_counts(version=version)

    def p95_since(self, version: str, baseline_counts: list[int]) -> float:
        """predict p95 over ONLY the samples observed since the baseline
        snapshot — a re-staged candidate must be judged on this bake's
        latency, not a previous bake's (lifetime p95 would carry old slow
        samples forever)."""
        current = self.predict_seconds.bucket_counts(version=version)
        delta = [max(0, c - b) for c, b in zip(current, baseline_counts)]
        if sum(delta) == 0:
            return 0.0
        return self.predict_seconds.percentile_from_counts(delta, 0.95)
