"""Content-addressed artifact store + rollout state machine.

Layout under one base directory (default ``$PIO_REGISTRY_DIR``, else
``$PIO_FS_BASEDIR/registry``)::

    <base>/<engine_key>/
        versions/v000001.json     one ModelManifest per published version
        blobs/<sha256>            the artifact bytes, content-addressed
        state.json                RolloutState (stable/candidate/history)

``engine_key`` is a filesystem-safe digest of the engine id (engine ids
may be absolute directory paths). Every write is atomic (tmp file +
``os.replace`` in the same directory) so a crashed publish can never leave
a half-written manifest that a concurrent deploy would trust. Blob reads
re-verify the manifest's sha256 — a truncated or bit-flipped artifact
surfaces as :class:`ArtifactIntegrityError`, never as a pickle of garbage.

GC keeps the last N versions plus anything the rollout state still
references (stable, candidate, previous stable); blobs are deleted only
once no surviving manifest references them (two manifests may share one
blob: re-publishing identical bytes is deduplicated by content address).

The registry is the source of truth for "what serves"; the metadata
store's EngineInstances table remains the training ledger the manifests
point back into (docs/DECISIONS.md).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import logging
import os
import re
import tempfile
import threading
import time
from typing import Any

try:
    import fcntl
except ImportError:  # non-POSIX: in-process serialization only
    fcntl = None  # type: ignore[assignment]

from predictionio_tpu.registry import lease as lease_mod
from predictionio_tpu.registry.manifest import ModelManifest

logger = logging.getLogger(__name__)

MODE_OFF = "off"
MODE_CANARY = "canary"
MODE_SHADOW = "shadow"

_VERSION_RE = re.compile(r"^v(\d{6,})$")
_HISTORY_LIMIT = 50


class ArtifactIntegrityError(RuntimeError):
    """An artifact failed its checksum/length verification — the bytes on
    disk are not the bytes that were published."""


def default_registry_dir() -> str:
    """Resolution order: ``PIO_REGISTRY_DIR``, else ``registry/`` under
    ``PIO_FS_BASEDIR`` (or its ``~/.pio_store`` default)."""
    explicit = os.environ.get("PIO_REGISTRY_DIR")
    if explicit:
        return explicit
    base = os.environ.get(
        "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".pio_store")
    )
    return os.path.join(base, "registry")


@dataclasses.dataclass
class RolloutState:
    """The rollout state machine for one engine.

    ``stable`` serves pinned traffic; ``candidate`` (when set) takes the
    configured canary fraction or shadow traffic while baking. ``history``
    is an append-only (bounded) trail of publish/stage/promote/rollback
    events — the audit log ``pio models show`` prints.
    """

    stable: str = ""
    candidate: str = ""
    mode: str = MODE_OFF  # off | canary | shadow
    fraction: float = 0.0
    previous_stable: str = ""  # rollback target after a promote
    staged_at: str = ""  # when the current candidate was staged
    updated_at: str = ""
    # monotonic change counter, bumped on EVERY persisted transition
    # (publish/stage/promote/unstage/rollback). Fleet replicas poll
    # :meth:`ArtifactStore.state_generation` and reconcile only when it
    # moved — one small-file read instead of a manifest-directory scan.
    generation: int = 0
    history: list[dict[str, Any]] = dataclasses.field(default_factory=list)

    def to_json_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json_dict(cls, data: dict[str, Any]) -> "RolloutState":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


def _atomic_write(path: str, data: bytes) -> None:
    """Write-then-rename in the destination directory: readers see either
    the old complete file or the new complete file, never a prefix."""
    directory = os.path.dirname(path)
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class ArtifactStore:
    """Versioned model artifacts + rollout state for any number of engines.

    Thread-safe within one process (one lock serializes version allocation
    and state transitions) AND across processes: every state transition is
    a read-modify-write held under an advisory ``flock`` on the engine's
    ``state.lock``, because a serving fleet makes every worker a registry
    writer (each runs its own bake gate and candidate breaker — two
    simultaneous transitions must not lose one or collide on the same
    generation number).
    """

    def __init__(self, base_dir: str | None = None):
        self.base_dir = os.path.abspath(base_dir or default_registry_dir())
        self._lock = threading.RLock()
        # reentrancy bookkeeping for the cross-process transition lock
        # (rollback nests unstage); guarded by self._lock
        self._flock_depth: dict[str, int] = {}
        self._flock_fd: dict[str, int] = {}
        # cross-HOST transition lock (lease.py); one mutex per engine,
        # acquired under the flock so same-host processes never contend
        # on it. Guarded by self._lock.
        self._leases: dict[str, "lease_mod.LeaseMutex"] = {}
        # highest rollout generation this store instance has ever read or
        # written, per engine key — the floor state_generation() reports
        # when a concurrent tmp+rename makes the state file momentarily
        # unreadable (a spurious 0 would make every fleet worker reload)
        self._gen_seen: dict[str, int] = {}

    # ------------------------------------------------------------- layout
    @staticmethod
    def engine_key(engine_id: str) -> str:
        """Filesystem-safe directory name for an engine id. Engine ids may
        be absolute paths; keep a readable slug plus a collision-proof
        digest."""
        slug = re.sub(r"[^A-Za-z0-9_-]+", "-", os.path.basename(str(engine_id)))
        slug = slug.strip("-") or "engine"
        digest = hashlib.sha256(str(engine_id).encode("utf-8")).hexdigest()[:10]
        return f"{slug[:40]}-{digest}"

    def _engine_dir(self, engine_id: str) -> str:
        return os.path.join(self.base_dir, self.engine_key(engine_id))

    def _manifest_path(self, engine_id: str, version: str) -> str:
        return os.path.join(self._engine_dir(engine_id), "versions", f"{version}.json")

    def _blob_path(self, engine_id: str, sha256: str) -> str:
        return os.path.join(self._engine_dir(engine_id), "blobs", sha256)

    def _state_path(self, engine_id: str) -> str:
        return os.path.join(self._engine_dir(engine_id), "state.json")

    def _lease_for(self, engine_id: str) -> "lease_mod.LeaseMutex":
        key = self.engine_key(engine_id)
        with self._lock:
            mx = self._leases.get(key)
            if mx is None:
                mx = lease_mod.LeaseMutex(
                    os.path.join(self._engine_dir(engine_id), "state.lease"),
                    ttl_s=lease_mod.lease_ttl_s(),
                )
                self._leases[key] = mx
            return mx

    @contextlib.contextmanager
    def _state_mutex(self, engine_id: str):
        """Cross-process AND cross-host transition lock, held for the
        whole read-modify-write. Two layers:

        - Same-host fast path: an advisory ``flock`` on the engine's
          ``state.lock`` — kernel-speed, zero polling, auto-released on
          holder death. Fleet workers on one box are concurrent registry
          writers (bake gates, breaker rollbacks, the CLI); without
          this, two simultaneous transitions read the same state, one
          write is lost, and both land on the same generation number.
        - Cross-host layer: a lease file with TTL expiry + fencing
          tokens (:mod:`~predictionio_tpu.registry.lease`), acquired
          UNDER the flock so only one process per host ever contends on
          it. ``flock`` is host-bound (and a no-op on many network
          mounts), so a registry on shared storage needs the lease for
          hosts the way it needs the flock for processes.
          ``PIO_REGISTRY_LEASE=0`` disables this layer.

        The in-process RLock (always held around this) serializes
        threads. Reentrant per store (``rollback`` nests ``unstage``) —
        both layers acquire at depth 0 only."""
        key = self.engine_key(engine_id)
        with self._lock:
            depth = self._flock_depth.get(key, 0)
            if depth == 0 and fcntl is not None:
                path = os.path.join(self._engine_dir(engine_id), "state.lock")
                os.makedirs(os.path.dirname(path), exist_ok=True)
                fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
                # blocking acquire: transitions are millisecond-scale
                fcntl.flock(fd, fcntl.LOCK_EX)
                self._flock_fd[key] = fd
            self._flock_depth[key] = depth + 1
        lease_held = False
        try:
            if depth == 0 and lease_mod.lease_enabled():
                mx = self._lease_for(engine_id)
                mx.acquire(timeout_s=max(60.0, 2.0 * mx.ttl_s))
                lease_held = True
            yield
        finally:
            if lease_held:
                try:
                    self._leases[key].release()
                except OSError:
                    logger.exception("lease release failed for %s", key)
            with self._lock:
                self._flock_depth[key] -= 1
                if self._flock_depth[key] == 0:
                    del self._flock_depth[key]
                    fd2 = self._flock_fd.pop(key, None)
                    if fd2 is not None:
                        try:
                            fcntl.flock(fd2, fcntl.LOCK_UN)
                        finally:
                            os.close(fd2)

    def engines(self) -> list[str]:
        """Engine keys present in the registry (directory names; the
        original engine id is recorded in each manifest)."""
        if not os.path.isdir(self.base_dir):
            return []
        return sorted(
            d
            for d in os.listdir(self.base_dir)
            if os.path.isdir(os.path.join(self.base_dir, d, "versions"))
        )

    # ------------------------------------------------------------ versions
    def list_versions(self, engine_id: str) -> list[ModelManifest]:
        return self.versions_by_key(self.engine_key(engine_id))

    def versions_by_key(self, engine_key: str) -> list[ModelManifest]:
        """Same listing keyed by the on-disk directory name (the admin API
        enumerates engines by key; only manifests know the original id)."""
        vdir = os.path.join(self.base_dir, engine_key, "versions")
        if not os.path.isdir(vdir):
            return []
        out: list[ModelManifest] = []
        for name in sorted(os.listdir(vdir)):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(vdir, name), encoding="utf-8") as fh:
                    out.append(ModelManifest.from_json_dict(json.load(fh)))
            except (OSError, ValueError, TypeError):
                logger.warning("unreadable manifest %s (skipped)", name)
        out.sort(key=lambda m: m.version)
        return out

    def get_manifest(self, engine_id: str, version: str) -> ModelManifest | None:
        path = self._manifest_path(engine_id, version)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as fh:
            return ModelManifest.from_json_dict(json.load(fh))

    def _next_version(self, engine_id: str) -> str:
        highest = 0
        for m in self.list_versions(engine_id):
            match = _VERSION_RE.match(m.version)
            if match:
                highest = max(highest, int(match.group(1)))
        return f"v{highest + 1:06d}"

    def publish(
        self,
        manifest: ModelManifest,
        blob: bytes,
        keep_last: int | None = None,
    ) -> ModelManifest:
        """Write the blob (content-addressed) and its manifest atomically;
        assign the next version id if the manifest doesn't carry one. The
        first published version becomes stable automatically — there is
        nothing to canary against yet."""
        with self._lock, self._state_mutex(manifest.engine_id):
            engine_id = manifest.engine_id
            state = self.get_state(engine_id)
            if not manifest.version:
                manifest.version = self._next_version(engine_id)
            if not manifest.created_at:
                manifest.created_at = ModelManifest.now_iso()
            if not manifest.parent_version:
                manifest.parent_version = state.stable
            manifest.blob_sha256 = hashlib.sha256(blob).hexdigest()
            manifest.blob_size = len(blob)
            blob_path = self._blob_path(engine_id, manifest.blob_sha256)
            if not os.path.exists(blob_path):  # dedupe by content address
                _atomic_write(blob_path, blob)
            _atomic_write(
                self._manifest_path(engine_id, manifest.version),
                json.dumps(manifest.to_json_dict(), indent=1).encode("utf-8"),
            )
            self._record(state, "publish", version=manifest.version)
            if not state.stable:
                state.stable = manifest.version
                self._record(state, "auto-stable", version=manifest.version)
            self._save_state(engine_id, state)
            if keep_last:
                self.gc(engine_id, keep_last)
            logger.info(
                "published %s %s (%d bytes, sha %s)",
                self.engine_key(engine_id),
                manifest.version,
                manifest.blob_size,
                manifest.blob_sha256[:12],
            )
            return manifest

    def load_blob(self, engine_id: str, version: str) -> bytes:
        """Read and *verify* one version's artifact bytes."""
        manifest = self.get_manifest(engine_id, version)
        if manifest is None:
            raise ArtifactIntegrityError(
                f"no manifest for version {version!r} of {engine_id!r}"
            )
        path = self._blob_path(engine_id, manifest.blob_sha256)
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError as exc:
            raise ArtifactIntegrityError(
                f"artifact blob missing for {version}: {exc}"
            ) from exc
        if len(blob) != manifest.blob_size:
            raise ArtifactIntegrityError(
                f"artifact {version} length mismatch: manifest says "
                f"{manifest.blob_size} bytes, blob is {len(blob)}"
            )
        digest = hashlib.sha256(blob).hexdigest()
        if digest != manifest.blob_sha256:
            raise ArtifactIntegrityError(
                f"artifact {version} checksum mismatch: manifest says "
                f"{manifest.blob_sha256[:12]}…, blob hashes to {digest[:12]}…"
            )
        return blob

    def attach_ann_index(
        self,
        engine_id: str,
        version: str,
        blob: bytes,
        meta: dict[str, Any],
    ) -> ModelManifest:
        """Pin an ANN index artifact on an existing version: write the
        blob content-addressed into the engine's blob store and record it
        (sha256 + layout metadata) in the version's manifest under
        ``ann_index``. Atomic manifest rewrite under the transition lock —
        a lane loader reads either the manifest without the index or with
        the complete one, never a half-written entry."""
        with self._lock, self._state_mutex(engine_id):
            manifest = self.get_manifest(engine_id, version)
            if manifest is None:
                raise ValueError(f"unknown version {version!r}")
            sha = hashlib.sha256(blob).hexdigest()
            blob_path = self._blob_path(engine_id, sha)
            if not os.path.exists(blob_path):  # dedupe by content address
                _atomic_write(blob_path, blob)
            manifest.ann_index = {
                **meta,
                "sha256": sha,
                "bytes": len(blob),
                "attachedAt": ModelManifest.now_iso(),
            }
            _atomic_write(
                self._manifest_path(engine_id, version),
                json.dumps(manifest.to_json_dict(), indent=1).encode("utf-8"),
            )
            logger.info(
                "ann index attached to %s %s (%d bytes, sha %s)",
                self.engine_key(engine_id),
                version,
                len(blob),
                sha[:12],
            )
            return manifest

    def save_bandit_state(self, engine_id: str, state: dict[str, Any]) -> str:
        """Persist per-arm bandit posterior state in the artifact grammar:
        the canonical JSON payload is written content-addressed into the
        engine's blob store, and ``bandit.json`` becomes an atomically
        replaced pointer ``{sha256, bytes, updatedAt}`` — readers see
        either the previous complete posterior or the new one, never a
        torn write. The superseded blob is unlinked (no manifest ever
        references it, so GC would otherwise never reap it). Returns the
        new content address."""
        with self._lock, self._state_mutex(engine_id):
            blob = json.dumps(state, sort_keys=True).encode("utf-8")
            sha = hashlib.sha256(blob).hexdigest()
            pointer_path = os.path.join(
                self._engine_dir(engine_id), "bandit.json"
            )
            old_sha = ""
            try:
                with open(pointer_path, "rb") as fh:
                    old_sha = str(json.loads(fh.read()).get("sha256", ""))
            except (OSError, ValueError):
                pass
            blob_path = self._blob_path(engine_id, sha)
            if not os.path.exists(blob_path):  # dedupe by content address
                _atomic_write(blob_path, blob)
            _atomic_write(
                pointer_path,
                json.dumps(
                    {
                        "sha256": sha,
                        "bytes": len(blob),
                        "updatedAt": ModelManifest.now_iso(),
                    },
                    indent=1,
                ).encode("utf-8"),
            )
            if old_sha and old_sha != sha:
                try:
                    os.unlink(self._blob_path(engine_id, old_sha))
                except OSError:
                    pass
            return sha

    def load_bandit_state(self, engine_id: str) -> dict[str, Any] | None:
        """Read the bandit posterior back through its pointer; a missing,
        torn, or digest-mismatched artifact reads as None (the bandit
        restarts with fresh priors rather than trusting corrupt reward
        history)."""
        pointer_path = os.path.join(self._engine_dir(engine_id), "bandit.json")
        try:
            with open(pointer_path, "rb") as fh:
                pointer = json.loads(fh.read().decode("utf-8"))
            sha = str(pointer["sha256"])
            with open(self._blob_path(engine_id, sha), "rb") as fh:
                blob = fh.read()
            if hashlib.sha256(blob).hexdigest() != sha:
                return None
            return json.loads(blob.decode("utf-8"))
        except (OSError, ValueError, KeyError):
            return None

    def attach_eval_evidence(
        self, engine_id: str, version: str, evidence: dict[str, Any]
    ) -> ModelManifest:
        """Record an evaluation grid's evidence block on an existing
        version's manifest (the winning refit of a ``pio eval`` search —
        docs/evaluation.md). Atomic manifest rewrite under the transition
        lock, the ``attach_ann_index`` idiom: a lane loader reads either
        the manifest without the evidence or with the complete block,
        never a torn one."""
        with self._lock, self._state_mutex(engine_id):
            manifest = self.get_manifest(engine_id, version)
            if manifest is None:
                raise ValueError(f"unknown version {version!r}")
            manifest.eval_evidence = dict(evidence)
            _atomic_write(
                self._manifest_path(engine_id, version),
                json.dumps(manifest.to_json_dict(), indent=1).encode("utf-8"),
            )
            logger.info(
                "eval evidence attached to %s %s (metric %s, best %s)",
                self.engine_key(engine_id),
                version,
                evidence.get("metric"),
                evidence.get("bestScore"),
            )
            return manifest

    def load_ann_blob(
        self, engine_id: str, version: str
    ) -> tuple[bytes, dict[str, Any]] | None:
        """Read and *verify* the version's ANN index artifact. None when
        the version pins no index; :class:`ArtifactIntegrityError` when it
        does but the bytes on disk are not the bytes that were attached."""
        manifest = self.get_manifest(engine_id, version)
        if manifest is None or not manifest.ann_index:
            return None
        meta = manifest.ann_index
        sha = meta.get("sha256", "")
        path = self._blob_path(engine_id, sha)
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError as exc:
            raise ArtifactIntegrityError(
                f"ann index blob missing for {version}: {exc}"
            ) from exc
        if len(blob) != int(meta.get("bytes", -1)):
            raise ArtifactIntegrityError(
                f"ann index for {version} length mismatch: manifest says "
                f"{meta.get('bytes')} bytes, blob is {len(blob)}"
            )
        digest = hashlib.sha256(blob).hexdigest()
        if digest != sha:
            raise ArtifactIntegrityError(
                f"ann index for {version} checksum mismatch: manifest says "
                f"{sha[:12]}…, blob hashes to {digest[:12]}…"
            )
        return blob, meta

    @staticmethod
    def _blob_shas_of(manifest: ModelManifest) -> set[str]:
        """Every blob a manifest pins: the model artifact plus its ANN
        index (GC must treat both as referenced)."""
        shas = {manifest.blob_sha256}
        ann_sha = (manifest.ann_index or {}).get("sha256")
        if ann_sha:
            shas.add(ann_sha)
        return shas - {""}

    def gc(self, engine_id: str, keep_last: int) -> list[str]:
        """Drop all but the newest ``keep_last`` versions, never dropping
        a version the rollout state still references. Returns the removed
        version ids."""
        with self._lock, self._state_mutex(engine_id):
            state = self.get_state(engine_id)
            pinned = {state.stable, state.candidate, state.previous_stable} - {""}
            versions = self.list_versions(engine_id)
            # keep = newest N plus everything pinned — pins must not eat
            # into the newest-N budget, or a publish with pinned count >=
            # keep_last would delete the very version it just wrote
            keep = {m.version for m in versions[-max(1, keep_last):]} | pinned
            removed: list[str] = []
            for m in versions:
                if m.version in keep:
                    continue
                try:
                    os.unlink(self._manifest_path(engine_id, m.version))
                except OSError:
                    continue
                removed.append(m.version)
            if removed:
                # delete blobs no surviving manifest references (model
                # artifacts AND ann index artifacts both count)
                live_shas: set[str] = set()
                for m in self.list_versions(engine_id):
                    live_shas |= self._blob_shas_of(m)
                for m in versions:
                    if m.version not in removed:
                        continue
                    for sha in self._blob_shas_of(m) - live_shas:
                        try:
                            os.unlink(self._blob_path(engine_id, sha))
                        except OSError:
                            pass
                logger.info(
                    "gc %s: removed %s", self.engine_key(engine_id), removed
                )
            return removed

    # --------------------------------------------------------------- state
    def get_state(self, engine_id: str) -> RolloutState:
        return self.state_by_key(self.engine_key(engine_id))

    def state_by_key(self, engine_key: str) -> RolloutState:
        """Unlocked read of the persisted rollout state. A concurrent
        writer is mid-``tmp+rename`` at any moment, so a torn or
        momentarily-missing read is expected operation, not corruption:
        retry once after a beat before concluding anything. Only a state
        file that stays unreadable is treated as fresh."""
        path = os.path.join(self.base_dir, engine_key, "state.json")
        for attempt in (0, 1):
            try:
                with open(path, encoding="utf-8") as fh:
                    state = RolloutState.from_json_dict(json.load(fh))
                with self._lock:
                    if state.generation > self._gen_seen.get(engine_key, 0):
                        self._gen_seen[engine_key] = state.generation
                return state
            except FileNotFoundError:
                # genuinely absent (fresh engine) unless this store has
                # already seen state here — then it's the rename window
                with self._lock:
                    seen = self._gen_seen.get(engine_key, 0)
                if not seen or attempt:
                    return RolloutState()
            except (OSError, ValueError, TypeError):
                if attempt:
                    logger.warning(
                        "unreadable rollout state for %s; starting fresh",
                        engine_key,
                    )
                    return RolloutState()
            time.sleep(0.01)
        return RolloutState()

    def state_generation(self, engine_id: str) -> int:
        """Cheap monotonic change detector for cross-process coordination:
        the ``generation`` counter of the persisted rollout state (0 when
        no state exists yet). One state-file read — callers poll this and
        only pay :meth:`get_state` + reconciliation when it moved.

        Never goes backwards within a store instance: when a concurrent
        transition makes the file momentarily unreadable, the last
        generation this store saw is the answer — a spurious 0 here
        would make every fleet worker's sync loop reload at once."""
        key = self.engine_key(engine_id)
        gen = self.state_by_key(key).generation
        with self._lock:
            floor = self._gen_seen.get(key, 0)
            if gen >= floor:
                self._gen_seen[key] = gen
                return gen
            return floor

    def _save_state(self, engine_id: str, state: RolloutState) -> None:
        key = self.engine_key(engine_id)
        with self._lock:
            mx = self._leases.get(key)
        if mx is not None and mx.held:
            # fencing: a holder whose lease expired mid-transition must
            # NOT persist — a newer token exists and its owner may have
            # already written. Raises LeaseLostError before any mutation.
            mx.verify()
        state.updated_at = ModelManifest.now_iso()
        state.generation += 1
        state.history = state.history[-_HISTORY_LIMIT:]
        _atomic_write(
            self._state_path(engine_id),
            json.dumps(state.to_json_dict(), indent=1).encode("utf-8"),
        )
        with self._lock:
            if state.generation > self._gen_seen.get(key, 0):
                self._gen_seen[key] = state.generation

    @staticmethod
    def _record(state: RolloutState, action: str, **fields: Any) -> None:
        state.history.append(
            {"at": ModelManifest.now_iso(), "action": action, **fields}
        )

    def stage_candidate(
        self,
        engine_id: str,
        version: str,
        mode: str = MODE_CANARY,
        fraction: float = 0.1,
    ) -> RolloutState:
        """Begin a progressive rollout: ``version`` starts taking the
        canary fraction (or shadow traffic) next to the pinned stable."""
        if mode not in (MODE_CANARY, MODE_SHADOW):
            raise ValueError(f"mode must be canary|shadow, got {mode!r}")
        if self.get_manifest(engine_id, version) is None:
            raise ValueError(f"unknown version {version!r}")
        with self._lock, self._state_mutex(engine_id):
            state = self.get_state(engine_id)
            if version == state.stable:
                raise ValueError(f"{version} is already stable")
            state.candidate = version
            state.mode = mode
            state.fraction = max(0.0, min(1.0, float(fraction)))
            state.staged_at = ModelManifest.now_iso()
            self._record(
                state, "stage", version=version, mode=mode, fraction=state.fraction
            )
            self._save_state(engine_id, state)
            return state

    def promote(self, engine_id: str, version: str | None = None) -> RolloutState:
        """Candidate (or an explicit version) becomes stable; the old
        stable is retained as the rollback target."""
        with self._lock, self._state_mutex(engine_id):
            state = self.get_state(engine_id)
            target = version or state.candidate
            if not target:
                raise ValueError("nothing to promote: no candidate staged")
            if self.get_manifest(engine_id, target) is None:
                raise ValueError(f"unknown version {target!r}")
            if target == state.stable:
                raise ValueError(f"{target} is already stable")
            state.previous_stable = state.stable
            state.stable = target
            if state.candidate and state.candidate != target:
                # promoting PAST a staged candidate obsoletes that rollout:
                # leaving it staged would report a canary no server is
                # baking and pin the orphan against GC forever
                self._record(
                    state, "unstage", version=state.candidate, reason="superseded"
                )
            state.candidate = ""
            state.mode = MODE_OFF
            state.fraction = 0.0
            self._record(
                state, "promote", version=target, from_=state.previous_stable
            )
            self._save_state(engine_id, state)
            return state

    def unstage(self, engine_id: str, reason: str = "") -> RolloutState:
        """Drop a staged candidate ONLY — the stable pin is never touched.
        A no-op when nothing is staged. This is the serving-side rollback
        primitive: the server must not inherit :meth:`rollback`'s
        previous-stable revert, or a breaker trip after a swallowed stage
        write would silently flip the registry to an older model than the
        one actually serving."""
        with self._lock, self._state_mutex(engine_id):
            state = self.get_state(engine_id)
            if state.candidate:
                dropped = state.candidate
                state.candidate = ""
                state.mode = MODE_OFF
                state.fraction = 0.0
                self._record(state, "rollback", version=dropped, reason=reason)
                self._save_state(engine_id, state)
            return state

    def rollback(self, engine_id: str, reason: str = "manual") -> RolloutState:
        """Back out: drop a staged candidate if one exists, else revert
        stable to the previous stable (post-promote regret)."""
        with self._lock, self._state_mutex(engine_id):
            state = self.get_state(engine_id)
            if state.candidate:
                return self.unstage(engine_id, reason=reason)
            if state.previous_stable:
                reverted_from = state.stable
                state.stable = state.previous_stable
                state.previous_stable = ""
                self._record(
                    state,
                    "rollback",
                    version=reverted_from,
                    to=state.stable,
                    reason=reason,
                )
            else:
                raise ValueError(
                    "nothing to roll back: no candidate staged and no "
                    "previous stable recorded"
                )
            self._save_state(engine_id, state)
            return state
