"""The shared "rollout active" probe.

Two control loops must never act while a candidate bakes: the
autoscaler must not resize (PR 12) and the lifecycle controller must not
launch a retune grid (PR 19). Both defer on the SAME question — is any
engine's rollout mode != off — and a private copy in each would let the
definitions drift (e.g. one learning about shadow mode, the other not).
This is the one home; fleet/autoscaler re-exports it for compatibility."""

from __future__ import annotations

from typing import Callable


def registry_rollout_probe(registry_dir: str) -> Callable[[], bool]:
    """True while ANY engine's rollout is mid-bake (mode != off) — the
    never-act-mid-bake input, read from the same registry the fleet
    coordinates through. Raises on an unreadable registry: callers must
    not act on unknown rollout state (their tick loops count the error
    and retry)."""
    from predictionio_tpu.registry.store import ArtifactStore

    store = ArtifactStore(registry_dir)

    def probe() -> bool:
        return any(
            store.state_by_key(key).mode != "off" for key in store.engines()
        )

    return probe


__all__ = ["registry_rollout_probe"]
