"""Version-keyed serving result cache.

PR 6's waterfall proved the query hot path is host glue, not compute; the
cheapest request is the one that never enters the micro-batch queue. This
LRU answers repeat queries in microseconds, keyed on
``(model_version, canonical_query_bytes)`` — the model version IS the
cache epoch:

- **Version-keyed, not time-keyed.** Registry artifacts are immutable and
  content-addressed (docs/model_registry.md): the same version answers a
  given query the same way forever, so an entry can never go stale by
  *model* change — a swap changes the lookup version and old entries
  simply stop being addressable. The TTL exists only for serving
  components that read live state outside the model (a FilterServing
  disabled-items file, the e-commerce constraint entities): their edits
  are visible within ``ttl_s`` at worst.
- **Stable lane only, quiesced rollouts only.** The query server bypasses
  the cache entirely while a rollout is active: canary users must
  exercise the candidate for the bake gates to mean anything, shadow mode
  needs dispatched stable answers to sample, and a cached canary answer
  outliving a rollback is exactly the stale-lane hazard the rollout
  machinery exists to prevent. Because candidate answers are never
  cached, "a canary answer served from a stale lane" is impossible by
  construction; the swap/rollback/promote paths additionally flush the
  affected version's entries (see QueryServer) so nothing lingers.
- **Hot-path cheap.** One small lock around an OrderedDict move-to-end;
  the serialized response text is memoized per entry on first hit, so a
  hit's respond phase is a prebuilt-string write.

Metrics are owned by the caller (the server wires pio_cache_* counters to
:meth:`stats`); this module stays import-light so tools can use it.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable


class _Entry:
    __slots__ = ("body", "text", "version", "expires_at")

    def __init__(self, body: Any, version: str, expires_at: float):
        self.body = body
        self.text: str | None = None  # serialized response, memoized on hit
        self.version = version
        self.expires_at = expires_at


class ResultCache:
    """Bounded LRU of encoded prediction bodies keyed on
    ``(model_version, canonical_query_bytes)``.

    ``max_entries <= 0`` disables every operation (the server treats a
    disabled cache as absent). ``ttl_s <= 0`` means entries live until
    evicted or invalidated.
    """

    def __init__(
        self,
        max_entries: int = 1024,
        ttl_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_entries = int(max_entries)
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple[str, bytes], _Entry] = OrderedDict()
        # monotonic counters, surfaced as pio_cache_*_total by the server
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, version: str, key: bytes) -> _Entry | None:
        """The pre-admission lookup. Counts a hit or miss; an expired
        entry is dropped and counted as a miss."""
        if self.max_entries <= 0:
            return None
        k = (version, key)
        with self._lock:
            entry = self._entries.get(k)
            if entry is not None and (
                self.ttl_s > 0 and entry.expires_at < self._clock()
            ):
                del self._entries[k]
                self.evictions += 1
                entry = None
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(k)
            self.hits += 1
            return entry

    def put(self, version: str, key: bytes, body: Any) -> None:
        if self.max_entries <= 0:
            return
        entry = _Entry(
            body, version, self._clock() + self.ttl_s if self.ttl_s > 0 else 0.0
        )
        with self._lock:
            self._entries[(version, key)] = entry
            self._entries.move_to_end((version, key))
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def flush_version(self, version: str) -> int:
        """Invalidate every entry of one model version (the swap /
        rollback / promote hook). Returns how many entries were dropped;
        the drop is counted as invalidations, not evictions."""
        with self._lock:
            doomed = [k for k in self._entries if k[0] == version]
            for k in doomed:
                del self._entries[k]
            self.invalidations += len(doomed)
            return len(doomed)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.invalidations += n
            return n

    def stats(self) -> dict[str, float]:
        with self._lock:
            return {
                "hits": float(self.hits),
                "misses": float(self.misses),
                "evictions": float(self.evictions),
                "invalidations": float(self.invalidations),
                "entries": float(len(self._entries)),
            }

    @property
    def hit_ratio(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return (self.hits / total) if total else 0.0
