// Native columnar JSONL event scanner.
//
// Role: the bulk-ingest hot path of training feeds — the predictionio_tpu
// analog of the reference's JVM-side storage scan layer (JdbcRDD /
// TableInputFormat partitions feeding Spark). Scans an events JSONL file
// (one wire-format event object per line), filters by event name, and
// dictionary-encodes entity/target ids into dense int32 columns with a
// float32 rating column — the exact layout `PEvents.to_columnar` produces —
// at C++ speed, without materializing Python objects per row.
//
// Exposed C ABI (ctypes):
//   pio_scan_file(path, event_names_csv, rating_key) -> handle
//   accessor functions to copy out columns / vocabularies
//   pio_scan_free(handle)
//
// The parser is specialized for the event wire format: a flat JSON object
// whose relevant keys ("event", "entityId", "targetEntityId", "eventTime",
// "properties") sit at the top level. It handles string escapes and nested
// objects/arrays inside "properties" correctly by brace matching with
// string-state tracking.

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <new>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Columns {
  std::vector<int32_t> entity_ids;
  std::vector<int32_t> target_ids;
  std::vector<int32_t> event_codes;
  std::vector<double> timestamps;
  std::vector<float> ratings;
  std::vector<std::string> entity_vocab;
  std::vector<std::string> target_vocab;
  std::vector<std::string> event_vocab;
  std::vector<std::string> row_ids;  // per-row event id ("" when absent)
  std::string error;
};

// Raw parsed row, interned against full (pre-compaction) vocabularies.
struct RawRow {
  int32_t entity;
  int32_t target;  // -1 = absent
  int32_t event;
  double ts;
  float rating;
  bool passes;  // filter verdict of the LATEST version of this row
  std::string id;
};

// --- minimal JSON helpers (specialized, no external deps) -----------------

// Find the value start for "key" at the TOP level of the object starting at
// `line`. Returns nullptr if absent. `end` bounds the scan (nullptr = until
// NUL), enabling lookups scoped to a nested object's extent.
const char* find_top_level_value(const char* line, const char* key,
                                 const char* end = nullptr) {
  size_t keylen = strlen(key);
  int depth = 0;
  bool in_str = false;
  const char* p = line;
  while (*p && (!end || p < end)) {
    char c = *p;
    if (in_str) {
      if (c == '\\' && p[1]) { p += 2; continue; }
      if (c == '"') in_str = false;
      ++p;
      continue;
    }
    switch (c) {
      case '"': {
        if (depth == 1) {
          // possible key
          const char* kstart = p + 1;
          const char* q = kstart;
          bool esc = false;
          while (*q && (esc || *q != '"')) { esc = (!esc && *q == '\\'); ++q; }
          if (*q == '"') {
            size_t klen = q - kstart;
            const char* after = q + 1;
            while (*after == ' ' || *after == '\t') ++after;
            if (*after == ':' && klen == keylen && strncmp(kstart, key, keylen) == 0) {
              ++after;
              while (*after == ' ' || *after == '\t') ++after;
              return after;
            }
            p = q + 1;
            continue;
          }
        }
        in_str = true;
        ++p;
        continue;
      }
      case '{': case '[': ++depth; break;
      case '}': case ']': --depth; break;
      default: break;
    }
    ++p;
  }
  return nullptr;
}

// Return the pointer one past the matching close of the object/array at `p`
// (which must point at '{' or '['), or nullptr on malformed input.
const char* object_end(const char* p) {
  if (*p != '{' && *p != '[') return nullptr;
  int depth = 0;
  bool in_str = false;
  while (*p) {
    char c = *p;
    if (in_str) {
      if (c == '\\' && p[1]) { p += 2; continue; }
      if (c == '"') in_str = false;
    } else if (c == '"') {
      in_str = true;
    } else if (c == '{' || c == '[') {
      ++depth;
    } else if (c == '}' || c == ']') {
      --depth;
      if (depth == 0) return p + 1;
    }
    ++p;
  }
  return nullptr;
}

// 4 hex digits at p -> value, or -1 when invalid/truncated (also the
// bounds check: a NUL inside the window fails the digit test, so a line
// ending mid-escape can never walk the cursor past the buffer).
int hex4(const char* p) {
  int v = 0;
  for (int i = 0; i < 4; ++i) {
    char c = p[i];
    int d;
    if (c >= '0' && c <= '9') d = c - '0';
    else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
    else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
    else return -1;
    v = (v << 4) | d;
  }
  return v;
}

void append_utf8(std::string* out, uint32_t cp) {
  if (cp < 0x80) {
    out->push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out->push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

// Parse a JSON string value at `p` into out; returns true on success.
// \uXXXX escapes are DECODED to UTF-8 (incl. surrogate pairs): the JSONL
// writer uses json.dumps' default ensure_ascii=True, so every non-ASCII id
// is stored escaped, and the python read path (json.loads) decodes it —
// keeping the escape verbatim made the two scan paths intern different
// vocab strings for the same id. Lone surrogates fail the parse (treated
// as a malformed value, like any truncated escape).
bool parse_string(const char* p, std::string* out) {
  if (*p != '"') return false;
  ++p;
  out->clear();
  while (*p && *p != '"') {
    if (*p == '\\' && p[1]) {
      ++p;
      switch (*p) {
        case 'n': out->push_back('\n'); break;
        case 't': out->push_back('\t'); break;
        case 'r': out->push_back('\r'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'u': {
          int v = hex4(p + 1);
          if (v < 0) return false;
          uint32_t cp = static_cast<uint32_t>(v);
          p += 4;  // at the last hex digit; the trailing ++p advances past
          if (cp >= 0xD800 && cp <= 0xDBFF) {
            // high surrogate: a \uXXXX low surrogate must follow
            if (p[1] != '\\' || p[2] != 'u') return false;
            int lo = hex4(p + 3);
            if (lo < 0xDC00 || lo > 0xDFFF) return false;
            cp = 0x10000 + ((cp - 0xD800) << 10) +
                 (static_cast<uint32_t>(lo) - 0xDC00);
            p += 6;
          } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
            return false;  // lone low surrogate
          }
          append_utf8(out, cp);
          break;
        }
        default: out->push_back(*p); break;
      }
      ++p;
    } else {
      out->push_back(*p);
      ++p;
    }
  }
  return *p == '"';
}

// ISO8601 -> epoch seconds (UTC). Handles "YYYY-MM-DDTHH:MM:SS(.mmm)?(Z|+HH:MM)".
double parse_iso8601(const std::string& s) {
  int y, mo, d, h, mi;
  double sec = 0;
  if (s.size() < 19) return 0.0;
  if (sscanf(s.c_str(), "%d-%d-%dT%d:%d:%lf", &y, &mo, &d, &h, &mi, &sec) != 6)
    return 0.0;
  // days since epoch (civil algorithm)
  int yy = y - (mo <= 2);
  int era = (yy >= 0 ? yy : yy - 399) / 400;
  unsigned yoe = static_cast<unsigned>(yy - era * 400);
  unsigned doy = (153 * (mo + (mo > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  long days = era * 146097L + static_cast<long>(doe) - 719468L;
  double ts = days * 86400.0 + h * 3600.0 + mi * 60.0 + sec;
  // timezone suffix: "+HH:MM", compact "+HHMM", or bare "+HH" — python's
  // fromisoformat accepts all three, so the native parse must agree (the
  // %d:%d sscanf read "+0530" as 530 HOURS)
  size_t zpos = s.find_last_of("Z+-");
  if (zpos != std::string::npos && zpos >= 19 && s[zpos] != 'Z') {
    const char* z = s.c_str() + zpos + 1;
    int oh = 0, om = 0, osec = 0;
    if (strchr(z, ':')) {
      // colon form must be exactly HH:MM or HH:MM:SS (2-digit fields) —
      // fromisoformat rejects "+5:30"/"+05:3", and sscanf would happily
      // parse them; agree with the python path (malformed row)
      size_t zlen = strlen(z);
      if (zlen != 5 && zlen != 8) return 0.0;
      for (size_t i = 0; i < zlen; ++i) {
        bool want_colon = (i == 2 || i == 5);
        if (want_colon ? z[i] != ':'
                       : !isdigit(static_cast<unsigned char>(z[i])))
          return 0.0;
      }
      sscanf(z, "%d:%d:%d", &oh, &om, &osec);  // ":SS" optional
    } else {
      // compact form must be exactly HH, HHMM or HHMMSS, all digits —
      // python's fromisoformat accepts those three and rejects e.g.
      // "+530", which atoi would otherwise read as 530 HOURS; agree with
      // the python path by treating anything else as a malformed row
      size_t zlen = strlen(z);
      if (zlen != 2 && zlen != 4 && zlen != 6) return 0.0;
      for (size_t i = 0; i < zlen; ++i)
        if (!isdigit(static_cast<unsigned char>(z[i]))) return 0.0;
      long v = atol(z);
      if (zlen == 6) { oh = v / 10000; om = (v / 100) % 100; osec = v % 100; }
      else if (zlen == 4) { oh = v / 100; om = v % 100; }
      else oh = v;
    }
    double off = oh * 3600.0 + om * 60.0 + osec;
    ts += (s[zpos] == '-') ? off : -off;
  }
  return ts;
}

int32_t encode(const std::string& v,
               std::unordered_map<std::string, int32_t>* index,
               std::vector<std::string>* vocab) {
  auto it = index->find(v);
  if (it != index->end()) return it->second;
  int32_t id = static_cast<int32_t>(vocab->size());
  index->emplace(v, id);
  vocab->push_back(v);
  return id;
}

// Dense-matrix cooccurrence accumulation + top-N select, shared by the
// uint16 (user count < 65535, half the cache traffic) and int32 widths.
// Input contract and output layout documented at pio_cooccur_topn below.
template <typename CT>
static int32_t cooccur_accumulate(const int32_t* __restrict users,
                                  const int32_t* __restrict items,
                                  int64_t nnz, int32_t n_items, int32_t top_n,
                                  int32_t* __restrict out_items,
                                  int32_t* __restrict out_counts) {
  // calloc, not a zero-filled vector: the kernel hands back zero pages
  // without touching ~27-54MB (ML-1M vocab) up front — first-touch
  // faults amortize into the accumulation pass
  CT* C = static_cast<CT*>(
      calloc(static_cast<size_t>(n_items) * n_items, sizeof(CT)));
  if (C == nullptr) return 3;
  int64_t pos = 0;
  while (pos < nnz) {
    const int32_t u = users[pos];
    int64_t end = pos;
    while (end < nnz && users[end] == u) ++end;
    for (int64_t a = pos; a < end; ++a) {
      CT* __restrict row = C + static_cast<size_t>(items[a]) * n_items;
      for (int64_t b = pos; b < end; ++b) row[items[b]]++;
    }
    pos = end;
  }
  // zero the diagonal (item self-count) once so the hot select loop below
  // needs no per-iteration j==i test
  for (int32_t i = 0; i < n_items; ++i)
    C[static_cast<size_t>(i) * n_items + i] = 0;
  for (int32_t i = 0; i < n_items; ++i) {
    const CT* row = C + static_cast<size_t>(i) * n_items;
    int32_t* oi = out_items + static_cast<size_t>(i) * top_n;
    int32_t* oc = out_counts + static_cast<size_t>(i) * top_n;
    for (int32_t k = 0; k < top_n; ++k) { oi[k] = -1; oc[k] = 0; }
    int32_t filled = 0;
    for (int32_t j = 0; j < n_items; ++j) {
      const int32_t c = static_cast<int32_t>(row[j]);
      if (c <= 0) continue;
      // scanning j ascending + strict comparisons keep equal counts in
      // item-ascending order (the lexsort tie-break)
      if (filled == top_n && c <= oc[top_n - 1]) continue;
      int32_t k = (filled < top_n) ? filled : top_n - 1;
      while (k > 0 && oc[k - 1] < c) {
        oc[k] = oc[k - 1];
        oi[k] = oi[k - 1];
        --k;
      }
      oc[k] = c;
      oi[k] = j;
      if (filled < top_n) ++filled;
    }
  }
  free(C);
  return 0;
}

}  // namespace

extern "C" {

void* pio_scan_file(const char* path, const char* event_names_csv,
                    const char* rating_key, const char* entity_type,
                    const char* target_entity_type) {
  auto* cols = new Columns();
  FILE* f = fopen(path, "rb");
  if (!f) {
    cols->error = "cannot open file";
    return cols;
  }
  // parse event-name filter
  std::unordered_map<std::string, bool> allowed;
  bool filter = event_names_csv && *event_names_csv;
  if (filter) {
    std::string csv(event_names_csv), cur;
    for (char c : csv) {
      if (c == ',') { if (!cur.empty()) allowed[cur] = true; cur.clear(); }
      else cur.push_back(c);
    }
    if (!cur.empty()) allowed[cur] = true;
  }
  // Pass 1: parse EVERY line into raw rows interned against full vocabs;
  // dedup by event id (later line wins, even if the later version fails the
  // filter — matching the backend's upsert-then-filter semantics).
  std::vector<RawRow> rows;
  std::vector<std::string> full_ent, full_tgt, full_ev;
  std::unordered_map<std::string, int32_t> ent_index, tgt_index, ev_index;
  std::unordered_map<std::string, size_t> row_by_id;
  char* line = nullptr;
  size_t cap = 0;
  ssize_t len;
  std::string sval;
  while ((len = getline(&line, &cap, f)) != -1) {
    if (len == 0 || line[0] != '{') continue;
    const char* ev = find_top_level_value(line, "event");
    if (!ev || !parse_string(ev, &sval)) continue;
    std::string event_name = sval;
    const char* ent = find_top_level_value(line, "entityId");
    if (!ent || !parse_string(ent, &sval)) continue;
    std::string entity = sval;

    RawRow row;
    row.passes = !filter || allowed.find(event_name) != allowed.end();
    if (row.passes && entity_type && *entity_type) {
      const char* et = find_top_level_value(line, "entityType");
      row.passes = et && parse_string(et, &sval) && sval == entity_type;
    }
    if (row.passes && target_entity_type && *target_entity_type) {
      const char* tt = find_top_level_value(line, "targetEntityType");
      row.passes = tt && parse_string(tt, &sval) && sval == target_entity_type;
    }
    std::string target;
    bool has_target = false;
    const char* tgt = find_top_level_value(line, "targetEntityId");
    if (tgt && parse_string(tgt, &sval)) { target = sval; has_target = true; }
    row.ts = 0.0;
    const char* t = find_top_level_value(line, "eventTime");
    if (t && parse_string(t, &sval)) row.ts = parse_iso8601(sval);
    // rating: top-level key of the properties OBJECT only (bounded scan)
    row.rating = __builtin_nanf("");
    const char* props = find_top_level_value(line, "properties");
    if (props && *props == '{') {
      const char* pend = object_end(props);
      const char* rv = pend ? find_top_level_value(
          props, rating_key ? rating_key : "rating", pend) : nullptr;
      if (rv) {
        char* endp = nullptr;
        double v = strtod(rv, &endp);
        if (endp != rv) row.rating = static_cast<float>(v);
      }
    }
    const char* eid = find_top_level_value(line, "eventId");
    row.id = (eid && parse_string(eid, &sval)) ? sval : "";

    row.event = encode(event_name, &ev_index, &full_ev);
    row.entity = encode(entity, &ent_index, &full_ent);
    row.target = has_target ? encode(target, &tgt_index, &full_tgt) : -1;

    // id-less rows share the "" key on purpose: the backend's dedup map is
    // keyed on `event_id or ""`, so every id-less line collapses into one
    // last-wins record there — the native path must produce the same row set
    auto it = row_by_id.find(row.id);
    if (it != row_by_id.end()) {
      rows[it->second] = std::move(row);  // upsert in place
      continue;
    }
    row_by_id.emplace(row.id, rows.size());
    rows.push_back(std::move(row));
  }
  free(line);
  fclose(f);

  // Pass 2: keep filter-passing rows, stable-sort by eventTime (matching
  // the python path, which reads via time-ordered find), and re-encode
  // vocabularies in first-use order of the OUTPUT rows for exact parity.
  std::vector<size_t> order;
  order.reserve(rows.size());
  for (size_t i = 0; i < rows.size(); ++i)
    if (rows[i].passes) order.push_back(i);
  std::stable_sort(order.begin(), order.end(), [&](size_t a, size_t b) {
    return rows[a].ts < rows[b].ts;
  });
  std::vector<int32_t> ent_map(full_ent.size(), -1),
      tgt_map(full_tgt.size(), -1), ev_map(full_ev.size(), -1);
  cols->entity_ids.reserve(order.size());
  for (size_t i : order) {
    const RawRow& r = rows[i];
    int32_t& em = ent_map[r.entity];
    if (em < 0) {
      em = static_cast<int32_t>(cols->entity_vocab.size());
      cols->entity_vocab.push_back(full_ent[r.entity]);
    }
    int32_t tm = -1;
    if (r.target >= 0) {
      int32_t& slot = tgt_map[r.target];
      if (slot < 0) {
        slot = static_cast<int32_t>(cols->target_vocab.size());
        cols->target_vocab.push_back(full_tgt[r.target]);
      }
      tm = slot;
    }
    int32_t& vm = ev_map[r.event];
    if (vm < 0) {
      vm = static_cast<int32_t>(cols->event_vocab.size());
      cols->event_vocab.push_back(full_ev[r.event]);
    }
    cols->entity_ids.push_back(em);
    cols->target_ids.push_back(tm);
    cols->event_codes.push_back(vm);
    cols->timestamps.push_back(r.ts);
    cols->ratings.push_back(r.rating);
    cols->row_ids.push_back(r.id);
  }
  return cols;
}

int64_t pio_scan_num_rows(void* h) {
  return static_cast<Columns*>(h)->entity_ids.size();
}
const char* pio_scan_error(void* h) {
  return static_cast<Columns*>(h)->error.c_str();
}
void pio_scan_copy_int32(void* h, int which, int32_t* out) {
  auto* c = static_cast<Columns*>(h);
  const std::vector<int32_t>* src =
      which == 0 ? &c->entity_ids : which == 1 ? &c->target_ids : &c->event_codes;
  memcpy(out, src->data(), src->size() * sizeof(int32_t));
}
void pio_scan_copy_f64(void* h, double* out) {
  auto* c = static_cast<Columns*>(h);
  memcpy(out, c->timestamps.data(), c->timestamps.size() * sizeof(double));
}
void pio_scan_copy_f32(void* h, float* out) {
  auto* c = static_cast<Columns*>(h);
  memcpy(out, c->ratings.data(), c->ratings.size() * sizeof(float));
}
int64_t pio_scan_vocab_size(void* h, int which) {
  auto* c = static_cast<Columns*>(h);
  const std::vector<std::string>* v =
      which == 0 ? &c->entity_vocab : which == 1 ? &c->target_vocab : &c->event_vocab;
  return v->size();
}
const char* pio_scan_vocab_get(void* h, int which, int64_t i) {
  auto* c = static_cast<Columns*>(h);
  const std::vector<std::string>* v =
      which == 0 ? &c->entity_vocab : which == 1 ? &c->target_vocab : &c->event_vocab;
  return (*v)[i].c_str();
}
const char* pio_scan_row_id(void* h, int64_t i) {
  return static_cast<Columns*>(h)->row_ids[i].c_str();
}
// Batched row-id export: one FFI call for lengths, one for the concatenated
// bytes (a pio_scan_row_id call + decode PER ROW was a 20M-iteration python
// loop that rivaled the whole C++ scan). Length-prefixing is separator-free,
// so ids may contain any byte.
int64_t pio_scan_ids_total_bytes(void* h) {
  auto* c = static_cast<Columns*>(h);
  int64_t total = 0;
  for (const auto& s : c->row_ids) total += static_cast<int64_t>(s.size());
  return total;
}
void pio_scan_copy_ids(void* h, int32_t* lengths, char* buf) {
  auto* c = static_cast<Columns*>(h);
  char* out = buf;
  for (size_t i = 0; i < c->row_ids.size(); ++i) {
    const std::string& s = c->row_ids[i];
    lengths[i] = static_cast<int32_t>(s.size());
    memcpy(out, s.data(), s.size());
    out += s.size();
  }
}
void pio_scan_free(void* h) { delete static_cast<Columns*>(h); }

// --- COO group-by for the ALS train feed ----------------------------------
//
// Stable counting sort of a COO rating list by entity id: the host half of
// the ALS ingest pipeline (ops/als.py). Replaces numpy's O(n log n)
// single-threaded argsort + fancy-indexing block packing (measured 12.1s at
// ML-20M on the bench host) with one O(n) histogram pass + one O(n) scatter
// pass over native arrays. The device rebuilds everything else (opposite-
// side ordering, block tables) from this grouped form, so this is the ONLY
// host-side work in the train ingest.
//
// Caller contract: deg_out zeroed, sized n_entities; every rows[j] must be
// in [0, n_entities) (the Python wrapper validates and falls back to numpy
// otherwise). Returns 0 on success.

int32_t pio_coo_group(const int32_t* rows, const int32_t* cols,
                      const float* vals, int64_t n, int32_t n_entities,
                      int32_t* cols_out, float* vals_out, int32_t* deg_out) {
  for (int64_t j = 0; j < n; ++j) {
    int32_t e = rows[j];
    if (e < 0 || e >= n_entities) return 1;
    deg_out[e]++;
  }
  std::vector<int64_t> cursor(static_cast<size_t>(n_entities));
  int64_t acc = 0;
  for (int32_t e = 0; e < n_entities; ++e) {
    cursor[e] = acc;
    acc += deg_out[e];
  }
  for (int64_t j = 0; j < n; ++j) {
    int64_t p = cursor[rows[j]]++;
    cols_out[p] = cols[j];
    vals_out[p] = vals[j];
  }
  return 0;
}

// Similar-product cooccurrence build (ref CooccurrenceAlgorithm.scala:30-90:
// the Spark self-join over per-user distinct item sets). Input is the
// DISTINCT (user, item) list sorted by user (the Python wrapper dedups +
// groups with one np.unique over 1-D codes); per user-run the dense count
// matrix row C[i] (n_items int32 = fits L1 for ML-scale vocabs) takes the
// pair increments, then a per-row insertion select keeps the top_n by
// (count desc, item asc) — the exact order of the scipy/lexsort fallback in
// ops/cooccurrence.py, which stays as the oracle. out_items padded with -1.
// Returns 0 on success; nonzero -> caller falls back to the python path.
int32_t pio_cooccur_topn(const int32_t* __restrict users,
                         const int32_t* __restrict items,
                         int64_t nnz, int32_t n_items, int32_t top_n,
                         int32_t* __restrict out_items,
                         int32_t* __restrict out_counts) {
  if (n_items <= 0 || top_n <= 0) return 1;
  // dense count matrix: bail out (python fallback) past ~1GB
  if (static_cast<int64_t>(n_items) * n_items > (1LL << 28)) return 2;
  int32_t max_user = -1;
  for (int64_t j = 0; j < nnz; ++j) {
    if (items[j] < 0 || items[j] >= n_items) return 4;
    if (users[j] > max_user) max_user = users[j];
  }
  // a cooccurrence count is at most the user count; when that fits uint16
  // the half-width matrix halves the cache traffic of both hot passes
  if (max_user < 65535)
    return cooccur_accumulate<uint16_t>(users, items, nnz, n_items, top_n,
                                        out_items, out_counts);
  return cooccur_accumulate<int32_t>(users, items, nnz, n_items, top_n,
                                     out_items, out_counts);
}

}  // extern "C"
