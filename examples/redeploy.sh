#!/usr/bin/env bash
# Cron-able train-and-redeploy loop (ref examples/redeploy-script/redeploy.sh:
# the reference spark-submits a retrain then curls the engine server; here
# train runs in-process on the TPU host and /reload hot-swaps the server to
# the newest COMPLETED engine instance without dropping connections).
#
# Crontab example — retrain hourly at :07:
#   7 * * * * /path/to/repo/examples/redeploy.sh >> /var/log/pio-redeploy.log 2>&1
set -euo pipefail

# ---- configuration ---------------------------------------------------------
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
ENGINE_DIR="${ENGINE_DIR:-$REPO_DIR/predictionio_tpu/models/recommendation}"
VARIANT="${VARIANT:-engine.json}"
HOST="${HOST:-127.0.0.1}"
# a port other than the default 8000 is recommended so a bare `pio deploy`
# by mistake cannot shut this server down
PORT="${PORT:-8001}"
# ---------------------------------------------------------------------------

echo "[$(date -Is)] training $ENGINE_DIR ($VARIANT)"
"$REPO_DIR/pio" train --engine-dir "$ENGINE_DIR" --variant "$VARIANT"

echo "[$(date -Is)] reloading server at $HOST:$PORT"
if curl -fsS -X POST "http://$HOST:$PORT/reload" > /dev/null; then
  echo "[$(date -Is)] reload OK"
else
  echo "[$(date -Is)] reload failed — is the server deployed on $PORT?" >&2
  exit 1
fi
