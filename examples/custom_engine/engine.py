"""A user-authored engine from scratch: time-decayed trending items.

This is the engine-developer walkthrough the reference ships as its
template skeletons (ref ``examples/scala-parallel-*/src/main/scala/
Engine.scala`` — DataSource/Preparator/Algorithm/Serving + an
``engineFactory``): everything a user writes to put their own model
behind ``pio train`` / ``pio deploy``. The model here is deliberately
tiny — exponentially time-decayed view/buy counts — so the DASE plumbing
stays in the foreground; the decayed accumulation itself runs under
``jax.jit`` (a segment-sum over the event stream), making this also the
minimal example of the JaxAlgorithm path.

Run it with::

    python -m predictionio_tpu.tools.cli train \
        --engine-dir examples/custom_engine
    python -m predictionio_tpu.tools.cli deploy \
        --engine-dir examples/custom_engine --port 8000
    curl -X POST :8000/queries.json -d '{"num": 5}'
"""

from __future__ import annotations

import dataclasses

import numpy as np

from predictionio_tpu.controller import (
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Params,
)
from predictionio_tpu.eval.metric import AverageMetric
from predictionio_tpu.controller.algorithm import JaxAlgorithm
from predictionio_tpu.controller.engine import Engine
from predictionio_tpu.workflow.context import WorkflowContext


# -- queries and results (the wire contract of POST /queries.json) ----------


@dataclasses.dataclass(frozen=True)
class Query:
    num: int = 10
    blacklist: tuple[str, ...] = ()

    @classmethod
    def from_json_dict(cls, d: dict) -> "Query":
        return cls(
            num=int(d.get("num", 10)),
            blacklist=tuple(d.get("blacklist", ())),
        )


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...]

    def to_json_dict(self) -> dict:
        return {
            "itemScores": [
                {"item": s.item, "score": s.score} for s in self.item_scores
            ]
        }


# -- D: data source ---------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: tuple[str, ...] = ("view", "buy")
    eval_k: int = 0  # folds for `pio eval`; 0 = training only


@dataclasses.dataclass
class TrainingData:
    item_ids: np.ndarray  # [N] int32 codes into item_vocab
    event_weights: np.ndarray  # [N] f32 (1.0 view, 3.0 buy)
    timestamps: np.ndarray  # [N] f64 epoch seconds
    item_vocab: list[str]


class DataSource(BaseDataSource):
    params_class = DataSourceParams
    params: DataSourceParams

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        store = ctx.p_event_store()
        vocab: dict[str, int] = {}
        items, weights, stamps = [], [], []
        for e in store.find(
            self.params.app_name or ctx.app_name,
            channel_name=ctx.channel_name,
            event_names=list(self.params.event_names),
        ):
            if not e.target_entity_id:
                continue
            code = vocab.setdefault(e.target_entity_id, len(vocab))
            items.append(code)
            weights.append(3.0 if e.event == "buy" else 1.0)
            stamps.append(e.event_time.timestamp())
        return TrainingData(
            np.asarray(items, np.int32),
            np.asarray(weights, np.float32),
            np.asarray(stamps, np.float64),
            list(vocab),
        )

    def read_eval(self, ctx: WorkflowContext):
        """k-fold split for `pio eval`: train on k-1 folds, ask whether a
        held-out interaction's item lands in the trending top-10."""
        from predictionio_tpu.e2.cross_validation import k_fold_split

        td = self.read_training(ctx)
        n = len(td.item_ids)
        folds = []
        for train_idx, test_idx in k_fold_split(
            list(range(n)), max(2, self.params.eval_k)
        ):
            tr = TrainingData(
                td.item_ids[train_idx],
                td.event_weights[train_idx],
                td.timestamps[train_idx],
                td.item_vocab,
            )
            qa = [
                (Query(num=10), ActualItem(td.item_vocab[td.item_ids[i]]))
                for i in test_idx
            ]
            folds.append((tr, {}, qa))
        return folds


@dataclasses.dataclass(frozen=True)
class ActualItem:
    """The held-out interaction's item (the eval ground truth)."""

    item: str


class HitAtK(AverageMetric):
    """Fraction of held-out interactions whose item is in the served
    top-N (a popularity model answers the same list for every query, so
    this measures how much tail traffic the trending list captures)."""

    def calculate_score(self, ei, q, p: PredictedResult, a: ActualItem) -> float:
        return 1.0 if any(s.item == a.item for s in p.item_scores) else 0.0


def evaluation():
    """`pio eval engine.evaluation` over half-life variants."""
    from predictionio_tpu.eval.evaluator import (
        EngineParamsGenerator,
        Evaluation,
    )

    engine = engine_factory()
    base = engine.engine_params_from_variant(
        {
            "datasource": {"params": {"appName": "MyApp1", "evalK": 3}},
            "algorithms": [
                {"name": "trending", "params": {"halfLifeDays": 7.0}}
            ],
        }
    )
    variants = []
    for days in (1.0, 7.0, 30.0):
        algo_name, algo_params = base.algorithms[0]
        variants.append(
            dataclasses.replace(
                base,
                algorithms=[
                    (algo_name, dataclasses.replace(algo_params, half_life_days=days))
                ],
            )
        )
    return Evaluation(
        engine=engine,
        metric=HitAtK(),
        engine_params_generator=EngineParamsGenerator(variants),
    )


# -- A/S: the jit-compiled scorer and first-serving -------------------------


@dataclasses.dataclass(frozen=True)
class AlgoParams(Params):
    half_life_days: float = 7.0


@dataclasses.dataclass
class TrendingModel:
    scores: np.ndarray  # [n_items] f32, decayed popularity
    item_vocab: list[str]


class TrendingAlgorithm(JaxAlgorithm):
    params_class = AlgoParams
    params: AlgoParams

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> TrendingModel:
        import jax
        import jax.numpy as jnp

        n_items = len(pd.item_vocab)
        if n_items == 0:
            return TrendingModel(np.zeros(0, np.float32), [])
        now = float(pd.timestamps.max()) if len(pd.timestamps) else 0.0
        half_life_s = self.params.half_life_days * 86400.0

        @jax.jit
        def decayed_counts(item_ids, weights, ages_s):
            decay = jnp.exp2(-ages_s / half_life_s).astype(jnp.float32)
            return jnp.zeros(n_items, jnp.float32).at[item_ids].add(
                weights * decay
            )

        scores = decayed_counts(
            pd.item_ids, pd.event_weights,
            (now - pd.timestamps).astype(np.float32),
        )
        return TrendingModel(np.asarray(scores), pd.item_vocab)

    def predict(self, model: TrendingModel, query: Query) -> PredictedResult:
        order = np.argsort(-model.scores, kind="stable")
        out = []
        banned = set(query.blacklist)
        for idx in order:
            if len(out) >= query.num:  # before append: num<=0 returns none
                break
            item = model.item_vocab[int(idx)]
            if item in banned:
                continue
            out.append(ItemScore(item, float(model.scores[idx])))
        return PredictedResult(tuple(out))


class Preparator(BasePreparator):
    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> TrainingData:
        return td  # nothing to transform for this model


class Serving(BaseServing):
    def serve(self, query: Query, predictions) -> PredictedResult:
        return predictions[0]


def engine_factory() -> Engine:
    return Engine(
        DataSource,
        Preparator,
        {"trending": TrendingAlgorithm},
        Serving,
        query_class=Query,
    )
