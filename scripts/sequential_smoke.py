"""CI sequential+bandit smoke (run_lint.sh --ci, ISSUE 20).

One process, real sockets: ingest ordered session events into the event
store -> train the sequential engine's markov scorer THROUGH the real
DataSource (ordered ``find_after`` reads) -> serve next-item queries
through the fleet Gateway fronting a real QueryServer with a Thompson
bandit engaged on a staged candidate -> post reward feedback events
carrying the served trace ids -> assert the candidate arm's reward
posterior MOVES and the bake-gate-as-reward-accounting promotes the
winner with zero client-visible 5xx.

Exit 0 = all held; any assertion exits nonzero and fails CI.
"""

from __future__ import annotations

import asyncio
import datetime as dt
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

APP = "seqsmoke"
N_USERS = 40
SESSION = ["i0", "i1", "i2", "i3", "i4"]


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main() -> int:
    import tempfile

    import numpy as np  # noqa: F401 - jax platform must be set before import

    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.models.sequential import engine_factory
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.create_server import (
        Lane,
        QueryServer,
        ServerConfig,
    )
    from predictionio_tpu.workflow.engine_loader import EngineManifest

    storage = Storage(
        env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )
    storage.get_meta_data_apps().insert(App(0, APP))
    app_id = storage.get_meta_data_apps().get_by_name(APP).id
    levents = storage.get_l_events()

    # -- 1. ingest ordered sessions (same creation second: the seq-key
    #       event-id tiebreak keeps ingest order) --------------------------
    t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    n = 0
    for u in range(N_USERS):
        for item in SESSION:
            n += 1
            ts = t0 + dt.timedelta(seconds=n)
            levents.insert(
                Event(
                    event="view",
                    entity_type="user",
                    entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id=item,
                    properties=DataMap({}),
                    event_time=ts,
                    creation_time=ts,
                ),
                app_id,
            )

    # -- 2. train through the real DataSource (ordered find_after reads) --
    engine = engine_factory()
    ep = engine.engine_params_from_variant(
        {
            "datasource": {"params": {"appName": APP, "page": 16}},
            "algorithms": [{"name": "markov", "params": {"top_n": 5}}],
        }
    )
    ctx = WorkflowContext(mode="training", _storage=storage, app_name=APP)
    ds, prep, algorithms, serving = engine.make_components(ep)
    td = prep.prepare(ctx, ds.read_training(ctx))
    model = algorithms[0].train(ctx, td)
    # the ingest order must survive the read: every session is i0..i4, so
    # the learned top transition from i0 is i1
    probs = dict(model.markov.transition_probs(model.item_vocab.index("i0")))
    nxt = max(probs, key=probs.get)
    assert model.item_vocab[nxt] == "i1", (probs, model.item_vocab)

    registry_dir = tempfile.mkdtemp(prefix="pio_seq_smoke_reg_")
    port = _free_port()
    server = QueryServer(
        engine=engine,
        engine_params=ep,
        models=[model],
        manifest=EngineManifest(
            engine_id=APP,
            version="1",
            variant="engine.json",
            engine_factory="predictionio_tpu.models.sequential.engine_factory",
        ),
        instance_id="seq-v1",
        storage=storage,
        config=ServerConfig(
            ip="127.0.0.1",
            port=port,
            registry_dir=registry_dir,
            bandit_policy="thompson",
            bandit_app_name=APP,
            bandit_min_pulls=4,
            bandit_epsilon=0.5,
            bake_window_s=0.2,
            bake_min_requests=8,
            bake_check_interval_s=0.1,
            # both lanes run the same model in-process: sub-ms jitter must
            # not trip the ratio gates before the reward verdict lands
            max_p95_ratio=50.0,
            max_error_ratio=100.0,
            max_batch_size=16,
        ),
    )
    server._active = Lane(algorithms, serving, [model], "v1", "seq-v1", ep)
    # candidate: the same trained model under a new version — the smoke
    # injects which arm WINS via rewards, so lane quality is irrelevant
    _, _, algorithms2, serving2 = engine.make_components(ep)
    server.stage_candidate_lane(
        Lane(algorithms2, serving2, [model], "v2", "seq-v2", ep),
        fraction=0.5,
        persist=False,
    )
    assert server.bandit is not None and server.bandit.active

    return asyncio.run(drive(server, storage, app_id, port))


async def drive(server, storage, app_id, port: int) -> int:
    import aiohttp

    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.fleet import Gateway, GatewayConfig
    from predictionio_tpu.obs.metrics import MetricsRegistry
    from predictionio_tpu.obs.tracing import TRACE_HEADER

    server_task = asyncio.ensure_future(server.run_until_stopped())
    gw_port = _free_port()
    gw = Gateway(
        GatewayConfig(
            ip="127.0.0.1",
            port=gw_port,
            replica_urls=(f"http://127.0.0.1:{port}",),
            probe_interval_s=0.2,
            probe_timeout_s=2.0,
            request_timeout_s=8.0,
        ),
        metrics=MetricsRegistry(),
    )
    await gw.start()
    gw_url = f"http://127.0.0.1:{gw_port}"
    session = aiohttp.ClientSession(timeout=aiohttp.ClientTimeout(total=10))
    levents = storage.get_l_events()
    t0 = dt.datetime(2026, 1, 2, tzinfo=dt.timezone.utc)
    try:
        # wait for the gateway to probe the replica healthy
        deadline = time.monotonic() + 15.0
        while True:
            try:
                async with session.get(f"{gw_url}/healthz") as resp:
                    if (await resp.json()).get("replicasHealthy", 0) >= 1:
                        break
            except Exception:
                pass
            assert time.monotonic() < deadline, "gateway never went healthy"
            await asyncio.sleep(0.2)

        # -- 3. serve next-item THROUGH the gateway; collect trace ids ----
        served = []  # (trace_id, version)
        failures = 0
        for k in range(60):
            trace = f"seq-smoke-{k}"
            async with session.post(
                f"{gw_url}/queries.json",
                json={"user": f"u{k % N_USERS}", "recentItems": ["i0"], "num": 3},
                headers={TRACE_HEADER: trace},
            ) as resp:
                if resp.status != 200:
                    failures += 1
                    continue
                body = await resp.json()
                assert body["itemScores"], body
                # top next-item after i0 must be i1 (the learned chain)
                assert body["itemScores"][0]["item"] == "i1", body
        assert failures == 0, f"{failures} client-visible failures"
        snap = server.bandit.snapshot()
        pulls = {
            snap["stable"]["arm"]: snap["stable"]["pulls"],
            snap["candidate"]["arm"]: snap["candidate"]["pulls"],
        }
        assert pulls["stable"] >= 4 and pulls["candidate"] >= 4, pulls
        for k in range(60):
            trace = f"seq-smoke-{k}"
            hit = server.bandit.impressions.peek(trace)
            if hit is not None:
                served.append((trace, hit[0]))

        # -- 4. feedback: reward EVERY candidate impression, no stable ----
        k = 0
        for trace, arm in served:
            if arm != "candidate":
                continue
            k += 1
            ts = t0 + dt.timedelta(seconds=k)
            levents.insert(
                Event(
                    event="reward",
                    entity_type="user",
                    entity_id=f"fb{k}",
                    properties=DataMap({"traceId": trace, "reward": 1.0}),
                    event_time=ts,
                    creation_time=ts,
                ),
                app_id,
            )
        assert k >= 4, f"only {k} candidate impressions to reward"

        # -- 5. the posterior must move, then the reward verdict promotes -
        deadline = time.monotonic() + 20.0
        moved = False
        while time.monotonic() < deadline:
            ins = server.bandit_instruments
            if not moved and ins.matched.value() > 0:
                moved = True
                print(
                    f"sequential smoke: {int(ins.matched.value())} rewards "
                    "matched; candidate posterior moving"
                )
            if server._candidate is None:
                break
            await asyncio.sleep(0.2)
        assert moved, "no reward ever matched an impression"
        assert server._active.version == "v2", (
            "reward-winning candidate was not promoted: "
            f"active={server._active.version} snap={server.bandit.snapshot()}"
        )
        assert not server.bandit.active
        print(
            "sequential smoke: ingest -> ordered train -> gateway serving -> "
            f"feedback moved the posterior -> v2 promoted ({k} rewards, "
            "0 client-visible failures)"
        )
        return 0
    finally:
        await session.close()
        await gw.stop()
        server.begin_drain()
        try:
            await asyncio.wait_for(server_task, timeout=10)
        except (asyncio.TimeoutError, Exception):
            server_task.cancel()


if __name__ == "__main__":
    raise SystemExit(main())
