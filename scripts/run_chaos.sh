#!/usr/bin/env bash
# Chaos gate: run the fault-injection/resilience suite (CPU-only, fast).
# Asserts the documented degraded-mode behavior — deadline 503s, load
# shedding, breaker trip/recovery, retry-then-succeed — under injected
# faults, AND that the telemetry layer sees it all happen (shed/retry/
# breaker counters moving, trace ids spanning ingress->batch->storage).
# The rollout-under-chaos stage (tests/test_registry.py) fault-injects the
# canary candidate lane and asserts the candidate breaker trips, the
# router auto-rolls back to stable, and stable traffic never errors.
# The tail-under-chaos stage (tests/test_stream.py) kills the speed-layer
# pipeline mid-drain under fault injection, restarts it, and asserts the
# cursor resumes with no skipped events and no duplicate registry publish
# (plus the full e2e: ingest -> stream -> candidate -> bake -> promote).
# The rollout-under-replica-loss stage (tests/test_fleet.py, incl. the
# slow-marked e2e) runs REAL worker processes behind the fleet gateway
# under load, SIGKILLs one mid-bake, and asserts zero 5xx on the stable
# lane, ejection within the probe interval, supervisor restart +
# readmission, and bake-gate convergence — AND (ISSUE 11) that the kill
# leaves a full incident bundle: the dead worker's stderr tail, a merged
# gateway+replica trace, the telemetry-ring window covering the kill,
# and the registry generation at trigger time. The flight-recorder
# stage (tests/test_flightrec.py) covers the plane itself: telemetry
# ring rotation/resume, incident capture mechanics, cross-tier span
# merging with the dead-replica cache, and trace-id continuity through
# a gateway retry down to a storage span. The elasticity stage
# (tests/test_autoscaler.py, incl. the slow-marked e2e) drives a REAL
# 1->3->1 fleet through a spike trace: scale-out under load and the
# drain-based scale-in both zero-5xx, scaling decisions in the
# telemetry ring, an autoscaler-saturated incident bundle at the
# envelope, and retired replicas' gauges dropped from the exposition.
# The multi-host stage (tests/test_hostrt.py, incl. the slow-marked
# kill-a-host e2e) pulls an entire fake-driver host's cord mid-rollout:
# zero client-visible 5xx, ONE host-death incident bundle carrying every
# dead worker's log tail, the registry lease stolen from the dead
# host's holder with a fresh fencing token, and capacity restored on
# the survivor through the host-aware spawn path. The lease stage
# (tests/test_lease.py) proves the shared-storage mutex itself:
# TTL-expiry steals, fencing on save, and a two-process hammer with no
# lost transitions and no token reuse. The profiling-plane stage
# (tests/test_profiler.py) exercises the observability side of failure:
# single-flight capture under contention (second capture 409s, never
# queues), profile-on-alert attaching the offending thread's folded host
# stacks to the bundle off the failure path, alert captures rate-limited
# and never raising into the serving loop, and the always-on sampler's
# self-measured overhead staying under 1% while a busy thread churns.
# The lifecycle stage (tests/test_lifecycle.py, incl. the slow-marked
# e2e) closes the loop with the controller itself in the blast radius:
# an injected drift breach triggers a background eval grid against a
# REAL serving process, the controller is SIGKILLed mid-grid and a
# restarted one resumes the SAME run via the durable ledger (zero
# retrained cells), the staged winner bakes under live traffic to an
# auto-promote with zero 5xx throughout, and the promote warms the
# result cache — plus the pure-policy matrix (defer-mid-bake, timeouts,
# cooldown, pause/manual-trigger) on a fake clock.
# The bandit stage (tests/test_bandit.py, incl. the slow-marked e2e)
# drives the full reward loop: ordered sessions ingested through the
# EventServer, the sequential engine trained and fold-in published with
# lineage, the candidate staged as a bandit arm, feedback events matched
# by trace id moving the posterior to an auto-promote, then a starved
# re-staged arm auto-retired through the rollback machinery — zero
# client-visible 5xx across both verdicts.
# See docs/resilience.md, docs/observability.md, docs/model_registry.md,
# docs/streaming.md, docs/fleet.md, docs/lifecycle.md, docs/bandit.md,
# docs/sequential.md.
# Usage: scripts/run_chaos.sh [extra pytest args...]
set -euo pipefail

repo_root="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$repo_root"

exec env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_resilience.py tests/test_obs.py tests/test_registry.py \
  tests/test_stream.py tests/test_fleet.py tests/test_flightrec.py \
  tests/test_autoscaler.py tests/test_hostrt.py tests/test_lease.py \
  tests/test_profiler.py tests/test_lifecycle.py \
  tests/test_sequential.py tests/test_bandit.py -q \
  -p no:cacheprovider "$@"
