#!/usr/bin/env bash
# Chaos gate: run the fault-injection/resilience suite (CPU-only, fast).
# Asserts the documented degraded-mode behavior — deadline 503s, load
# shedding, breaker trip/recovery, retry-then-succeed — under injected
# faults. See docs/resilience.md.
# Usage: scripts/run_chaos.sh [extra pytest args...]
set -euo pipefail

repo_root="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$repo_root"

exec env JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
  -p no:cacheprovider "$@"
