"""CI profile smoke (run_lint.sh --ci): the profiling plane end to end.

Self-contained, one real server process: this script in ``--worker``
mode serves the recommendation engine over random factors on the CPU
backend with the profiling plane on (always-on host sampler + on-demand
capture). The orchestrator then proves the ISSUE 18 acceptance rails
against the LIVE server:

1. ``pio profile serve`` (the real CLI, urllib POST to
   ``/profile/capture``) captures a short device trace and returns the
   bundle id + the serving lane's model version;
2. the bundle is listed by ``pio profile list``, rendered by
   ``pio profile show`` (manifest model version MUST match the serving
   lane), and exported by ``pio profile export``;
3. ``GET /profile/stacks`` serves non-empty folded host stacks from the
   always-on sampler;
4. ``pio doctor --roofline`` exits 0 with finite numbers for every
   registered bucket family — the device-free cost model runs on the
   CPU backend in CI on every push.

Exit 0 = all held; any assertion exits nonzero and fails CI.
"""

from __future__ import annotations

import asyncio
import contextlib
import io
import json
import math
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_main(port: int, profile_dir: str) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.models.recommendation import engine_factory
    from predictionio_tpu.models.recommendation.engine import ALSModel
    from predictionio_tpu.workflow.create_server import (
        QueryServer,
        ServerConfig,
    )
    from predictionio_tpu.workflow.engine_loader import EngineManifest

    rng = np.random.default_rng(0)
    n_users, n_items, rank = 500, 300, 8
    model = ALSModel(
        rng.normal(size=(n_users, rank)).astype("float32"),
        rng.normal(size=(n_items, rank)).astype("float32"),
        [f"u{i}" for i in range(n_users)],
        [f"i{i}" for i in range(n_items)],
    )
    engine = engine_factory()
    ep = engine.engine_params_from_variant(
        {
            "datasource": {"params": {"appName": "profsmoke"}},
            "algorithms": [{"name": "als", "params": {}}],
        }
    )
    storage = Storage(
        env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )
    server = QueryServer(
        engine=engine,
        engine_params=ep,
        models=[model],
        manifest=EngineManifest(
            engine_id="profsmoke",
            version="1",
            variant="engine.json",
            engine_factory="predictionio_tpu.models.recommendation.engine_factory",
        ),
        instance_id="profsmoke",
        storage=storage,
        config=ServerConfig(
            ip="127.0.0.1",
            port=port,
            max_batch_size=32,
            profile_dir=profile_dir,
            sampler_period_s=0.02,
        ),
    )

    async def run() -> None:
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGTERM, server.begin_drain)
        except (NotImplementedError, RuntimeError):
            pass
        await server.run_until_stopped()

    print(f"profile smoke worker serving on 127.0.0.1:{port}",
          file=sys.stderr, flush=True)
    asyncio.run(run())
    return 0


def _cli(argv: list[str]) -> tuple[int, str]:
    from predictionio_tpu.tools.cli import main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    return rc, buf.getvalue()


def orchestrate(profile_dir: str) -> int:
    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", str(port),
         profile_dir],
        env=env,
        cwd=REPO,
    )
    try:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(f"{url}/healthz", timeout=1.0):
                    break
            except OSError:
                if proc.poll() is not None:
                    raise AssertionError("worker died before becoming ready")
                time.sleep(0.2)
        else:
            raise AssertionError("worker never became healthy")

        # 1. the real CLI capture path against the live server
        rc, out = _cli(["profile", "serve", "--url", url, "--ms", "100"])
        assert rc == 0, f"pio profile serve failed rc={rc}"
        resp = json.loads(out)
        bundle_id = resp["bundle"]
        lane_version = resp["modelVersion"]
        assert bundle_id and lane_version

        # 2. list / show / export the bundle through the CLI
        rc, out = _cli(["profile", "list", "--profile-dir", profile_dir])
        assert rc == 0 and bundle_id in out, "bundle not listed"
        rc, out = _cli(
            ["profile", "show", bundle_id, "--profile-dir", profile_dir,
             "--json"]
        )
        assert rc == 0, "pio profile show failed"
        manifest = json.loads(out)["manifest"]
        assert manifest["context"]["modelVersion"] == lane_version, (
            f"bundle model version {manifest['context']['modelVersion']!r} "
            f"!= serving lane {lane_version!r}"
        )
        assert manifest["trace"], "device capture produced no trace artifacts"
        with tempfile.TemporaryDirectory() as dest:
            rc, _ = _cli(
                ["profile", "export", bundle_id, dest, "--profile-dir",
                 profile_dir]
            )
            assert rc == 0
            assert os.path.exists(
                os.path.join(dest, bundle_id, "manifest.json")
            ), "export left no manifest"

        # 3. the always-on sampler serves folded stacks
        with urllib.request.urlopen(
            f"{url}/profile/stacks", timeout=5.0
        ) as r:
            folded = r.read().decode()
        assert folded.strip(), "sampler served empty folded stacks"

        print(
            f"profile smoke: captured {bundle_id} via pio profile serve "
            f"(model {lane_version}), listed/shown/exported, "
            f"{len(folded.splitlines())} folded stacks live"
        )
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10.0)

    # 4. the device-free roofline: exit 0 + finite numbers per family
    rc, out = _cli(["doctor", "--roofline"])
    assert rc == 0, "pio doctor --roofline exited nonzero"
    report = json.loads(out)
    assert not report["errors"], f"roofline errors: {report['errors']}"
    for family, entry in report["families"].items():
        for key in ("arithmeticIntensity", "perQueryModelTimeS",
                    "costPer1kQueriesUsd"):
            v = entry[key]
            assert isinstance(v, (int, float)) and math.isfinite(v) and v > 0, (
                f"{family}.{key} not finite-positive: {v!r}"
            )
    fams = ", ".join(
        f"{f} ai={e['arithmeticIntensity']:.2f}"
        for f, e in report["families"].items()
    )
    print(f"roofline smoke: {fams} on {report['device']['name']}")
    return 0


def main() -> int:
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        return worker_main(int(sys.argv[2]), sys.argv[3])
    with tempfile.TemporaryDirectory() as d:
        return orchestrate(os.path.join(d, "profiles"))


if __name__ == "__main__":
    sys.exit(main())
