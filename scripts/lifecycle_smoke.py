#!/usr/bin/env python
"""CI smoke for the lifecycle controller (ISSUE 19, docs/lifecycle.md).

One full self-driving loop on a tiny corpus, zero human commands after
setup: a scheduled cadence trigger fires → the REAL eval grid runs
(workers=0, cpu-fallback class) and stages its winner as a registry
CANDIDATE → the bake resolves (the smoke promotes the candidate the way
a serving bake gate would; the full gate-under-traffic rail is the
slow-marked e2e in tests/test_lifecycle.py, run by the chaos gate) →
the controller observes the promote and warms the result cache by
replaying bounded queries over a REAL HTTP socket → the episode closes
PROMOTED with every transition on the telemetry ring, and `pio
lifecycle status` renders the durable state file from a separate
process.
"""

from __future__ import annotations

import dataclasses
import http.server
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from predictionio_tpu.controller import (  # noqa: E402
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    EngineParams,
    Params,
)
from predictionio_tpu.eval import AverageMetric, Evaluation  # noqa: E402

ENGINE_ID = "lifecycle-smoke"
N_FOLDS = 2
N_PARAMS = 2
WARM_LIMIT = 5


@dataclasses.dataclass(frozen=True)
class SmokeParams(Params):
    weight: float = 1.0


class SmokeDataSource(BaseDataSource):
    def read_training(self, ctx):
        return list(range(20))

    def read_eval(self, ctx):
        for fold in range(N_FOLDS):
            yield list(range(20)), {"fold": fold}, [(i, i) for i in range(6)]


class SmokePreparator(BasePreparator):
    def prepare(self, ctx, td):
        return td


class SmokeAlgo(BaseAlgorithm):
    params_class = SmokeParams
    params: SmokeParams

    def train(self, ctx, pd):
        return {"weight": self.params.weight}

    def predict(self, model, query):
        return query * model["weight"]


class SmokeServing(BaseServing):
    def serve(self, query, predictions):
        return predictions[0]


class SmokeMetric(AverageMetric):
    def calculate_score(self, ei, q, p, a) -> float:
        return float(p)


def smoke_params(weight: float) -> EngineParams:
    return EngineParams(
        data_source=("", None),
        preparator=("", None),
        algorithms=[("", SmokeParams(weight=weight))],
        serving=("", None),
    )


def make_engine() -> Engine:
    return Engine(SmokeDataSource, SmokePreparator, SmokeAlgo, SmokeServing)


def make_evaluation() -> Evaluation:
    return Evaluation(
        engine=make_engine(),
        metric=SmokeMetric(),
        engine_params_generator=[smoke_params(1.0), smoke_params(3.0)],
    )


def _manifest():
    from predictionio_tpu.workflow.engine_loader import EngineManifest

    return EngineManifest(
        engine_id=ENGINE_ID,
        version="1",
        variant="engine.json",
        engine_factory="scripts.lifecycle_smoke.make_engine",
        description="",
        variant_json={},
        engine_dir=".",
    )


class _WarmTarget(http.server.BaseHTTPRequestHandler):
    hits: list[dict] = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        type(self).hits.append(json.loads(body))
        payload = b"{}"
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *a):
        pass


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="pio_lifecycle_smoke_")
    registry_dir = os.path.join(tmp, "registry")
    obs_dir = os.path.join(tmp, "obs")
    state_dir = os.path.join(obs_dir, "lifecycle")
    env = {
        k: v for k, v in os.environ.items() if not k.startswith("PIO_STORAGE_")
    }
    env.update(
        {"PIO_FS_BASEDIR": os.path.join(tmp, "store"), "JAX_PLATFORMS": "cpu"}
    )
    os.environ.update(env)

    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.lifecycle import (
        LifecycleConfig,
        LifecycleController,
        LifecyclePolicy,
        build_grid_tuner,
        build_warmer,
        read_json_file,
    )
    from predictionio_tpu.obs.tsring import TelemetryRing
    from predictionio_tpu.registry import ArtifactStore, registry_rollout_probe
    from predictionio_tpu.workflow.core_workflow import run_train

    # setup: a v1 stable for the grid winner to canary against
    storage = Storage(env=env)
    run_train(
        make_engine(),
        _manifest(),
        smoke_params(1.0),
        storage=storage,
        registry_dir=registry_dir,
    )

    # the warm target: a real socket standing in for the serving tier
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _WarmTarget)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    serve_url = f"http://127.0.0.1:{srv.server_address[1]}"

    config = LifecycleConfig(
        cadence_s=0.2,  # the scheduled trigger under test
        cooldown_s=9999.0,
        tick_interval_s=0.05,
        warm_limit=WARM_LIMIT,
    )
    ring = TelemetryRing(
        os.path.join(obs_dir, "telemetry"), writer_id="lifecycle"
    )
    controller = LifecycleController(
        LifecyclePolicy(config),
        state_dir=state_dir,
        engine_id=ENGINE_ID,
        registry_dir=registry_dir,
        tune=build_grid_tuner(
            make_evaluation,
            workdir=os.path.join(state_dir, "grid"),
            engine_manifest=_manifest(),
            registry_dir=registry_dir,
            storage=storage,
            workers=0,
            stage_fraction=1.0,
        ),
        warm=build_warmer(
            serve_url,
            lambda: ({"user": f"u{i}", "num": 1} for i in range(20)),
            limit=WARM_LIMIT,
        ),
        rollout_probe=registry_rollout_probe(registry_dir),
        ring=ring,
    )

    # the driver loop, with the smoke acting as the serving bake gate:
    # the moment the grid's candidate appears, "traffic" promotes it
    store = ArtifactStore(registry_dir)
    deadline = time.monotonic() + 120
    while controller.policy.last_outcome != "promoted":
        assert time.monotonic() < deadline, (
            f"loop never promoted; state={controller.policy.state} "
            f"grid={controller._grid_state!r} err={controller._grid_error!r}"
        )
        controller.tick()
        state = store.get_state(ENGINE_ID)
        if state.candidate:
            store.promote(ENGINE_ID)
        time.sleep(config.tick_interval_s)

    # the loop closed: winner promoted, cache warmed, episode idle
    final = store.get_state(ENGINE_ID)
    assert final.stable == "v000002" and final.candidate == "", final
    assert len(_WarmTarget.hits) == WARM_LIMIT, _WarmTarget.hits
    assert all(h["num"] == 1 for h in _WarmTarget.hits)
    m = controller.metrics.get("pio_lifecycle_runs_total")
    assert m.value(outcome="promoted") == 1.0
    assert controller.metrics.get("pio_lifecycle_triggers_total").value(
        reason="cadence"
    ) == 1.0
    events = [
        r["event"] for r in ring.records() if r.get("kind") == "lifecycle"
    ]
    assert events == ["triggered", "tuning", "baking", "finished"], events

    # the operator surface reads the same durable file from outside
    status = read_json_file(os.path.join(state_dir, "lifecycle.json"))
    assert status["policy"]["lastOutcome"] == "promoted", status
    out = subprocess.run(
        [os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "pio"),
         "lifecycle", "status", "--obs-dir", obs_dir, "--json"],
        env=env,
        capture_output=True,
        timeout=60,
    )
    assert out.returncode == 0, out.stderr.decode()[-2000:]
    assert json.loads(out.stdout)["policy"]["lastOutcome"] == "promoted"

    srv.shutdown()
    srv.server_close()
    print(
        f"lifecycle smoke: cadence trigger -> grid ({N_PARAMS}x{N_FOLDS} "
        f"cells) -> candidate v000002 baked+promoted -> {len(_WarmTarget.hits)} "
        "warm queries replayed -> episode closed PROMOTED, "
        "`pio lifecycle status` renders"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
