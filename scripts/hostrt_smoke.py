"""CI multi-host smoke (run_lint.sh --ci): two fake-driver hosts, kill one.

The two-"host" survive-host-death gate on one machine (ISSUE 17,
docs/fleet.md §Multi-host): four real worker processes partitioned
under two fake host names by :class:`FakeHostDriver`, fronted by the
fleet gateway under live traffic. ``kill_host`` pulls one box's cord —
SIGKILLs every resident AND fails the host's liveness probe, which is
what a kernel panic looks like from the supervisor's chair — and the
smoke then asserts:

1. zero failed queries through the kill (the surviving host's workers
   absorb the traffic inside the gateway's probe window);
2. the supervisor folded the whole box into ONE host-death transition:
   exactly one ``host-death`` incident bundle, carrying every dead
   worker's captured log tail, and NO per-worker crash bundles;
3. ``pio top --fleet`` renders the host census with the ``HOST-DOWN``
   marker from the federated /metrics;
4. the host-aware scale-out path (``pick_host`` -> ``add_worker`` ->
   gateway admission) restores capacity on the survivor.

Workers are ``scripts/fleet_smoke.py --worker`` processes — the same
self-contained QueryServer the single-box fleet smoke drives. Exit 0 =
all held; any assertion exits nonzero and fails CI.
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def orchestrate(obs_dir: str) -> int:
    import aiohttp

    from predictionio_tpu.fleet import (
        Gateway,
        GatewayConfig,
        Supervisor,
        SupervisorConfig,
        WorkerSpec,
    )
    from predictionio_tpu.fleet.hostrt import (
        DRIVER_FAKE,
        FakeHostDriver,
        HostRuntime,
        HostSpec,
        assign_hosts,
    )
    from predictionio_tpu.fleet.launch import (
        build_obs_plane,
        wire_incident_sources,
    )
    from predictionio_tpu.obs.incidents import list_bundles, load_bundle
    from predictionio_tpu.obs.metrics import MetricsRegistry

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    metrics = MetricsRegistry()
    obs = build_obs_plane(obs_dir, metrics)

    fake = FakeHostDriver(obs["logbook"])
    runtime = HostRuntime(
        [
            HostSpec("ha", 2, driver=DRIVER_FAKE),
            HostSpec("hb", 3, driver=DRIVER_FAKE),
        ],
        logbook=obs["logbook"],
        drivers={DRIVER_FAKE: fake},
    )
    placement = assign_hosts(4, runtime.hosts())
    specs = [
        WorkerSpec(f"w{i}", _free_port(), host=placement[i]) for i in range(4)
    ]
    worker_script = os.path.join(REPO, "scripts", "fleet_smoke.py")

    def spawn(spec):
        return runtime.spawn_worker(
            spec.host,
            spec.name,
            [sys.executable, worker_script, "--worker", str(spec.port)],
            env=env,
        )

    def on_host_down(info: dict) -> None:
        texts = {}
        for winfo in info.get("workers", []):
            tail = winfo.pop("logTail", "")
            if tail:
                texts[f"log_tail_{winfo['replica']}"] = tail
        obs["incidents"].trigger("host-death", context=info, texts=texts)

    sup = Supervisor(
        spawn,
        specs,
        SupervisorConfig(
            poll_interval_s=0.1,
            backoff_base_s=0.2,
            term_grace_s=8.0,
            host_probe_interval_s=0.5,
        ),
        metrics=metrics,
        logbook=obs["logbook"],
        on_crash=obs["on_crash"],
        runtime=runtime,
        on_host_down=on_host_down,
    )
    gw_port = _free_port()
    gw = Gateway(
        GatewayConfig(
            ip="127.0.0.1",
            port=gw_port,
            replica_urls=tuple(s.url for s in specs),
            probe_interval_s=0.2,
            probe_timeout_s=1.0,
            request_timeout_s=8.0,
            telemetry_interval_s=0.2,
        ),
        metrics=metrics,
        telemetry=obs["telemetry"],
        incidents=obs["incidents"],
    )
    wire_incident_sources(obs["incidents"], gw, sup)
    gw_url = f"http://127.0.0.1:{gw_port}"
    sup.start()
    sup_task = asyncio.ensure_future(sup.run())
    await gw.start()
    session = aiohttp.ClientSession(timeout=aiohttp.ClientTimeout(total=10))

    async def healthy_count() -> int:
        async with session.get(f"{gw_url}/healthz") as resp:
            return (await resp.json()).get("replicasHealthy", 0)

    async def wait_for(cond, message: str, deadline_s: float) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                if await cond():
                    return
            except Exception:
                pass
            assert time.monotonic() < deadline, message
            await asyncio.sleep(0.2)

    async def query(i: int) -> int:
        async with session.post(
            f"{gw_url}/queries.json", json={"user": f"u{i % 50}", "num": 5}
        ) as resp:
            await resp.read()
            return resp.status

    try:
        # 1. all four workers come up across both hosts
        await wait_for(
            lambda: _is(healthy_count, 4), "workers never became ready", 180.0
        )
        for i in range(10):
            assert await query(i) == 200, "fleet did not answer pre-kill"
        # 2. pull host ha's cord: both residents die, the probe fails
        dead = [s.name for s in specs if s.host == "ha"]
        killed = fake.kill_host("ha")
        assert killed == len(dead), f"kill_host reaped {killed} != {len(dead)}"
        await wait_for(
            lambda: _is(healthy_count, 2),
            "dead host's replicas never ejected",
            10.0,
        )
        failures = 0
        for i in range(20):
            if await query(100 + i) != 200:
                failures += 1
        assert failures == 0, f"{failures}/20 queries failed after host kill"
        # 3. ONE host-death bundle, every dead worker's tail, no crash
        # bundles (run the listing off-loop: it stats files)
        refs = await asyncio.get_running_loop().run_in_executor(
            None, list_bundles, os.path.join(obs_dir, "incidents")
        )
        host_deaths = [r for r in refs if r.trigger == "host-death"]
        assert len(host_deaths) == 1, (
            f"expected ONE host-death bundle, got "
            f"{[r.trigger for r in refs]}"
        )
        assert not [r for r in refs if r.trigger == "worker-crash"], (
            "host death leaked per-worker crash bundles"
        )
        bundle = load_bundle(
            os.path.join(obs_dir, "incidents"), host_deaths[0].bundle_id
        )
        ctx = bundle["manifest"]["context"]
        assert ctx["host"] == "ha", ctx
        assert sorted(w["replica"] for w in ctx["workers"]) == sorted(dead)
        for name in dead:
            tail = bundle["texts"].get(f"log_tail_{name}", "")
            assert "fleet smoke worker serving" in tail, (
                f"{name}'s log tail missing from the bundle: {tail!r}"
            )
        # 4. pio top --fleet shows the host census with the DOWN marker
        top = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: subprocess.run(
                [
                    os.path.join(REPO, "pio"),
                    "top",
                    "--fleet",
                    "--once",
                    "--url",
                    gw_url,
                ],
                capture_output=True,
                timeout=60,
                env=env,
            ),
        )
        screen = top.stdout.decode(errors="replace")
        assert top.returncode == 0, top.stderr.decode(errors="replace")[-500:]
        assert "HOST-DOWN" in screen, (
            f"no HOST-DOWN marker in pio top output:\n{screen}"
        )
        assert "hb" in screen, screen
        # 5. host-aware scale-out restores capacity on the survivor
        target = sup.pick_host()
        assert target == "hb", f"pick_host chose {target!r}, not the survivor"
        replacement = WorkerSpec("w4", _free_port(), host=target)
        await asyncio.get_running_loop().run_in_executor(
            None, sup.add_worker, replacement
        )
        gw.add_replica(replacement.url, replacement.worker_class)
        await wait_for(
            lambda: _is(healthy_count, 3),
            "replacement capacity never came up on the survivor",
            180.0,
        )
        for i in range(10):
            assert await query(200 + i) == 200, "fleet failed after scale-out"
        print(
            json.dumps(
                {
                    "hostrt_smoke": "ok",
                    "hosts": {h.name: h.slots for h in runtime.hosts()},
                    "killed_host": "ha",
                    "dead_workers": dead,
                    "host_death_bundle": host_deaths[0].bundle_id,
                    "top_shows_host_down": True,
                    "replacement_on": target,
                }
            )
        )
        return 0
    finally:
        sup_task.cancel()
        await asyncio.gather(sup_task, return_exceptions=True)
        await session.close()
        await gw.stop()
        await asyncio.get_running_loop().run_in_executor(None, sup.stop)
        obs["telemetry"].close()


async def _is(fn, expect) -> bool:
    return (await fn()) == expect


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="pio_hostrt_smoke_obs_") as d:
        return asyncio.run(orchestrate(d))


if __name__ == "__main__":
    raise SystemExit(main())
