"""CI fleet smoke (run_lint.sh --ci): 2 workers + gateway, kill one.

Self-contained (no training, no shared storage): each worker is THIS
script in ``--worker`` mode serving the recommendation engine over
random factors — latency/availability smoke only, model quality is the
bench's job. The orchestrator spawns the workers under the fleet
supervisor (with the flight-recorder plane attached: worker log
capture, telemetry ring, incident recorder), fronts them with the
gateway, then:

1. proves the fleet answers through the gateway;
2. SIGKILLs one worker and asserts the gateway KEEPS answering
   (ejection + failover, zero client-visible failures);
3. asserts ``pio top --fleet`` renders the fleet line from the
   gateway's federated /metrics;
4. waits for the supervisor restart + gateway readmission;
5. **incident-bundle smoke** (ISSUE 11): the kill must have produced an
   incident bundle containing the dead worker's captured stderr tail
   AND a merged gateway+replica trace for an affected request — the
   flight recorder is CI-proven on every run, not only in the slow
   chaos suite;
6. **elasticity smoke** (ISSUE 13): one deterministic scale-out/scale-in
   cycle through the autoscaler's apply funnel — a third worker is
   spawned at runtime, earns routing via its first passing probe,
   answers traffic, then is retired through the gateway-first drain
   ordering with zero client-visible failures; both decisions must land
   in the telemetry ring and the retired replica's gauges must drop
   from the federated /metrics.

Exit 0 = all held; any assertion exits nonzero and fails CI.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_main(port: int) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.models.recommendation import engine_factory
    from predictionio_tpu.models.recommendation.engine import ALSModel
    from predictionio_tpu.workflow.create_server import (
        QueryServer,
        ServerConfig,
    )
    from predictionio_tpu.workflow.engine_loader import EngineManifest

    rng = np.random.default_rng(0)
    n_users, n_items, rank = 2000, 1000, 16
    model = ALSModel(
        rng.normal(size=(n_users, rank)).astype("float32"),
        rng.normal(size=(n_items, rank)).astype("float32"),
        [f"u{i}" for i in range(n_users)],
        [f"i{i}" for i in range(n_items)],
    )
    engine = engine_factory()
    ep = engine.engine_params_from_variant(
        {
            "datasource": {"params": {"appName": "fleetsmoke"}},
            "algorithms": [{"name": "als", "params": {}}],
        }
    )
    storage = Storage(
        env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )
    server = QueryServer(
        engine=engine,
        engine_params=ep,
        models=[model],
        manifest=EngineManifest(
            engine_id="fleetsmoke",
            version="1",
            variant="engine.json",
            engine_factory="predictionio_tpu.models.recommendation.engine_factory",
        ),
        instance_id="fleetsmoke",
        storage=storage,
        config=ServerConfig(ip="127.0.0.1", port=port, max_batch_size=32),
    )

    async def run() -> None:
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGTERM, server.begin_drain)
        except (NotImplementedError, RuntimeError):
            pass
        await server.run_until_stopped()

    # stderr breadcrumb: captured by the supervisor's logbook so a
    # SIGKILLed worker still leaves a tail for the incident bundle
    print(f"fleet smoke worker serving on 127.0.0.1:{port}",
          file=sys.stderr, flush=True)
    asyncio.run(run())
    return 0


async def orchestrate(obs_dir: str) -> int:
    import aiohttp

    from predictionio_tpu.fleet import (
        Gateway,
        GatewayConfig,
        Supervisor,
        SupervisorConfig,
        WorkerSpec,
    )
    from predictionio_tpu.fleet.launch import (
        build_obs_plane,
        wire_incident_sources,
    )
    from predictionio_tpu.fleet.worklog import spawn_with_log
    from predictionio_tpu.obs.metrics import MetricsRegistry

    specs = [WorkerSpec(f"w{i}", _free_port()) for i in range(2)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    metrics = MetricsRegistry()
    obs = build_obs_plane(obs_dir, metrics)

    def spawn(spec):
        return spawn_with_log(
            [sys.executable, os.path.abspath(__file__), "--worker", str(spec.port)],
            obs["logbook"],
            spec.name,
            env=env,
            cwd=REPO,
        )

    sup = Supervisor(
        spawn,
        specs,
        SupervisorConfig(poll_interval_s=0.1, backoff_base_s=0.2, term_grace_s=8.0),
        metrics=metrics,
        logbook=obs["logbook"],
        on_crash=obs["on_crash"],
    )
    gw_port = _free_port()
    gw = Gateway(
        GatewayConfig(
            ip="127.0.0.1",
            port=gw_port,
            replica_urls=tuple(s.url for s in specs),
            probe_interval_s=0.2,
            probe_timeout_s=1.0,
            request_timeout_s=8.0,
            telemetry_interval_s=0.2,
        ),
        metrics=metrics,
        telemetry=obs["telemetry"],
        incidents=obs["incidents"],
    )
    wire_incident_sources(obs["incidents"], gw, sup)
    gw_url = f"http://127.0.0.1:{gw_port}"
    sup.start()
    sup_task = asyncio.ensure_future(sup.run())
    await gw.start()
    session = aiohttp.ClientSession(timeout=aiohttp.ClientTimeout(total=10))

    async def healthy_count() -> int:
        async with session.get(f"{gw_url}/healthz") as resp:
            return (await resp.json()).get("replicasHealthy", 0)

    async def wait_for(cond, message: str, deadline_s: float) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                if await cond():
                    return
            except Exception:
                pass
            assert time.monotonic() < deadline, message
            await asyncio.sleep(0.2)

    async def query(i: int) -> int:
        async with session.post(
            f"{gw_url}/queries.json", json={"user": f"u{i % 50}", "num": 5}
        ) as resp:
            await resp.read()
            return resp.status

    try:
        # 1. both workers come up (each pays the jax import)
        await wait_for(
            lambda: _is(healthy_count, 2), "workers never became ready", 120.0
        )
        for i in range(10):
            assert await query(i) == 200, "fleet did not answer pre-kill"
        # let a telemetry tick fan-in the replicas' spans: the incident
        # bundle must hold the VICTIM's spans after it is SIGKILLed
        await asyncio.sleep(0.5)
        # 2. SIGKILL one worker; the gateway must keep answering
        victim = sup.snapshot()[1]
        os.kill(victim["pid"], signal.SIGKILL)
        await wait_for(
            lambda: _is(healthy_count, 1), "dead replica never ejected", 10.0
        )
        failures = 0
        for i in range(20):
            if await query(100 + i) != 200:
                failures += 1
        assert failures == 0, f"{failures}/20 queries failed after replica kill"
        # 3. pio top --fleet renders from the federated scrape (run OFF
        # the event loop: the gateway serves /metrics on this very loop)
        top = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: subprocess.run(
                [
                    os.path.join(REPO, "pio"),
                    "top",
                    "--fleet",
                    "--once",
                    "--url",
                    gw_url,
                ],
                capture_output=True,
                timeout=60,
                env=env,
            ),
        )
        screen = top.stdout.decode(errors="replace")
        assert top.returncode == 0, top.stderr.decode(errors="replace")[-500:]
        assert "fleet" in screen, f"no fleet line in pio top output:\n{screen}"
        assert "1/2 up" in screen or "2/2 up" in screen, screen
        # 4. supervisor restart + readmission closes the loop
        await wait_for(
            lambda: _is(healthy_count, 2),
            "restarted replica never readmitted",
            120.0,
        )
        # 6. elasticity smoke (ISSUE 13): one deterministic scale cycle
        # through the autoscaler's apply funnel — spawn-at-runtime,
        # probe-gated admission, drain-based retire, ring records
        from predictionio_tpu.fleet.autoscaler import (
            Autoscaler,
            AutoscalerConfig,
            Decision,
            SCALE_IN,
            SCALE_OUT,
            ScalingPolicy,
        )

        extra_port = _free_port()

        def spec_factory(worker_class: str) -> WorkerSpec:
            return WorkerSpec("w2", extra_port, worker_class)

        auto = Autoscaler(
            ScalingPolicy(AutoscalerConfig(min_replicas=1, max_replicas=3)),
            sup,
            gw,
            spec_factory,
            ring=obs["telemetry"],
            metrics=metrics,
            incidents=obs["incidents"],
        )
        auto.apply(Decision(SCALE_OUT, "ci-smoke", "device", 1))
        assert len(sup.live_specs()) == 3, "scale-out spawned no worker"
        await wait_for(
            lambda: _is(healthy_count, 3),
            "scaled-out replica never became routable",
            120.0,
        )
        for i in range(10):
            assert await query(200 + i) == 200, "fleet failed after scale-out"
        # scale-in: gateway stops routing first, then the worker drains —
        # traffic through the cycle must stay failure-free
        auto.apply(Decision(SCALE_IN, "ci-smoke", "device", 1))
        failures = 0
        for i in range(20):
            if await query(300 + i) != 200:
                failures += 1
        assert failures == 0, f"{failures}/20 queries failed during scale-in"
        async def live_count() -> int:
            return len(sup.live_specs()) + sum(
                1 for w in sup.snapshot() if w["retiring"]
            )

        await wait_for(
            lambda: _is(live_count, 2), "retired worker never reaped", 30.0
        )
        scaling = [
            r
            for r in obs["telemetry"].records()
            if r.get("kind") == "scaling"
        ]
        actions = [r["decision"]["action"] for r in scaling]
        assert SCALE_OUT in actions and SCALE_IN in actions, (
            f"scaling decisions missing from the telemetry ring: {actions}"
        )
        # the retired replica's live-set series dropped from /metrics
        async with session.get(f"{gw_url}/metrics") as resp:
            exposition = await resp.text()
        retired_lines = [
            line
            for line in exposition.splitlines()
            if line.startswith(
                ("pio_fleet_replica_up", "pio_fleet_worker_up")
            )
            and (f":{extra_port}" in line or 'replica="w2"' in line)
        ]
        assert retired_lines == [], (
            f"retired replica still in the exposition: {retired_lines}"
        )
        # 5. incident-bundle smoke (ISSUE 11): the kill left a bundle
        # with the dead worker's stderr tail and a merged two-tier trace
        from predictionio_tpu.obs.incidents import list_bundles, load_bundle

        inc_dir = os.path.join(obs_dir, "incidents")
        crash = [
            r for r in list_bundles(inc_dir) if r.trigger == "worker-crash"
        ]
        assert crash, "SIGKILL produced no worker-crash incident bundle"
        bundle = load_bundle(inc_dir, crash[0].bundle_id)
        tail = bundle["texts"].get("stderr_tail", "")
        assert "fleet smoke worker serving" in tail, (
            f"bundle missing the dead worker's stderr tail: {tail!r}"
        )
        tiers_by_tid: dict = {}
        for s in bundle["parts"]["traces"]:
            tiers_by_tid.setdefault(s.get("traceId"), set()).add(
                "gateway" if s.get("source") == "gateway" else "replica"
            )
        assert any(
            t == {"gateway", "replica"} for t in tiers_by_tid.values()
        ), "no merged gateway+replica trace in the incident bundle"
        print(
            json.dumps(
                {
                    "fleet_smoke": "ok",
                    "replicas": 2,
                    "killed": victim["name"],
                    "restarts": sup.snapshot()[1]["restarts"],
                    "top_screen_has_fleet_line": True,
                    "incident_bundle": crash[0].bundle_id,
                    "incident_has_stderr_tail": True,
                    "incident_has_merged_trace": True,
                    "elastic_cycle": "ok",
                    "elastic_scaling_actions": actions,
                }
            )
        )
        return 0
    finally:
        sup_task.cancel()
        await asyncio.gather(sup_task, return_exceptions=True)
        await session.close()
        await gw.stop()
        await asyncio.get_running_loop().run_in_executor(None, sup.stop)
        obs["telemetry"].close()


async def _is(fn, expect) -> bool:
    return (await fn()) == expect


def main() -> int:
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        return worker_main(int(sys.argv[2]))
    with tempfile.TemporaryDirectory(prefix="pio_fleet_smoke_obs_") as obs_dir:
        return asyncio.run(orchestrate(obs_dir))


if __name__ == "__main__":
    raise SystemExit(main())
