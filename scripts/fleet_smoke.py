"""CI fleet smoke (run_lint.sh --ci): 2 workers + gateway, kill one.

Self-contained (no training, no shared storage): each worker is THIS
script in ``--worker`` mode serving the recommendation engine over
random factors — latency/availability smoke only, model quality is the
bench's job. The orchestrator spawns the workers under the fleet
supervisor, fronts them with the gateway, then:

1. proves the fleet answers through the gateway;
2. SIGKILLs one worker and asserts the gateway KEEPS answering
   (ejection + failover, zero client-visible failures);
3. asserts ``pio top --fleet`` renders the fleet line from the
   gateway's federated /metrics;
4. waits for the supervisor restart + gateway readmission.

Exit 0 = all held; any assertion exits nonzero and fails CI.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_main(port: int) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.models.recommendation import engine_factory
    from predictionio_tpu.models.recommendation.engine import ALSModel
    from predictionio_tpu.workflow.create_server import (
        QueryServer,
        ServerConfig,
    )
    from predictionio_tpu.workflow.engine_loader import EngineManifest

    rng = np.random.default_rng(0)
    n_users, n_items, rank = 2000, 1000, 16
    model = ALSModel(
        rng.normal(size=(n_users, rank)).astype("float32"),
        rng.normal(size=(n_items, rank)).astype("float32"),
        [f"u{i}" for i in range(n_users)],
        [f"i{i}" for i in range(n_items)],
    )
    engine = engine_factory()
    ep = engine.engine_params_from_variant(
        {
            "datasource": {"params": {"appName": "fleetsmoke"}},
            "algorithms": [{"name": "als", "params": {}}],
        }
    )
    storage = Storage(
        env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )
    server = QueryServer(
        engine=engine,
        engine_params=ep,
        models=[model],
        manifest=EngineManifest(
            engine_id="fleetsmoke",
            version="1",
            variant="engine.json",
            engine_factory="predictionio_tpu.models.recommendation.engine_factory",
        ),
        instance_id="fleetsmoke",
        storage=storage,
        config=ServerConfig(ip="127.0.0.1", port=port, max_batch_size=32),
    )

    async def run() -> None:
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGTERM, server.begin_drain)
        except (NotImplementedError, RuntimeError):
            pass
        await server.run_until_stopped()

    asyncio.run(run())
    return 0


async def orchestrate() -> int:
    import aiohttp

    from predictionio_tpu.fleet import (
        Gateway,
        GatewayConfig,
        Supervisor,
        SupervisorConfig,
        WorkerSpec,
    )
    from predictionio_tpu.obs.metrics import MetricsRegistry

    specs = [WorkerSpec(f"w{i}", _free_port()) for i in range(2)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def spawn(spec):
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker", str(spec.port)],
            env=env,
            cwd=REPO,
        )

    metrics = MetricsRegistry()
    sup = Supervisor(
        spawn,
        specs,
        SupervisorConfig(poll_interval_s=0.1, backoff_base_s=0.2, term_grace_s=8.0),
        metrics=metrics,
    )
    gw_port = _free_port()
    gw = Gateway(
        GatewayConfig(
            ip="127.0.0.1",
            port=gw_port,
            replica_urls=tuple(s.url for s in specs),
            probe_interval_s=0.2,
            probe_timeout_s=1.0,
            request_timeout_s=8.0,
        ),
        metrics=metrics,
    )
    gw_url = f"http://127.0.0.1:{gw_port}"
    sup.start()
    sup_task = asyncio.ensure_future(sup.run())
    await gw.start()
    session = aiohttp.ClientSession(timeout=aiohttp.ClientTimeout(total=10))

    async def healthy_count() -> int:
        async with session.get(f"{gw_url}/healthz") as resp:
            return (await resp.json()).get("replicasHealthy", 0)

    async def wait_for(cond, message: str, deadline_s: float) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                if await cond():
                    return
            except Exception:
                pass
            assert time.monotonic() < deadline, message
            await asyncio.sleep(0.2)

    async def query(i: int) -> int:
        async with session.post(
            f"{gw_url}/queries.json", json={"user": f"u{i % 50}", "num": 5}
        ) as resp:
            await resp.read()
            return resp.status

    try:
        # 1. both workers come up (each pays the jax import)
        await wait_for(
            lambda: _is(healthy_count, 2), "workers never became ready", 120.0
        )
        for i in range(10):
            assert await query(i) == 200, "fleet did not answer pre-kill"
        # 2. SIGKILL one worker; the gateway must keep answering
        victim = sup.snapshot()[1]
        os.kill(victim["pid"], signal.SIGKILL)
        await wait_for(
            lambda: _is(healthy_count, 1), "dead replica never ejected", 10.0
        )
        failures = 0
        for i in range(20):
            if await query(100 + i) != 200:
                failures += 1
        assert failures == 0, f"{failures}/20 queries failed after replica kill"
        # 3. pio top --fleet renders from the federated scrape (run OFF
        # the event loop: the gateway serves /metrics on this very loop)
        top = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: subprocess.run(
                [
                    os.path.join(REPO, "pio"),
                    "top",
                    "--fleet",
                    "--once",
                    "--url",
                    gw_url,
                ],
                capture_output=True,
                timeout=60,
                env=env,
            ),
        )
        screen = top.stdout.decode(errors="replace")
        assert top.returncode == 0, top.stderr.decode(errors="replace")[-500:]
        assert "fleet" in screen, f"no fleet line in pio top output:\n{screen}"
        assert "1/2 up" in screen or "2/2 up" in screen, screen
        # 4. supervisor restart + readmission closes the loop
        await wait_for(
            lambda: _is(healthy_count, 2),
            "restarted replica never readmitted",
            120.0,
        )
        print(
            json.dumps(
                {
                    "fleet_smoke": "ok",
                    "replicas": 2,
                    "killed": victim["name"],
                    "restarts": sup.snapshot()[1]["restarts"],
                    "top_screen_has_fleet_line": True,
                }
            )
        )
        return 0
    finally:
        sup_task.cancel()
        await asyncio.gather(sup_task, return_exceptions=True)
        await session.close()
        await gw.stop()
        await asyncio.get_running_loop().run_in_executor(None, sup.stop)


async def _is(fn, expect) -> bool:
    return (await fn()) == expect


def main() -> int:
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        return worker_main(int(sys.argv[2]))
    return asyncio.run(orchestrate())


if __name__ == "__main__":
    raise SystemExit(main())
