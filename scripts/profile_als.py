"""Op-level attribution of the ALS solver from an XLA profiler trace.

Round-4 verdict task #3: the ~0.5 s/iter ML-20M solver is *claimed*
gather-bound; this script produces the evidence. It trains ALS twice
(cold run compiles; the traced run is warm), captures a profiler trace
of the warm train, then aggregates the trace's XLA op events into a
top-N table by total device time — enough to show whether gathers /
scatters / einsums / CG matvecs dominate the iteration.

Usage (on the TPU; CPU works for plumbing checks):

    python scripts/profile_als.py --scale ml1m --iterations 3 \
        --trace-dir /tmp/als_trace

Prints the table and writes it as markdown next to the trace. Cite the
output in docs/PERF.md once captured on hardware.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run_and_trace(scale: str, iterations: int, trace_dir: str) -> dict:
    import numpy as np

    sys.path.insert(0, REPO)
    # must precede the jax import: with JAX_PLATFORMS=cpu on a tunnel host,
    # the out-of-tree plugin's registration can hang on a wedged tunnel
    from predictionio_tpu.utils.platform import ensure_cpu_if_requested

    ensure_cpu_if_requested()
    from bench import _scale_params, synthesize_ratings
    from predictionio_tpu.ops.als import ALSConfig, als_train

    import jax

    _, n_users, n_items, n_ratings, rank, _ = _scale_params("cpu")
    if scale:
        os.environ["PIO_BENCH_SCALE"] = scale
        _, n_users, n_items, n_ratings, rank, _ = _scale_params("tpu")
    users, items, vals = synthesize_ratings(n_users, n_items, n_ratings)
    cfg = ALSConfig(rank=rank, iterations=iterations, reg=0.05, chunk=65536)
    print(f"[profile] cold train (compile), scale={scale} it={iterations}")
    als_train(users, items, vals, n_users, n_items, cfg)
    print("[profile] warm train under trace")
    timings: dict = {}
    with jax.profiler.trace(trace_dir):
        als_train(users, items, vals, n_users, n_items, cfg, timings=timings)
    print(f"[profile] timings: { {k: round(v, 3) if isinstance(v, float) else v for k, v in timings.items()} }")
    return timings


def attribute(
    trace_dir: str, top_n: int | None = 30
) -> list[tuple[str, float, int]]:
    """Aggregate XLA op events from the newest .trace.json.gz under
    trace_dir; returns [(op_name, total_ms, count)] sorted by total.

    Only DEVICE-lane events are summed when the trace has device process
    lanes (process_name metadata matching TPU/device); host runtime rows
    also carry ``dur`` and would otherwise swamp the op table. Falls back
    to all lanes (with a notice) for traces without device lanes (CPU)."""
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        raise SystemExit(f"no .trace.json.gz under {trace_dir}")
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    proc_names: dict[object, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            proc_names[ev.get("pid")] = str(
                (ev.get("args") or {}).get("name", "")
            )
    device_pids = {
        pid
        for pid, nm in proc_names.items()
        if any(tag in nm.lower() for tag in ("tpu", "device", "accelerator"))
    }
    if not device_pids:
        print(
            "[profile] no device lanes in trace "
            f"({sorted(set(proc_names.values()))}); aggregating ALL lanes",
            file=sys.stderr,
        )
    totals: dict[str, float] = defaultdict(float)
    counts: dict[str, int] = defaultdict(int)
    for ev in events:
        dur = ev.get("dur")  # microseconds
        name = ev.get("name")
        if not dur or not name:
            continue
        if device_pids and ev.get("pid") not in device_pids:
            continue
        totals[name] += dur / 1000.0
        counts[name] += 1
    rows = sorted(totals.items(), key=lambda kv: -kv[1])
    if top_n is not None:
        rows = rows[:top_n]
    return [(name, ms, counts[name]) for name, ms in rows]


_CATEGORIES = (
    ("gather", ("gather",)),
    ("scatter", ("scatter",)),
    ("matmul", ("dot", "einsum", "conv")),
    ("sort", ("sort",)),
    ("collective", ("all-reduce", "all-gather", "all-to-all", "ppermute",
                    "reduce-scatter", "collective")),
    ("copy/transpose", ("copy", "transpose", "bitcast", "reshape")),
    ("fusion (opaque)", ("fusion",)),
)


def categorize(rows: list[tuple[str, float, int]]) -> list[tuple[str, float]]:
    """Roll op rows up into coarse buckets by root op name — the one-line
    answer to 'is the iteration gather-bound?'. Fused ops stay opaque
    (XLA hides their internals) but fusion names usually embed the
    dominant op on TPU traces."""
    buckets: dict[str, float] = defaultdict(float)
    for name, ms, _ in rows:
        low = name.lower()
        for cat, keys in _CATEGORIES:
            if any(k in low for k in keys):
                buckets[cat] += ms
                break
        else:
            buckets["other"] += ms
    return sorted(buckets.items(), key=lambda kv: -kv[1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", default="", help="ml100k|ml1m|ml20m (default: cpu-scale)")
    ap.add_argument("--iterations", type=int, default=3)
    ap.add_argument("--trace-dir", default="/tmp/als_trace")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--skip-train", action="store_true",
                    help="only parse an existing trace")
    args = ap.parse_args()

    if not args.skip_train:
        run_and_trace(args.scale, args.iterations, args.trace_dir)
    all_rows = attribute(args.trace_dir, top_n=None)
    rows = all_rows[: args.top]
    top_ms = sum(ms for _, ms, _ in rows)
    lines = [
        "| op | total ms | calls | % of top-N |",
        "|---|---|---|---|",
    ]
    for name, ms, cnt in rows:
        lines.append(
            f"| `{name[:80]}` | {ms:.1f} | {cnt} | {100.0 * ms / top_ms:.1f}% |"
        )
    # the category verdict must cover ALL rows, not the top-N: a long tail
    # of small gathers below rank N is exactly the gather-bound signature
    total_ms = sum(ms for _, ms, _ in all_rows)
    cat_lines = ["", "| category | total ms | % of all |", "|---|---|---|"]
    for cat, ms in categorize(all_rows):
        cat_lines.append(f"| {cat} | {ms:.1f} | {100.0 * ms / total_ms:.1f}% |")
    table = "\n".join(lines) + "\n" + "\n".join(cat_lines)
    print(table)
    out_md = os.path.join(args.trace_dir, "attribution.md")
    with open(out_md, "w") as f:
        f.write(f"# ALS op-level attribution (scale={args.scale or 'cpu'})\n\n")
        f.write(table + "\n")
    print(f"\n[profile] wrote {out_md}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
