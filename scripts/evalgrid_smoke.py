#!/usr/bin/env python
"""CI smoke for the evaluation grid (ISSUE 15, docs/evaluation.md).

Proves the two acceptance rails end to end on a tiny corpus, with REAL
process death in the loop:

1. a 2 params × 2 folds grid runs to completion and its winner is staged
   as a registry CANDIDATE carrying the grid evidence, and
2. a run SIGKILLed mid-grid, resumed with ``--resume``, retrains ZERO
   finished cells (the durable ledger is the resume contract).

Parent mode orchestrates; ``--child`` mode runs the grid in a separate OS
process so the SIGKILL is a real kill (no atexit, no finally blocks).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from predictionio_tpu.controller import (  # noqa: E402
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Engine,
    EngineParams,
    Params,
)
from predictionio_tpu.eval import AverageMetric, Evaluation  # noqa: E402

N_FOLDS = 2
N_PARAMS = 2


@dataclasses.dataclass(frozen=True)
class SmokeParams(Params):
    weight: float = 1.0


class SmokeDataSource(BaseDataSource):
    def read_training(self, ctx):
        return list(range(20))

    def read_eval(self, ctx):
        for fold in range(N_FOLDS):
            yield list(range(20)), {"fold": fold}, [
                (i, i) for i in range(6)
            ]


class SmokePreparator(BasePreparator):
    def prepare(self, ctx, td):
        return td


class SmokeAlgo(BaseAlgorithm):
    params_class = SmokeParams
    params: SmokeParams

    def train(self, ctx, pd):
        time.sleep(float(os.environ.get("EG_SMOKE_SLEEP", "0")))
        return {"weight": self.params.weight}

    def predict(self, model, query):
        return query * model["weight"]


class SmokeServing(BaseServing):
    def serve(self, query, predictions):
        return predictions[0]


class SmokeMetric(AverageMetric):
    def calculate_score(self, ei, q, p, a) -> float:
        return float(p)


def smoke_params(weight: float) -> EngineParams:
    return EngineParams(
        data_source=("", None),
        preparator=("", None),
        algorithms=[("", SmokeParams(weight=weight))],
        serving=("", None),
    )


def make_engine() -> Engine:
    return Engine(SmokeDataSource, SmokePreparator, SmokeAlgo, SmokeServing)


def make_evaluation() -> Evaluation:
    return Evaluation(
        engine=make_engine(),
        metric=SmokeMetric(),
        engine_params_generator=[smoke_params(1.0), smoke_params(3.0)],
    )


def _manifest():
    from predictionio_tpu.workflow.engine_loader import EngineManifest

    return EngineManifest(
        engine_id="evalgrid-smoke",
        version="1",
        variant="engine.json",
        engine_factory="scripts.evalgrid_smoke.make_engine",
        description="",
        variant_json={},
        engine_dir=".",
    )


def child(workdir: str, registry_dir: str, resume: bool) -> int:
    from predictionio_tpu.tuning import run_grid

    report = run_grid(
        make_evaluation(),
        workdir=workdir,
        workers=0,
        resume=resume,
        publish=resume,  # the resumed run ships the winner
        registry_dir=registry_dir,
        engine_manifest=_manifest() if resume else None,
        stage_fraction=0.5,
    )
    print("CHILD_REPORT " + json.dumps(report.to_json_dict()))
    return 0


def _ledger_lines(path: str) -> int:
    if not os.path.exists(path):
        return 0
    n = 0
    with open(path) as fh:
        for line in fh:
            try:
                json.loads(line)
                n += 1
            except ValueError:
                pass
    return n


def main() -> int:
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        workdir, registry_dir, resume = (
            sys.argv[i + 1],
            sys.argv[i + 2],
            "--resume" in sys.argv,
        )
        return child(workdir, registry_dir, resume)

    tmp = tempfile.mkdtemp(prefix="pio_evalgrid_smoke_")
    workdir = os.path.join(tmp, "grid")
    registry_dir = os.path.join(tmp, "registry")
    env = {
        k: v for k, v in os.environ.items() if not k.startswith("PIO_STORAGE_")
    }
    env.update({"PIO_FS_BASEDIR": os.path.join(tmp, "store"),
                "JAX_PLATFORMS": "cpu"})

    # a v1 stable to canary the grid winner against
    os.environ.update(env)
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.workflow.core_workflow import run_train

    storage = Storage(env=env)
    run_train(
        make_engine(),
        _manifest(),
        smoke_params(1.0),
        storage=storage,
        registry_dir=registry_dir,
    )

    # run 1: SIGKILL mid-grid (1 ledger line = at least one finished cell)
    ledger = os.path.join(workdir, "ledger.jsonl")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child", workdir,
         registry_dir],
        env={**env, "EG_SMOKE_SLEEP": "0.8"},
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    deadline = time.monotonic() + 120
    try:
        while _ledger_lines(ledger) < 1:
            if proc.poll() is not None:
                print("grid finished before the kill:", file=sys.stderr)
                print(proc.stdout.read().decode(errors="replace")[-2000:],
                      file=sys.stderr)
                return 1
            if time.monotonic() > deadline:
                print("no ledger line in 120s", file=sys.stderr)
                proc.kill()
                return 1
            time.sleep(0.05)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    finished = _ledger_lines(ledger)
    total = N_PARAMS * N_FOLDS
    assert 1 <= finished < total, finished

    # run 2: --resume completes, retraining zero finished cells, and
    # stages the winner as a candidate with the grid evidence
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", workdir,
         registry_dir, "--resume"],
        env={**env, "EG_SMOKE_SLEEP": "0"},
        capture_output=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stdout.decode()[-2000:] + out.stderr.decode()[-2000:]
    report = json.loads(
        next(
            line for line in out.stdout.decode().splitlines()
            if line.startswith("CHILD_REPORT ")
        ).split(" ", 1)[1]
    )
    assert report["cells_total"] == total
    assert report["cells_skipped"] == finished, report
    assert report["cells_run"] == total - finished, report
    assert report["best_params_index"] == 1  # weight 3.0 wins

    from predictionio_tpu.registry import ArtifactStore

    store = ArtifactStore(registry_dir)
    state = store.get_state("evalgrid-smoke")
    assert state.stable == "v000001", state
    assert state.candidate == report["published_version"] == "v000002", state
    evidence = store.get_manifest("evalgrid-smoke", "v000002").eval_evidence
    assert evidence["cellsTotal"] == total
    assert evidence["ledgerSha256"] == report["ledger_sha256"]
    print(
        f"evalgrid smoke: SIGKILL after {finished}/{total} cells -> resume "
        f"retrained {report['cells_run']} (zero finished cells), winner "
        f"v000002 staged as candidate with grid evidence"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
