#!/usr/bin/env bash
# Tier-1 lint gate: run the TPU-aware static analyzer over the package and
# examples. Exits nonzero on any unsuppressed error-severity finding.
# Usage: scripts/run_lint.sh [extra lint args...]
set -euo pipefail

repo_root="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$repo_root"

exec python -m predictionio_tpu.analysis.cli "$@"
