#!/usr/bin/env bash
# Tier-1 lint gate: run the TPU-aware static analyzer over the package and
# examples. Exits nonzero on any unsuppressed error-severity finding.
# Usage: scripts/run_lint.sh [extra lint args...]
#        scripts/run_lint.sh --ci   # CI entry point: lint + perf gate + chaos
set -euo pipefail

repo_root="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$repo_root"

if [[ "${1:-}" == "--ci" ]]; then
  shift
  python -m predictionio_tpu.analysis.cli "$@"

  # --- lint artifacts (ISSUE 16): machine-readable SARIF for code-scanning
  #     upload, the git-scoped mode PR branches use (whole-program call
  #     graph, only changed files reported), and the suppression inventory
  #     (every pio-lint disable site with its reason; stale ones warn in
  #     the main pass above).
  python -m predictionio_tpu.analysis.cli --format sarif > /tmp/pio_lint.sarif
  python - <<'PYEOF'
import json
d = json.load(open("/tmp/pio_lint.sarif"))
assert d["version"] == "2.1.0", d["version"]
assert d["runs"][0]["tool"]["driver"]["name"] == "pio-lint"
print(f"sarif artifact: {len(d['runs'][0]['results'])} result(s), "
      f"{len(d['runs'][0]['tool']['driver']['rules'])} rules declared")
PYEOF
  python -m predictionio_tpu.analysis.cli --changed
  python -m predictionio_tpu.analysis.cli --report-suppressions \
    > /tmp/pio_lint_suppressions.txt
  echo "suppression inventory: $(tail -n 1 /tmp/pio_lint_suppressions.txt)"

  # --- perf-regression gate (docs/observability.md, ROADMAP item 5) -------
  # 1. the gate must PASS an unchanged run ...
  baseline="tests/fixtures/bench_baseline.json"
  python bench.py --compare "$baseline" --current "$baseline" \
    > /tmp/pio_compare_same.json
  # 2. ... and TRIP on an injected slowdown (latencies doubled, qps halved)
  python - "$baseline" > /tmp/pio_bench_regressed.json <<'PYEOF'
import json, sys
d = json.load(open(sys.argv[1]))
for k, v in list(d.items()):
    if isinstance(v, (int, float)) and (k.endswith("_ms") or k.endswith("_qps")):
        d[k] = v * 2.0 if k.endswith("_ms") else v / 2.0
print(json.dumps(d))
PYEOF
  if python bench.py --compare "$baseline" --current /tmp/pio_bench_regressed.json \
      > /tmp/pio_compare_regressed.json; then
    echo "perf-regression gate FAILED to trip on an injected slowdown" >&2
    exit 1
  fi
  echo "perf-regression gate: passes unchanged run, trips injected slowdown"

  # --- capacity-planner self-check (pio doctor; docs/observability.md) ----
  # the planner must PASS a plan that fits ...
  ./pio doctor --capacity 100000 50000 16 --hbm-bytes 16GB \
    > /tmp/pio_doctor_fit.json
  # ... and EXIT NONZERO on one that exceeds the budget
  if ./pio doctor --capacity 10000000 1000000 128 --hbm-bytes 1MB \
      > /tmp/pio_doctor_over.json 2>/dev/null; then
    echo "pio doctor --capacity FAILED to flag an over-budget plan" >&2
    exit 1
  fi
  echo "capacity planner: fits within budget, trips over budget"

  # --- profiled CPU train smoke: the xray tiling contract end to end ------
  env JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np
from predictionio_tpu.obs import xray
from predictionio_tpu.ops.als import ALSConfig, als_train

rng = np.random.default_rng(0)
u = rng.integers(0, 300, 4000).astype(np.int32)
i = rng.integers(0, 200, 4000).astype(np.int32)
r = rng.normal(3.0, 1.0, 4000).astype(np.float32)
prof = xray.TrainProfile("ci-smoke")
with xray.use_profile(prof), prof.measure():
    als_train(u, i, r, 300, 200, ALSConfig(rank=8, iterations=3, chunk=1024))
pj = prof.finish().to_json_dict()
assert pj["steps"] == 3, pj["steps"]
ratio = pj["attributedS"] / pj["wallClockS"]
assert 0.9 <= ratio <= 1.001, f"tiling contract broken in CI: {ratio:.3f}"
assert pj["deviceS"] > 0.0
print(
    f"profiled train smoke: wall {pj['wallClockS']:.2f}s, "
    f"attributed {100*ratio:.1f}%, device frac {pj['deviceTimeFrac']:.2f}, "
    f"peak/dev {pj['memory']['peakBytesPerDevice']} B"
)
PYEOF

  # 3. a CPU-only bench smoke: the serving_local phase drives the real
  #    QueryServer over loopback and records the full phase waterfall —
  #    proving the evidence chain end to end on every CI run.
  #    --no-compare: the smoke's own gate (next step) runs with a
  #    noise-tolerant threshold, not the strict full-round default
  env JAX_PLATFORMS=cpu PIO_BENCH_SCALE=ml100k \
    python bench.py --cpu-only --no-compare --only serving_local \
    > /tmp/pio_bench_smoke.json
  echo "bench smoke: $(tail -c 300 /tmp/pio_bench_smoke.json)"

  # 4. the device-bound-serving gate (ISSUE 8): the smoke's fetch-phase
  #    p50 (and the other p50/qps fields it shares with the fixture) must
  #    stay under the checked-in pre-fused-top-k baseline — the O(batch*k)
  #    fetch contract is held by measurement on every CI run. p95s are
  #    excluded (shared-CI-host tail noise) and the tolerance is wide: the
  #    full-fetch regression this guards is a step change, not jitter.
  python - "$baseline" > /tmp/pio_smoke_baseline.json <<'PYEOF'
import json, sys
d = json.load(open(sys.argv[1]))
keep = {
    k: v for k, v in d.items()
    if k.endswith("_p50_ms") or k.endswith("_qps")
}
print(json.dumps(keep))
PYEOF
  if ! python bench.py --compare /tmp/pio_smoke_baseline.json \
      --current /tmp/pio_bench_smoke.json --compare-tolerance 1.0 \
      > /tmp/pio_compare_smoke.json; then
    echo "serving smoke regressed vs checked-in baseline:" >&2
    tail -c 600 /tmp/pio_compare_smoke.json >&2
    exit 1
  fi
  echo "serving smoke vs baseline: $(tail -c 240 /tmp/pio_compare_smoke.json)"

  # --- batchpredict smoke (ISSUE 14, docs/batch_predict.md): the offline
  #     mega-batch pipeline on the same CPU backend must beat the serving
  #     smoke's online qps by >= 3x (the full-round gate in bench.py is
  #     5x; the CI floor is looser for shared-host noise), its
  #     read->assemble->dispatch->fetch->write timeline must tile the run
  #     wall clock, and `pio top --batchpredict` must render the progress
  #     line from the run's status file.
  env JAX_PLATFORMS=cpu PIO_BENCH_SCALE=ml100k \
    python bench.py --cpu-only --no-compare --only batchpredict \
    > /tmp/pio_bench_bp.json
  bp_status=$(python - <<'PYEOF'
import json
def last_json(path):
    for line in reversed(open(path).read().strip().splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"no JSON line in {path}")
bp = last_json("/tmp/pio_bench_bp.json")
sv = last_json("/tmp/pio_bench_smoke.json")
off, on = bp["batchpredict_offline_qps"], sv["serving_local_e2e_qps"]
assert bp["batchpredict_errors"] == 0, bp["batchpredict_errors"]
assert bp["batchpredict_tiling_gate_ok"], bp["batchpredict_tiling_ratio"]
assert off >= 3.0 * on, f"offline {off} q/s < 3x online {on} q/s"
import sys
print(
    f"batchpredict smoke: offline {off:.0f} q/s vs online {on:.0f} q/s "
    f"({off / on:.1f}x), phases tile ({bp['batchpredict_tiling_ratio']:.3f})",
    file=sys.stderr,
)
print(bp["batchpredict_status_file"])
PYEOF
  )
  # plain grep (not -q): -q exits at first match and SIGPIPEs the still-
  # writing renderer, which pipefail then reports as a stage failure
  if ! ./pio top --batchpredict "$bp_status" --once | grep "batchpredict" >/dev/null; then
    echo "pio top --batchpredict did not render the progress line" >&2
    exit 1
  fi
  echo "pio top --batchpredict renders from the run's status file"

  # --- evalgrid smoke (ISSUE 15, docs/evaluation.md): 2 params x 2 folds
  #     on a tiny corpus with a REAL SIGKILL mid-grid — the resumed run
  #     must retrain zero finished cells (the durable-ledger contract)
  #     and stage the winner as a registry candidate carrying the grid
  #     evidence (scores table + ledger sha). The lint pass above already
  #     holds the scoring-path rails statically (serving-host-roundtrip /
  #     train-unaccounted-sync / eval-per-query-predict over tuning/).
  env JAX_PLATFORMS=cpu python scripts/evalgrid_smoke.py

  # --- lifecycle smoke (ISSUE 19, docs/lifecycle.md): one full
  #     self-driving loop with zero human commands after setup — a
  #     scheduled cadence trigger fires, the REAL eval grid runs on
  #     cpu-fallback workers and stages its winner as a registry
  #     CANDIDATE, the bake resolves to a promote, the controller warms
  #     the result cache over a real HTTP socket, and the episode closes
  #     PROMOTED with every transition on the telemetry ring and `pio
  #     lifecycle status` rendering the durable state file. The
  #     drift-triggered + SIGKILL-resume rails run in the chaos gate
  #     (tests/test_lifecycle.py e2e).
  env JAX_PLATFORMS=cpu python scripts/lifecycle_smoke.py

  # --- ANN smoke (ISSUE 10, docs/ann.md): build a small clustered index,
  #     serve a real engine through it via the registry attach path, and
  #     hold the two acceptance rails by measurement: recall@10 >= 0.95
  #     vs exact at <=10% of the corpus scored, and the exact path still
  #     answering when no index is pinned (the fallback default).
  env JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np, tempfile
from predictionio_tpu.ann import AnnConfig
from predictionio_tpu.ann import lifecycle
from predictionio_tpu.models.similarproduct.engine import (
    ALSAlgorithm, Query, SimilarModel,
)
from predictionio_tpu.registry import ArtifactStore, ModelManifest
from predictionio_tpu.workflow import model_io

rng = np.random.default_rng(0)
n, f = 8000, 16
modes = rng.normal(size=(48, f)); modes /= np.linalg.norm(modes, axis=1, keepdims=True)
vf = (modes[rng.integers(0, 48, n)] + 0.1 * rng.normal(size=(n, f))).astype(np.float32)
vf /= np.linalg.norm(vf, axis=1, keepdims=True)
vocab = [f"i{j}" for j in range(n)]
algo = ALSAlgorithm(None)
queries = [Query(items=(vocab[int(j)],), num=10) for j in rng.integers(0, n, 32)]

# exact-fallback rail: a model with NO index pinned answers exactly
plain = SimilarModel(vf.copy(), list(vocab), [None] * n)
exact = algo.predict_batch(plain, queries)
assert all(len(r.item_scores) == 10 for r in exact), "exact fallback broken"

with tempfile.TemporaryDirectory() as d:
    store = ArtifactStore(d)
    model = SimilarModel(vf.copy(), list(vocab), [None] * n)
    m = store.publish(
        ModelManifest(version="", engine_id="ann-smoke", engine_version="1",
                      engine_variant="v"),
        model_io.serialize_models([model]),
    )
    lifecycle.build_for_version(
        store, "ann-smoke", m.version, [model], AnnConfig(min_items=0), force=True
    )
    models = model_io.deserialize_models(store.load_blob("ann-smoke", m.version))
    serving = lifecycle.attach_from_registry(store, "ann-smoke", m.version, models)
    assert serving is not None, "index did not attach"
    ann = algo.predict_batch(models[0], queries)
    hits = total = 0
    for a, e in zip(ann, exact):
        ai = {s.item for s in a.item_scores}
        ei = [s.item for s in e.item_scores]
        hits += sum(1 for it in ei if it in ai)
        total += len(ei)
    recall = hits / total
    frac = serving.index.bucket_cap * serving.index.nprobe / n
    assert recall >= 0.95, f"ANN recall@10 {recall:.3f} < 0.95"
    assert frac <= 0.10, f"ANN candidate bound {frac:.3f} > 10% of corpus"
    print(f"ann smoke: recall@10 {recall:.3f} at <= {frac:.1%} of corpus scored, "
          f"exact fallback answers")
PYEOF

  # --- fleet smoke (ISSUEs 9+11, docs/fleet.md): 2 workers + gateway,
  #     kill one — the gateway must keep answering (ejection + failover),
  #     `pio top --fleet` must render from the federated /metrics, AND
  #     the flight recorder must capture the kill: an incident bundle
  #     with the dead worker's stderr tail and a merged gateway+replica
  #     trace (the incident-bundle smoke). The full kill-mid-ROLLOUT
  #     chaos stage lives in tests/test_fleet.py (run by the chaos gate
  #     below); this is the fast availability+evidence rail.
  env JAX_PLATFORMS=cpu python scripts/fleet_smoke.py
  echo "fleet smoke: gateway survives replica kill, pio top --fleet renders, incident bundle captured, scale-out/scale-in cycle clean"

  # --- multi-host smoke (ISSUE 17, docs/fleet.md §Multi-host): two fake-
  #     driver hosts, four workers, kill one host mid-traffic — zero
  #     failed queries, ONE host-death incident bundle carrying every
  #     dead worker's log tail (no per-worker crash bundles), pio top
  #     --fleet shows the HOST-DOWN census, and the host-aware scale-out
  #     path restores capacity on the survivor. The full kill-a-host
  #     chaos e2e (mid-ROLLOUT, lease steal from the dead holder) is the
  #     slow-marked stage in tests/test_hostrt.py, run by the chaos gate
  #     below.
  env JAX_PLATFORMS=cpu python scripts/hostrt_smoke.py
  echo "hostrt smoke: host death survived with zero failed queries, one host-death bundle, HOST-DOWN census rendered, capacity restored on survivor"

  # --- profile smoke (ISSUE 18, docs/observability.md §Profiling plane):
  #     one real CPU server with the plane on — `pio profile serve`
  #     captures a short device trace into a content-addressed bundle,
  #     the bundle lists/shows/exports through the CLI with the manifest
  #     model version matching the serving lane, /profile/stacks serves
  #     the always-on sampler's folded stacks, and `pio doctor
  #     --roofline` exits 0 with finite numbers for every bucket family.
  env JAX_PLATFORMS=cpu python scripts/profile_smoke.py

  # --- sequential+bandit smoke (ISSUE 20, docs/sequential.md +
  #     docs/bandit.md): ingest ordered sessions -> train the sequential
  #     engine THROUGH the real DataSource (find_after ordered reads) ->
  #     serve next-item queries through the fleet gateway into a real
  #     QueryServer with a Thompson bandit engaged on a staged candidate
  #     -> reward feedback events matched by trace id MOVE the candidate
  #     arm's posterior -> the reward verdict auto-promotes the winner
  #     with zero client-visible 5xx. The slow ingest->stream-fold-in->
  #     retire-loser e2e lives in tests/test_bandit.py (chaos gate).
  env JAX_PLATFORMS=cpu python scripts/sequential_smoke.py

  # chaos gate includes the observability suite (tests/test_obs.py):
  # counters moving under faults + trace propagation are CI-asserted
  exec "$repo_root/scripts/run_chaos.sh"
fi

exec python -m predictionio_tpu.analysis.cli "$@"
