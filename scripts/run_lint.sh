#!/usr/bin/env bash
# Tier-1 lint gate: run the TPU-aware static analyzer over the package and
# examples. Exits nonzero on any unsuppressed error-severity finding.
# Usage: scripts/run_lint.sh [extra lint args...]
#        scripts/run_lint.sh --ci   # CI entry point: lint + chaos suite
set -euo pipefail

repo_root="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$repo_root"

if [[ "${1:-}" == "--ci" ]]; then
  shift
  python -m predictionio_tpu.analysis.cli "$@"
  # chaos gate includes the observability suite (tests/test_obs.py):
  # counters moving under faults + trace propagation are CI-asserted
  exec "$repo_root/scripts/run_chaos.sh"
fi

exec python -m predictionio_tpu.analysis.cli "$@"
