#!/usr/bin/env bash
# Tier-1 lint gate: run the TPU-aware static analyzer over the package and
# examples. Exits nonzero on any unsuppressed error-severity finding.
# Usage: scripts/run_lint.sh [extra lint args...]
#        scripts/run_lint.sh --ci   # CI entry point: lint + perf gate + chaos
set -euo pipefail

repo_root="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$repo_root"

if [[ "${1:-}" == "--ci" ]]; then
  shift
  python -m predictionio_tpu.analysis.cli "$@"

  # --- perf-regression gate (docs/observability.md, ROADMAP item 5) -------
  # 1. the gate must PASS an unchanged run ...
  baseline="tests/fixtures/bench_baseline.json"
  python bench.py --compare "$baseline" --current "$baseline" \
    > /tmp/pio_compare_same.json
  # 2. ... and TRIP on an injected slowdown (latencies doubled, qps halved)
  python - "$baseline" > /tmp/pio_bench_regressed.json <<'PYEOF'
import json, sys
d = json.load(open(sys.argv[1]))
for k, v in list(d.items()):
    if isinstance(v, (int, float)) and (k.endswith("_ms") or k.endswith("_qps")):
        d[k] = v * 2.0 if k.endswith("_ms") else v / 2.0
print(json.dumps(d))
PYEOF
  if python bench.py --compare "$baseline" --current /tmp/pio_bench_regressed.json \
      > /tmp/pio_compare_regressed.json; then
    echo "perf-regression gate FAILED to trip on an injected slowdown" >&2
    exit 1
  fi
  echo "perf-regression gate: passes unchanged run, trips injected slowdown"
  # 3. a CPU-only bench smoke: the serving_local phase drives the real
  #    QueryServer over loopback and records the full phase waterfall —
  #    proving the evidence chain end to end on every CI run
  env JAX_PLATFORMS=cpu PIO_BENCH_SCALE=ml100k \
    python bench.py --cpu-only --only serving_local > /tmp/pio_bench_smoke.json
  echo "bench smoke: $(tail -c 300 /tmp/pio_bench_smoke.json)"

  # chaos gate includes the observability suite (tests/test_obs.py):
  # counters moving under faults + trace propagation are CI-asserted
  exec "$repo_root/scripts/run_chaos.sh"
fi

exec python -m predictionio_tpu.analysis.cli "$@"
