"""Ring attention + fused attention tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from predictionio_tpu.ops.attention import (
    attention_reference,
    fused_attention,
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
)
from predictionio_tpu.parallel.mesh import make_mesh


def qkv(B=2, H=2, L=32, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, L, D)).astype(np.float32))
    return mk(), mk(), mk()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        mesh = make_mesh("sp=8")
        q, k, v = qkv()
        expected = attention_reference(q, k, v, causal=causal)
        got = ring_attention_sharded(q, k, v, mesh, axis="sp", causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)

    def test_2d_mesh_with_data_axis(self):
        mesh = make_mesh("data=2,sp=4")
        q, k, v = qkv(L=16)
        expected = attention_reference(q, k, v, causal=True)
        got = ring_attention_sharded(q, k, v, mesh, axis="sp", causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)

    def test_dp_sp_composed(self):
        # batch sharded over `data` AND sequence over `sp` in ONE shard_map
        # (dp x sp): the composition the two-tower context-parallel encoder
        # relies on — without batch_axis, GSPMD must all-gather the batch
        mesh = make_mesh("data=2,sp=4")
        q, k, v = qkv(B=4, L=16)
        expected = attention_reference(q, k, v, causal=True)
        got = ring_attention_sharded(
            q, k, v, mesh, axis="sp", causal=True, batch_axis="data"
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)

    def test_bad_length_rejected(self):
        mesh = make_mesh("sp=8")
        q, k, v = qkv(L=30)  # not divisible by 8
        with pytest.raises(ValueError):
            ring_attention_sharded(q, k, v, mesh, axis="sp")

    def test_long_sequence(self):
        mesh = make_mesh("sp=8")
        q, k, v = qkv(B=1, H=1, L=256, D=16, seed=3)
        expected = attention_reference(q, k, v, causal=True)
        got = ring_attention_sharded(q, k, v, mesh, axis="sp", causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-4)


class TestFusedAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_interpret_matches_reference(self, causal):
        q, k, v = qkv(B=1, H=2, L=16, D=8)
        expected = attention_reference(q, k, v, causal=causal)
        got = fused_attention(q, k, v, causal=causal, force_pallas=True)
        # the kernel multiplies in bf16 (f32 accumulation) — the MXU's
        # native contract; tolerance is bf16 rounding, not f32
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-2)

    def test_cpu_fallback(self):
        q, k, v = qkv(B=1, H=1, L=8, D=4)
        got = fused_attention(q, k, v)
        expected = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-6)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_tiled_kernel_matches_reference(self, causal):
        """L=1024 crosses the single-block VMEM budget, so force_pallas
        routes to the tiled flash kernel (online softmax carried across
        K-block grid steps in scratch) — the path long sequences take on
        real TPU hardware."""
        from predictionio_tpu.ops.attention import _flash_attention_pallas

        q, k, v = qkv(B=1, H=1, L=1024, D=8)
        expected = attention_reference(q, k, v, causal=causal)
        got = _flash_attention_pallas(
            q, k, v, causal=causal, interpret=True, block_q=256, block_k=256
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-2)
        # dispatch routing: force_pallas at this size must take the flash path
        got2 = fused_attention(q, k, v, causal=causal, force_pallas=True)
        np.testing.assert_allclose(np.asarray(got2), np.asarray(expected), atol=2e-2)


class TestUlyssesAttention:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses scheme) must match
    the dense reference exactly — full sequence is reconstructed per head."""

    def test_matches_reference(self):
        q, k, v = qkv(H=8, D=16)
        out = ulysses_attention(q, k, v, make_mesh("sp=8"))
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_causal_matches_reference(self):
        q, k, v = qkv(H=8, D=16, seed=1)
        out = ulysses_attention(q, k, v, make_mesh("sp=8"), causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_matches_ring(self):
        q, k, v = qkv(H=8, D=16, seed=2)
        mesh = make_mesh("sp=8")
        np.testing.assert_allclose(
            np.asarray(ulysses_attention(q, k, v, mesh, causal=True)),
            np.asarray(ring_attention(q, k, v, mesh, causal=True)),
            atol=2e-5,
        )

    def test_head_divisibility_enforced(self):
        q, k, v = qkv(H=6)  # 6 heads on 8 devices
        with pytest.raises(ValueError, match="head count"):
            ulysses_attention(q, k, v, make_mesh("sp=8"))

    def test_dp_sp_composed(self):
        # dp x sp on one 2-D mesh (see TestRingAttention.test_dp_sp_composed)
        mesh = make_mesh("data=2,sp=4")
        q, k, v = qkv(B=4, H=4, L=16, D=16, seed=3)
        out = ulysses_attention(
            q, k, v, mesh, axis="sp", causal=True, batch_axis="data"
        )
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
