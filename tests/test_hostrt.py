"""Multi-host fleet (fleet/hostrt.py + supervisor host supervision +
the shared-nothing gateway tier): inventory parsing, driver contracts,
host-death detection as ONE transition, host-aware placement, telemetry
ring writer namespacing, gateway peer fan-in — and the PR-17 acceptance
gate: a two-"host" (fake-driver) chaos e2e that kills an entire host
mid-rollout and demands zero client-visible 5xx, capacity restored on
the survivor, the registry lease surviving the dead host's held mutex,
and one host-death incident bundle carrying every dead worker's log
tail (docs/fleet.md §Multi-host)."""

from __future__ import annotations

import asyncio
import os
import signal as _signal
import socket
import sys
import time

import pytest

from predictionio_tpu.fleet.gateway import (
    Gateway,
    GatewayConfig,
    GatewayGroup,
)
from predictionio_tpu.fleet.hostrt import (
    DRIVER_CONTAINER,
    DRIVER_FAKE,
    DRIVER_LOCAL,
    DRIVER_SSH,
    ContainerHostDriver,
    FakeHostDriver,
    HostDriver,
    HostRuntime,
    HostSpec,
    LocalHostDriver,
    SshHostDriver,
    assign_hosts,
    make_driver,
    parse_hosts,
)
from predictionio_tpu.fleet.supervisor import (
    Supervisor,
    SupervisorConfig,
    WorkerSpec,
)
from predictionio_tpu.fleet.worklog import WorkerLogBook
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tsring import TelemetryRing
from tests.test_fleet import FakeClock, FakeProc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# inventory parsing + boot-time placement
# ---------------------------------------------------------------------------


class TestParseHosts:
    def test_bare_entry_means_local_driver(self):
        (h,) = parse_hosts("box:2")
        assert h == HostSpec(name="box", slots=2, driver=DRIVER_LOCAL)
        assert h.connect_ip == "127.0.0.1"

    def test_mixed_inventory(self):
        hosts = parse_hosts("local:2, ssh@node1:4 ,container@pio-img:1,fake@b:3")
        assert [h.driver for h in hosts] == [
            DRIVER_LOCAL,
            DRIVER_SSH,
            DRIVER_CONTAINER,
            DRIVER_FAKE,
        ]
        assert [h.slots for h in hosts] == [2, 4, 1, 3]

    def test_ssh_user_at_host_keeps_user_in_address_only(self):
        (h,) = parse_hosts("ssh@deploy@node1:4")
        assert h.address == "deploy@node1"  # what ssh dials
        assert h.name == "node1"  # metric label / placement identity
        assert h.connect_ip == "node1"  # where the gateway connects

    def test_container_entry_names_the_image_on_loopback(self):
        (h,) = parse_hosts("container@pio-worker:2")
        assert h.address == "pio-worker" and h.name == "pio-worker"
        assert h.connect_ip == "127.0.0.1"  # --network host

    @pytest.mark.parametrize(
        "bad",
        [
            "box",  # no slots
            "box:none",  # non-integer slots
            "box:0",  # slots must be >= 1
            "warp@box:2",  # unknown driver
            "a:1,a:2",  # duplicate names
            "@:2",  # empty host
            "",  # empty inventory
        ],
    )
    def test_malformed_entries_raise(self, bad):
        with pytest.raises(ValueError):
            parse_hosts(bad)


class TestAssignHosts:
    H = [HostSpec("a", 2), HostSpec("b", 2), HostSpec("c", 4)]

    def test_breadth_first_fills_evenly_by_load_ratio(self):
        # c has double the slots, so it absorbs workers at half the
        # ratio cost: 6 workers land 2/2/2 before anyone overfills
        got = assign_hosts(6, self.H)
        assert sorted(got) == ["a", "a", "b", "b", "c", "c"]
        assert got[0] == "a"  # ties break by name

    def test_taken_counts_preexisting_residents(self):
        got = assign_hosts(2, self.H, taken={"a": 2, "b": 2})
        assert got == ["c", "c"]

    def test_overfull_inventory_refuses_to_boot(self):
        with pytest.raises(ValueError, match="slots"):
            assign_hosts(9, self.H)


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


class TestLocalDriver:
    def test_spawn_captures_output_in_logbook(self, tmp_path):
        logbook = WorkerLogBook(str(tmp_path))
        drv = LocalHostDriver(logbook)
        host = HostSpec("local", 1)
        proc = drv.spawn(
            host, "w0", [sys.executable, "-c", "print('hello from w0')"]
        )
        assert proc.wait(timeout=30) == 0
        assert "hello from w0" in drv.fetch_log_tail(host, "w0")

    def test_probe_never_fails(self):
        assert LocalHostDriver().probe(HostSpec("local", 1))


class TestSshDriver:
    def test_remote_cmd_tags_worker_and_quotes_env(self):
        drv = SshHostDriver()
        cmd = drv._remote_cmd(
            "w1", ["python", "-m", "pio", "--x", "a b"], {"K": "v w"}
        )
        assert cmd.startswith("exec env PIO_WORKER_NAME=w1 ")
        assert "K='v w'" in cmd and "'a b'" in cmd

    def test_signal_pkills_by_worker_tag(self, monkeypatch):
        calls: list[list[str]] = []

        def fake_run(argv, **kw):
            calls.append(list(argv))

            class R:
                returncode = 0

            return R()

        monkeypatch.setattr(
            "predictionio_tpu.fleet.hostrt.subprocess.run", fake_run
        )
        drv = SshHostDriver()
        host = HostSpec("node1", 2, driver=DRIVER_SSH, address="u@node1")
        proc = FakeProc()
        proc.send_signal = lambda sig: None
        drv.signal(host, "w3", proc, _signal.SIGTERM)
        assert calls and calls[0][-2] == "u@node1"
        assert calls[0][-1] == "pkill -TERM -f PIO_WORKER_NAME=w3"

    def test_probe_false_when_ssh_unreachable(self, monkeypatch):
        def boom(argv, **kw):
            raise OSError("no ssh")

        monkeypatch.setattr(
            "predictionio_tpu.fleet.hostrt.subprocess.run", boom
        )
        assert not SshHostDriver().probe(HostSpec("gone", 1, driver=DRIVER_SSH))


class TestContainerDriver:
    def test_container_name_is_engine_safe(self):
        host = HostSpec("img:tag/x", 1, driver=DRIVER_CONTAINER)
        assert ContainerHostDriver.container_name(host, "w0") == (
            "pio-img-tag-x-w0"
        )

    def test_spawn_argv_runs_the_image(self, monkeypatch):
        argvs: list[list[str]] = []

        def fake_popen(argv, **kw):
            argvs.append(list(argv))
            return FakeProc()

        monkeypatch.setattr(
            "predictionio_tpu.fleet.hostrt.subprocess.Popen", fake_popen
        )
        drv = ContainerHostDriver(engine="docker")
        host = HostSpec(
            "pio-img", 1, driver=DRIVER_CONTAINER, address="pio-img"
        )
        drv.spawn(host, "w0", ["python", "-m", "pio"], env={"A": "1"})
        (argv,) = argvs
        assert argv[:3] == ["docker", "run", "--rm"]
        assert "pio-img" in argv and "-e" in argv and "A=1" in argv
        # image before the worker argv
        assert argv.index("pio-img") < argv.index("python")


class TestFakeDriver:
    def _sleeper(self, drv, host, name):
        return drv.spawn(
            host, name, [sys.executable, "-c", "import time; time.sleep(60)"]
        )

    def test_kill_host_kills_residents_and_fails_probe(self):
        drv = FakeHostDriver()
        ha, hb = HostSpec("ha", 2, driver=DRIVER_FAKE), HostSpec(
            "hb", 2, driver=DRIVER_FAKE
        )
        pa = self._sleeper(drv, ha, "w0")
        pb = self._sleeper(drv, hb, "w1")
        try:
            assert drv.probe(ha) and drv.probe(hb)
            assert drv.kill_host("ha") == 1
            assert pa.wait(timeout=10) == -_signal.SIGKILL
            assert pb.poll() is None  # the other host is untouched
            assert not drv.probe(ha) and drv.probe(hb)
            with pytest.raises(OSError):
                self._sleeper(drv, ha, "w2")  # dead boxes refuse spawns
            drv.revive_host("ha")
            assert drv.probe(ha)
            self._sleeper(drv, ha, "w2").kill()
        finally:
            pb.kill()
            pb.wait(timeout=10)


class TestHostRuntime:
    def test_one_shared_driver_instance_per_kind(self):
        rt = HostRuntime(
            [
                HostSpec("a", 1, driver=DRIVER_FAKE),
                HostSpec("b", 1, driver=DRIVER_FAKE),
                HostSpec("local", 1),
            ]
        )
        # the fake driver's kill switch must cover both fake hosts
        assert rt.driver_for("a") is rt.driver_for("b")
        assert rt.driver_for("local") is not rt.driver_for("a")
        assert rt.total_slots() == 3

    def test_unknown_host_raises(self):
        rt = HostRuntime([HostSpec("a", 1)])
        with pytest.raises(KeyError, match="unknown host"):
            rt.host("zz")

    def test_probe_wraps_driver_exceptions_as_down(self):
        class Exploding(HostDriver):
            def probe(self, host):
                raise RuntimeError("driver bug")

        rt = HostRuntime(
            [HostSpec("a", 1, driver=DRIVER_FAKE)],
            drivers={DRIVER_FAKE: Exploding()},
        )
        assert rt.probe("a") is False

    def test_make_driver_rejects_unknown(self):
        with pytest.raises(ValueError):
            make_driver("warp")


# ---------------------------------------------------------------------------
# supervisor host supervision (fake clock, no real processes)
# ---------------------------------------------------------------------------


class SwitchDriver(HostDriver):
    """Probe/tail/signal controlled by the test; spawning goes through
    the supervisor's own spawn callable, exactly like launch.py."""

    kind = DRIVER_FAKE

    def __init__(self):
        self.alive: dict[str, bool] = {}
        self.signals: list[tuple[str, str, int]] = []

    def signal(self, host, name, handle, sig):
        self.signals.append((host.name, name, sig))
        if sig == _signal.SIGKILL:
            handle.kill()
        else:
            handle.terminate()

    def fetch_log_tail(self, host, name, max_bytes=8192):
        return f"dying words of {name}"

    def probe(self, host):
        return self.alive.get(host.name, True)


def _host_sup(placement=("ha", "ha", "hb"), **cfg_kw):
    cfg = SupervisorConfig(
        poll_interval_s=0.1,
        backoff_base_s=1.0,
        backoff_multiplier=2.0,
        backoff_max_s=60.0,
        crash_loop_window_s=1e9,
        crash_loop_budget=99,
        healthy_reset_s=1e9,
        host_probe_interval_s=5.0,
        **cfg_kw,
    )
    clock = FakeClock()
    drv = SwitchDriver()
    rt = HostRuntime(
        [
            HostSpec("ha", 2, driver=DRIVER_FAKE),
            HostSpec("hb", 2, driver=DRIVER_FAKE),
        ],
        drivers={DRIVER_FAKE: drv},
    )
    spawned: list[FakeProc] = []

    def spawn(spec):
        p = FakeProc()
        spawned.append(p)
        return p

    deaths: list[dict] = []
    crashes: list[dict] = []
    sup = Supervisor(
        spawn,
        [
            WorkerSpec(f"w{i}", 9000 + i, host=h)
            for i, h in enumerate(placement)
        ],
        cfg,
        clock=clock,
        runtime=rt,
        on_crash=crashes.append,
        on_host_down=deaths.append,
    )
    return sup, spawned, clock, drv, deaths, crashes


class TestSupervisorHostDeath:
    def test_host_death_is_one_transition_with_every_resident(self):
        sup, spawned, clock, drv, deaths, crashes = _host_sup()
        sup.start()
        assert len(spawned) == 3
        # pull host ha's cord: both residents die in the same tick and
        # the immediate probe fails
        drv.alive["ha"] = False
        spawned[0].exit(-9)
        spawned[1].exit(-9)
        clock.advance(0.1)
        sup.tick()
        assert len(deaths) == 1, "host death must be ONE notification"
        info = deaths[0]
        assert info["host"] == "ha" and info["deaths"] == 1
        assert sorted(w["replica"] for w in info["workers"]) == ["w0", "w1"]
        for w in info["workers"]:
            assert w["logTail"] == f"dying words of {w['replica']}"
        assert crashes == [], "residents must not file individual crashes"
        census = sup.host_census()
        assert not census["ha"]["up"] and census["ha"]["deaths"] == 1
        assert census["hb"]["up"]
        # residents of the dead box are NOT respawned while it is down,
        # even after their restart clocks elapse
        clock.advance(1.5)
        sup.tick()
        assert len(spawned) == 3
        text = sup.metrics.render_prometheus()
        assert 'pio_fleet_host_up{host="ha"} 0' in text
        assert 'pio_fleet_host_deaths_total{host="ha"} 1' in text

    def test_probe_recovery_readmits_and_respawns_residents(self):
        sup, spawned, clock, drv, deaths, _ = _host_sup()
        sup.start()
        drv.alive["ha"] = False
        spawned[0].exit(-9)
        spawned[1].exit(-9)
        clock.advance(0.1)
        sup.tick()
        assert len(deaths) == 1
        drv.alive["ha"] = True
        clock.advance(5.1)  # past the periodic probe interval + backoff
        sup.tick()
        census = sup.host_census()
        assert census["ha"]["up"]
        assert len(spawned) == 5  # both residents respawned

    def test_host_backoff_ladder_escalates_with_deaths(self):
        sup, spawned, clock, drv, deaths, _ = _host_sup()
        sup.start()
        for expected_backoff in (1.0, 2.0, 4.0):  # base * mult^(deaths-1)
            drv.alive["ha"] = False
            for w in sup._workers:
                if w.spec.host == "ha" and w.proc is not None:
                    w.proc.exit(-9)
            clock.advance(0.1)
            sup.tick()
            t_death = clock.now
            for w in sup._workers:
                if w.spec.host == "ha":
                    assert w.next_restart_at == pytest.approx(
                        t_death + expected_backoff
                    )
            drv.alive["ha"] = True
            clock.advance(5.1 + expected_backoff)
            sup.tick()  # readmit + respawn for the next round
        assert len(deaths) == 3 and deaths[-1]["deaths"] == 3

    def test_single_exit_on_live_host_is_a_plain_crash(self):
        sup, spawned, clock, drv, deaths, crashes = _host_sup()
        sup.start()
        spawned[0].exit(1)
        clock.advance(0.1)
        sup.tick()
        assert deaths == []
        assert len(crashes) == 1 and crashes[0]["replica"] == "w0"
        assert sup.host_census()["ha"]["up"]

    def test_simultaneous_exits_with_passing_probe_are_crashes(self):
        # both residents die together but the box answers its probe:
        # that is two worker crashes, not a host death
        sup, spawned, clock, drv, deaths, crashes = _host_sup()
        sup.start()
        spawned[0].exit(1)
        spawned[1].exit(1)
        clock.advance(0.1)
        sup.tick()
        assert deaths == []
        assert sorted(c["replica"] for c in crashes) == ["w0", "w1"]


class TestSupervisorHostPlacement:
    def test_pick_host_prefers_free_headroom_on_up_hosts(self):
        sup, spawned, clock, drv, _, _ = _host_sup(placement=("ha", "ha", "hb"))
        sup.start()
        assert sup.pick_host() == "hb"  # ha is full (2/2)
        drv.alive["hb"] = False
        clock.advance(5.1)
        sup.tick()  # periodic probe declares hb down
        assert sup.pick_host() is None  # only full ha remains up
        # the fleet refuses to place on a dead or unknown box
        with pytest.raises(ValueError, match="unknown host"):
            sup.add_worker(WorkerSpec("w9", 9999, host="zz"))

    def test_scale_out_on_picked_host_is_supervised(self):
        sup, spawned, clock, drv, _, _ = _host_sup(placement=("ha", "ha"))
        sup.start()
        target = sup.pick_host()
        assert target == "hb"
        sup.add_worker(WorkerSpec("w9", 9999, host=target))
        assert len(spawned) == 3
        census = sup.host_census()
        assert census["hb"]["resident"] == ["w9"]
        text = sup.metrics.render_prometheus()
        assert 'pio_fleet_worker_host_info{replica="w9",host="hb"} 1' in text

    def test_signals_route_through_the_host_driver(self):
        sup, spawned, clock, drv, _, _ = _host_sup(placement=("ha", "hb"))
        sup.start()
        sup.stop()
        sent = {(h, n) for h, n, sig in drv.signals if sig == _signal.SIGTERM}
        assert sent == {("ha", "w0"), ("hb", "w1")}

    def test_snapshot_carries_the_home_host(self):
        sup, _, _, _, _, _ = _host_sup(placement=("ha", "hb"))
        sup.start()
        assert [s["host"] for s in sup.snapshot()] == ["ha", "hb"]


# ---------------------------------------------------------------------------
# gateway tier: ring writer namespacing, group fan-out, peer fan-in
# ---------------------------------------------------------------------------


class TestRingWriterNamespacing:
    def test_two_writers_never_share_a_segment_file(self, tmp_path):
        d = str(tmp_path)
        g0 = TelemetryRing(d, segment_records=2, writer_id="g0")
        g1 = TelemetryRing(d, segment_records=2, writer_id="g1")
        g0.append({"t": 1.0, "v": "a"})
        g1.append({"t": 2.0, "v": "b"})
        g0.append({"t": 3.0, "v": "c"})
        g1.append({"t": 4.0, "v": "d"})
        g0.close()
        g1.close()
        names = sorted(os.listdir(d))
        assert all("-g0-" in n or "-g1-" in n for n in names), names
        # a fresh reader merges every writer's segments by record time
        merged = TelemetryRing(d).records()
        assert [r["v"] for r in merged] == ["a", "b", "c", "d"]
        assert {r["writer"] for r in merged} == {"g0", "g1"}

    def test_single_writer_layout_is_unchanged(self, tmp_path):
        d = str(tmp_path)
        ring = TelemetryRing(d, segment_records=4)
        for i in range(3):
            ring.append({"v": i})
        ring.close()
        (name,) = os.listdir(d)
        assert name == "seg-00000.jsonl"  # pre-PR-17 naming, byte-for-byte
        assert [r["v"] for r in TelemetryRing(d).records()] == [0, 1, 2]

    def test_writer_id_must_be_label_safe(self, tmp_path):
        with pytest.raises(ValueError):
            TelemetryRing(str(tmp_path), writer_id="g0/../../etc")


class TestGatewayGroup:
    def _gw(self, port):
        return Gateway(
            GatewayConfig(
                ip="127.0.0.1",
                port=port,
                replica_urls=("http://127.0.0.1:1",),
            ),
            metrics=MetricsRegistry(),
        )

    def test_membership_changes_fan_out_to_every_gateway(self):
        g0, g1 = self._gw(free_port()), self._gw(free_port())
        group = GatewayGroup([g0, g1])
        group.add_replica("http://127.0.0.1:2", worker_class="device")
        assert len(g0.replicas) == len(g1.replicas) == 2
        group.retire_replica("http://127.0.0.1:2")
        assert len(g0.replicas) == len(g1.replicas) == 1

    def test_everything_else_delegates_to_the_primary(self):
        g0, g1 = self._gw(free_port()), self._gw(free_port())
        group = GatewayGroup([g0, g1])
        assert group.primary is g0
        assert group.config is g0.config
        with pytest.raises(ValueError):
            GatewayGroup([])


class TestGatewayPeerFanIn:
    def test_slo_fans_in_peers_and_reports_lost_ones(self):
        # two shared-nothing gateways behind an imaginary balancer: /slo
        # on either answers for the tier; a dead peer is REPORTED as an
        # error entry, never silently dropped (the balancer-misroute /
        # gateway-peer-loss evidence row in docs/fleet.md)
        p0, p1 = free_port(), free_port()
        backend = f"http://127.0.0.1:{free_port()}"

        def gw(port, gid, peer_port):
            return Gateway(
                GatewayConfig(
                    ip="127.0.0.1",
                    port=port,
                    replica_urls=(backend,),
                    probe_interval_s=30.0,
                    probe_timeout_s=1.0,
                    telemetry_interval_s=0,
                    gateway_id=gid,
                    peer_urls=(f"http://127.0.0.1:{peer_port}",),
                ),
                metrics=MetricsRegistry(),
            )

        g0, g1 = gw(p0, "g0", p1), gw(p1, "g1", p0)

        async def body():
            import aiohttp

            await g0.start()
            await g1.start()
            session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=5)
            )
            try:
                async with session.get(
                    f"http://127.0.0.1:{p0}/slo"
                ) as resp:
                    tier = await resp.json()
                assert tier["gateway"] == "g0"
                peer_key = f"http://127.0.0.1:{p1}"
                assert "error" not in tier["peers"][peer_key], tier["peers"]
                # ?local=1 answers without recursing into peers
                async with session.get(
                    f"http://127.0.0.1:{p0}/slo?local=1"
                ) as resp:
                    local = await resp.json()
                assert "peers" not in local
                # traces fan-in stays well-formed with peers configured
                async with session.get(
                    f"http://127.0.0.1:{p0}/traces/recent?limit=5"
                ) as resp:
                    assert isinstance((await resp.json())["spans"], list)
                # kill the peer: the tier view must surface the loss
                await g1.stop()
                async with session.get(
                    f"http://127.0.0.1:{p0}/slo"
                ) as resp:
                    tier = await resp.json()
                assert "error" in tier["peers"][peer_key]
            finally:
                await session.close()
                await g0.stop()
                try:
                    await g1.stop()
                except Exception:
                    pass

        asyncio.run(body())


# ---------------------------------------------------------------------------
# pio top --fleet: the host census block
# ---------------------------------------------------------------------------


class TestTopHostCensus:
    # mirrors the real exposition: the GATEWAY keys replica rows by
    # address, the SUPERVISOR keys worker rows by name — the census must
    # read liveness from the worker-named series (live-fleet regression)
    TEXT = (
        "pio_fleet_replicas 3\n"
        'pio_fleet_replica_up{replica="127.0.0.1:8101"} 0\n'
        'pio_fleet_replica_up{replica="127.0.0.1:8102"} 0\n'
        'pio_fleet_replica_up{replica="127.0.0.1:8103"} 1\n'
        'pio_fleet_worker_up{replica="w0"} 0\n'
        'pio_fleet_worker_up{replica="w1"} 0\n'
        'pio_fleet_worker_up{replica="w2"} 1\n'
        'pio_fleet_host_up{host="ha"} 0\n'
        'pio_fleet_host_slots{host="ha"} 2\n'
        'pio_fleet_host_deaths_total{host="ha"} 1\n'
        'pio_fleet_host_up{host="hb"} 1\n'
        'pio_fleet_host_slots{host="hb"} 2\n'
        'pio_fleet_worker_host_info{replica="w0",host="ha"} 1\n'
        'pio_fleet_worker_host_info{replica="w1",host="ha"} 1\n'
        'pio_fleet_worker_host_info{replica="w2",host="hb"} 1\n'
    )

    def test_summary_groups_replicas_by_host(self):
        from predictionio_tpu.tools.top import parse_prometheus, summarize

        fleet = summarize(parse_prometheus(self.TEXT))["fleet"]
        assert fleet["hosts"]["ha"] == {
            "residents": ["w0", "w1"],
            "residents_up": 0,
            "up": False,
            "slots": 2.0,
            "deaths": 1.0,
        }
        assert fleet["hosts"]["hb"]["up"] is True
        assert fleet["hosts"]["hb"]["residents_up"] == 1

    def test_render_marks_the_dead_host(self):
        from predictionio_tpu.tools.top import (
            parse_prometheus,
            render,
            summarize,
        )

        screen = render(
            summarize(parse_prometheus(self.TEXT)), "http://gw:8000"
        )
        (ha_line,) = [
            ln for ln in screen.splitlines() if ln.strip().startswith("host")
            and " ha " in ln
        ]
        assert "HOST-DOWN" in ha_line and "deaths 1" in ha_line
        assert "0/2 slots" in ha_line
        (hb_line,) = [
            ln for ln in screen.splitlines() if ln.strip().startswith("host")
            and " hb " in ln
        ]
        assert "HOST-DOWN" not in hb_line and "1/2 slots" in hb_line


# ---------------------------------------------------------------------------
# e2e: kill an entire host mid-rollout (the PR-17 acceptance gate)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestKillHostMidRolloutE2E:
    """Two fake-driver hosts, four real worker processes, a real gateway
    under real load. Pull host ha's cord mid-bake: the surviving lane
    must never 5xx, the supervisor must fold both deaths into ONE
    host-death incident bundle carrying each dead worker's log tail,
    the host-aware scale-out path must restore capacity on the
    survivor, and a registry transition must steal the lease the dead
    host's holder never released."""

    def test_kill_host_mid_rollout(self, tmp_path, monkeypatch):
        from predictionio_tpu.data.storage.registry import Storage
        from predictionio_tpu.registry.store import ArtifactStore
        from tests.test_registry import _train_version

        monkeypatch.setenv("PIO_REGISTRY_LEASE_TTL", "2.0")
        basedir = str(tmp_path / "store")
        registry_dir = str(tmp_path / "registry")
        storage = Storage(env={"PIO_FS_BASEDIR": basedir})
        _train_version(storage, registry_dir, algo_id=3)  # v000001 stable
        _train_version(storage, registry_dir, algo_id=5)  # v000002
        store = ArtifactStore(registry_dir)

        from predictionio_tpu.fleet.launch import (
            build_obs_plane,
            wire_incident_sources,
        )

        metrics = MetricsRegistry()
        obs_dir = str(tmp_path / "obs")
        obs = build_obs_plane(obs_dir, metrics, registry_dir=registry_dir)

        fake = FakeHostDriver(obs["logbook"])
        runtime = HostRuntime(
            [
                HostSpec("ha", 2, driver=DRIVER_FAKE),
                HostSpec("hb", 3, driver=DRIVER_FAKE),
            ],
            logbook=obs["logbook"],
            drivers={DRIVER_FAKE: fake},
        )
        placement = assign_hosts(4, runtime.hosts())
        specs = [
            WorkerSpec(f"w{i}", free_port(), host=placement[i])
            for i in range(4)
        ]
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            # long enough that the host death lands MID-bake
            "FLEET_BAKE_WINDOW": "30.0",
            "FLEET_BAKE_MIN": "100000",
            "PIO_FS_BASEDIR": basedir,
            "PIO_REGISTRY_LEASE_TTL": "2.0",
        }

        def spawn(spec):
            return runtime.spawn_worker(
                spec.host,
                spec.name,
                [
                    sys.executable,
                    os.path.join(REPO, "tests", "fleet_worker.py"),
                    registry_dir,
                    str(spec.port),
                    basedir,
                ],
                env=env,
            )

        def on_host_down(info: dict) -> None:
            # mirror of launch.py's closure: ONE bundle per host death,
            # each dead worker's tail as its own text part
            texts = {}
            for winfo in info.get("workers", []):
                tail = winfo.pop("logTail", "")
                if tail:
                    texts[f"log_tail_{winfo['replica']}"] = tail
            obs["incidents"].trigger("host-death", context=info, texts=texts)

        sup = Supervisor(
            spawn,
            specs,
            SupervisorConfig(
                poll_interval_s=0.1,
                backoff_base_s=0.2,
                term_grace_s=8.0,
                host_probe_interval_s=0.5,
            ),
            metrics=metrics,
            logbook=obs["logbook"],
            on_crash=obs["on_crash"],
            runtime=runtime,
            on_host_down=on_host_down,
        )
        gw = Gateway(
            GatewayConfig(
                ip="127.0.0.1",
                port=free_port(),
                replica_urls=tuple(s.url for s in specs),
                probe_interval_s=0.2,
                probe_timeout_s=1.0,
                request_timeout_s=8.0,
                telemetry_interval_s=0.2,
            ),
            metrics=metrics,
            telemetry=obs["telemetry"],
            incidents=obs["incidents"],
        )
        wire_incident_sources(obs["incidents"], gw, sup)
        results: dict = {"statuses": [], "errors": []}
        try:
            asyncio.run(
                self._drive(sup, gw, store, runtime, fake, results, specs)
            )
        finally:
            sup.stop()
            obs["telemetry"].close()
        fivexx = [s for s in results["statuses"] if s >= 500]
        assert fivexx == [], (
            f"{len(fivexx)} 5xx under host loss "
            f"(of {len(results['statuses'])} requests): "
            f"{results.get('bodies_5xx', [])[:5]}"
        )
        assert results["errors"] == []
        assert len(results["statuses"]) > 50
        # the lease the dead host's holder never released was stolen
        # with a fresh fencing token, and the transition went through
        assert results["lease_gen_after"] > results["lease_gen_foreign"]
        self._assert_host_death_bundle(obs_dir, results["dead"])
        text = metrics.render_prometheus()
        assert 'pio_fleet_host_up{host="ha"} 0' in text
        assert 'pio_fleet_host_deaths_total{host="ha"} 1' in text

    def _assert_host_death_bundle(self, obs_dir, dead_names) -> None:
        from predictionio_tpu.obs.incidents import list_bundles, load_bundle

        inc_dir = os.path.join(obs_dir, "incidents")
        refs = list_bundles(inc_dir)
        host_deaths = [r for r in refs if r.trigger == "host-death"]
        assert len(host_deaths) == 1, (
            f"expected ONE host-death bundle, got "
            f"{[r.trigger for r in refs]}"
        )
        bundle = load_bundle(inc_dir, host_deaths[0].bundle_id)
        ctx = bundle["manifest"]["context"]
        assert ctx["host"] == "ha" and ctx["deaths"] == 1
        assert sorted(w["replica"] for w in ctx["workers"]) == sorted(
            dead_names
        )
        for name in dead_names:
            tail = bundle["texts"].get(f"log_tail_{name}", "")
            assert "fleet worker serving" in tail, (
                f"{name}'s dying words missing from the bundle: {tail!r}"
            )
        # the host death must NOT also file per-worker crash bundles
        crash = [r for r in refs if r.trigger == "worker-crash"]
        assert crash == [], "host death leaked individual crash bundles"

    async def _drive(
        self, sup, gw, store, runtime, fake, results, specs
    ) -> None:
        import aiohttp

        from predictionio_tpu.registry.lease import LeaseMutex, LeaseRecord

        sup.start()
        sup_task = asyncio.ensure_future(sup.run())
        await gw.start()
        gw_url = f"http://127.0.0.1:{gw.config.port}"
        session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=10)
        )
        stop_load = asyncio.Event()
        load_task = None
        try:
            for spec in sup.workers:
                await self._wait_ready(session, spec.url, 120.0)
            load_task = asyncio.ensure_future(
                self._load(session, gw_url, stop_load, results)
            )
            await asyncio.sleep(0.3)
            # stage the canary THROUGH the gateway: the host death must
            # land mid-rollout
            async with session.post(
                f"{gw_url}/models/candidate",
                json={"version": "v000002", "mode": "canary", "fraction": 0.4},
            ) as resp:
                assert resp.status == 200, await resp.text()
            # a holder on the soon-to-die host grabbed the registry
            # lease and will never release it
            lease_path = store._lease_for("regtest").path
            foreign = LeaseMutex(lease_path, owner="ha-holder", ttl_s=2.0)
            cur = foreign.read()
            rec = LeaseRecord(
                owner="ha-holder",
                generation=cur.generation + 1,
                acquired_at=time.time(),
                ttl_s=2.0,
                host="host-ha",  # not this box: no same-host fast steal
                pid=999999,
            )
            foreign._write(rec)
            results["lease_gen_foreign"] = rec.generation
            # pull the cord on ha
            dead = [s.name for s in specs if s.host == "ha"]
            results["dead"] = dead
            assert fake.kill_host("ha") == len(dead)
            # the gateway ejects both residents inside the probe window
            survivors = len(specs) - len(dead)
            await self._poll_async(
                lambda: self._gw_healthy_count(session, gw_url, survivors),
                "dead host's replicas never ejected",
                10.0,
            )
            # the survivor-side transition steals the dead holder's
            # lease (TTL expiry) instead of deadlocking on it
            def transition() -> int:
                with store._state_mutex("regtest"):
                    mx = store._leases[store.engine_key("regtest")]
                    return mx.generation

            gen = await asyncio.get_running_loop().run_in_executor(
                None, transition
            )
            results["lease_gen_after"] = gen
            # host-aware scale-out restores capacity on the survivor
            await self._poll_async_sync(
                lambda: sup.pick_host() == "hb",
                "pick_host never settled on the survivor",
                10.0,
            )
            replacement = WorkerSpec("w4", free_port(), host="hb")
            await asyncio.get_running_loop().run_in_executor(
                None, sup.add_worker, replacement
            )
            gw.add_replica(replacement.url, replacement.worker_class)
            await self._poll_async(
                lambda: self._gw_healthy_count(
                    session, gw_url, survivors + 1
                ),
                "replacement capacity never came up on the survivor",
                120.0,
            )
        finally:
            stop_load.set()
            if load_task is not None:
                await asyncio.gather(load_task, return_exceptions=True)
            sup_task.cancel()
            await asyncio.gather(sup_task, return_exceptions=True)
            await session.close()
            await gw.stop()

    async def _load(self, session, gw_url, stop, results) -> None:
        i = 0
        while not stop.is_set():
            i += 1
            try:
                async with session.post(
                    f"{gw_url}/queries.json",
                    json={"qid": i, "user": f"u{i % 40}"},
                ) as resp:
                    body = await resp.read()
                    results["statuses"].append(resp.status)
                    if resp.status >= 500:
                        results.setdefault("bodies_5xx", []).append(
                            body[:120].decode("utf-8", "replace")
                        )
            except Exception as exc:  # the gateway must never drop us
                results["errors"].append(repr(exc))
            await asyncio.sleep(0.01)

    async def _wait_ready(self, session, url, deadline_s) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                async with session.get(f"{url}/healthz") as resp:
                    if resp.status == 200:
                        return
            except Exception:
                pass
            assert time.monotonic() < deadline, f"{url} never became ready"
            await asyncio.sleep(0.25)

    async def _poll_async(self, cond, message, deadline_s) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                ok = await cond()
            except Exception:
                ok = False
            if ok:
                return
            assert time.monotonic() < deadline, message
            await asyncio.sleep(0.1)

    async def _poll_async_sync(self, cond, message, deadline_s) -> None:
        deadline = time.monotonic() + deadline_s
        while not cond():
            assert time.monotonic() < deadline, message
            await asyncio.sleep(0.1)

    async def _gw_healthy_count(self, session, gw_url, expect) -> bool:
        async with session.get(f"{gw_url}/healthz") as resp:
            data = await resp.json()
            return data.get("replicasHealthy") == expect
