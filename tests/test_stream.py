"""Speed-layer tests (tier-1, CPU-only).

Covers the subsystem end to end: durable cursors (atomic checkpoint,
resume-after-crash), the resilient event tailer (bounded drains, retry,
breaker), the incremental trainers (fold-in ALS via the batched SPD
solves, streaming naive bayes, incremental cooccurrence) with their drift
guards, and the StreamPipeline — including the acceptance rail: trained
stable -> fresh events through the EventServer -> StreamPipeline publishes
a registry candidate with correct lineage/train-span -> the existing bake
gates auto-promote it; a drift-injected run suppresses the publish; a
crash/restart mid-stream yields exactly one published candidate. The
tail-under-chaos stage (scripts/run_chaos.sh) kills the pipeline
mid-drain under fault injection and asserts the cursor resumes with no
skipped events and no duplicate publish.
"""

from __future__ import annotations

import asyncio
import datetime as dt
import os
import time

import numpy as np
import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import event_seq_key
from predictionio_tpu.data.storage.memory import MemoryStorageClient
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.registry import ArtifactStore, ModelManifest
from predictionio_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    FaultInjector,
    ResiliencePolicy,
    RetryPolicy,
)
from predictionio_tpu.stream import (
    CursorStore,
    EventTailer,
    FoldInALSTrainer,
    StreamConfig,
    StreamInstruments,
    StreamPipeline,
    StreamingCooccurrenceTrainer,
    StreamingNaiveBayesTrainer,
    span_id_of,
    trainer_for_models,
)
from predictionio_tpu.stream.trainers import DriftReport
from predictionio_tpu.workflow import model_io

UTC = dt.timezone.utc
APP = 1


def t(n: int) -> dt.datetime:
    return dt.datetime(2024, 3, 1, 0, 0, 0, n, tzinfo=UTC)


def rate_event(user: str, item: str, rating: float, n: int) -> Event:
    return Event(
        event="rate",
        entity_type="user",
        entity_id=user,
        target_entity_type="item",
        target_entity_id=item,
        properties=DataMap({"rating": rating}),
        event_time=t(n),
        creation_time=t(n),
    )


def _levents():
    return MemoryStorageClient().l_events()


def dataclasses_replace_creation(e: Event, creation: dt.datetime) -> Event:
    import dataclasses

    return dataclasses.replace(e, creation_time=creation)


class RecordingTrainer:
    """Protocol-conformant trainer that records what it absorbed."""

    name = "recording"

    def __init__(self):
        self.ids: list[str] = []
        self.ok = True

    def absorb(self, events):
        self.ids.extend(e.event_id for e in events)
        return len(events)

    def snapshot(self):
        return [{"absorbed": len(self.ids)}]

    def drift(self):
        return DriftReport(self.ok, "test", reason="" if self.ok else "forced breach")


# ---------------------------------------------------------------------------
# cursors
# ---------------------------------------------------------------------------


class TestCursorStore:
    def test_roundtrip_and_resume(self, tmp_path):
        cursors = CursorStore(str(tmp_path))
        c = cursors.load(APP)
        assert c.pos() is None and c.events_read == 0
        c.advance((1000, "ev1"), 10)
        c.record_publish("v000002", "start..1000:ev1", (1000, "ev1"))
        cursors.save(c)
        again = CursorStore(str(tmp_path)).load(APP)
        assert again.pos() == (1000, "ev1")
        assert again.published_pos() == (1000, "ev1")
        assert again.events_read == 10
        assert again.last_published_version == "v000002"
        assert again.last_published_span == "start..1000:ev1"

    def test_channel_cursors_are_separate_files(self, tmp_path):
        cursors = CursorStore(str(tmp_path))
        a = cursors.load(APP)
        a.advance((1, "a"), 1)
        cursors.save(a)
        b = cursors.load(APP, 7)
        assert b.pos() is None
        b.advance((2, "b"), 1)
        cursors.save(b)
        assert CursorStore(str(tmp_path)).load(APP).pos() == (1, "a")
        assert CursorStore(str(tmp_path)).load(APP, 7).pos() == (2, "b")

    def test_unreadable_cursor_starts_fresh(self, tmp_path):
        cursors = CursorStore(str(tmp_path))
        with open(cursors.path(APP), "w") as fh:
            fh.write("{half a json")
        assert cursors.load(APP).pos() is None

    def test_no_tmp_litter(self, tmp_path):
        cursors = CursorStore(str(tmp_path))
        c = cursors.load(APP)
        for i in range(5):
            c.advance((i, f"e{i}"), 1)
            cursors.save(c)
        assert [f for f in os.listdir(tmp_path) if f.startswith(".tmp")] == []

    def test_span_id_is_deterministic(self):
        assert span_id_of(None, (5, "x")) == "start..5:x"
        assert span_id_of((1, "a"), (5, "x")) == "1:a..5:x"


# ---------------------------------------------------------------------------
# tailer
# ---------------------------------------------------------------------------


class TestEventTailer:
    def _seed(self, l, n):
        for i in range(n):
            l.insert(rate_event(f"u{i % 4}", f"i{i % 3}", 3.0, i), APP)

    def test_bounded_drains_walk_the_store(self):
        l = _levents()
        l.init(APP)
        self._seed(l, 25)
        tailer = EventTailer(l, APP, batch_limit=10)
        seen = []
        pos = None
        sizes = []
        while True:
            res = tailer.drain(pos)
            if not res.events:
                assert res.more is False
                break
            sizes.append(len(res.events))
            seen.extend(e.event_id for e in res.events)
            pos = res.position
        assert sizes == [10, 10, 5]
        assert len(seen) == len(set(seen)) == 25

    def test_retry_then_succeed_on_transient_fault(self):
        l = _levents()
        l.init(APP)
        self._seed(l, 3)
        flaky = FaultInjector(l)
        flaky.inject(methods="find_after", fail_count=1)
        tailer = EventTailer(flaky, APP, batch_limit=10)
        res = tailer.drain(None)
        assert len(res.events) == 3
        assert flaky.faults == 1  # the fault happened and was retried over

    def test_breaker_opens_after_persistent_failure(self):
        l = _levents()
        l.init(APP)
        broken = FaultInjector(l)
        broken.inject(methods="find_after", fail_count=10_000)
        policy = ResiliencePolicy(
            retry=RetryPolicy(max_attempts=2, backoff_base_s=0.0),
            breaker=CircuitBreaker(name="t", failure_threshold=3),
        )
        tailer = EventTailer(broken, APP, batch_limit=10, policy=policy)
        with pytest.raises(ConnectionError):
            tailer.drain(None)
        with pytest.raises((ConnectionError, CircuitOpenError)):
            tailer.drain(None)
        with pytest.raises(CircuitOpenError):
            tailer.drain(None)

    def test_safety_lag_holds_back_fresh_events(self):
        """The watermark: events inside the safety-lag window stay in the
        store for the next cycle, so a concurrently committing insert can
        never land behind an already-advanced cursor."""
        l = _levents()
        l.init(APP)
        now = dt.datetime.now(tz=UTC)
        old = dataclasses_replace_creation(rate_event("u1", "i0", 3.0, 1),
                                           now - dt.timedelta(seconds=60))
        fresh = dataclasses_replace_creation(rate_event("u2", "i0", 3.0, 2), now)
        l.insert(old, APP)
        l.insert(fresh, APP)
        tailer = EventTailer(l, APP, batch_limit=10, safety_lag_s=5.0)
        res = tailer.drain(None)
        assert [e.entity_id for e in res.events] == ["u1"]
        assert res.more is False  # waiting on the watermark, not behind
        # the fresh event is picked up once it ages past the lag
        eager = EventTailer(l, APP, batch_limit=10, safety_lag_s=0.0)
        res2 = eager.drain(res.position)
        assert [e.entity_id for e in res2.events] == ["u2"]

    def test_lag_and_head_position(self):
        l = _levents()
        l.init(APP)
        self._seed(l, 12)
        tailer = EventTailer(l, APP, batch_limit=5)
        n, secs = tailer.lag(None)
        assert n == 12 and secs > 0
        head = tailer.head_position()
        assert tailer.lag(head) == (0, 0.0)
        assert tailer.drain(head).events == []


# ---------------------------------------------------------------------------
# trainers
# ---------------------------------------------------------------------------


def _seed_als_model(rank=4, n_users=3, n_items=4, seed=0):
    from predictionio_tpu.models.recommendation.engine import ALSModel

    rng = np.random.default_rng(seed)
    uf = rng.normal(size=(n_users, rank)).astype(np.float32)
    vf = rng.normal(size=(n_items, rank)).astype(np.float32)
    return ALSModel(
        uf, vf, [f"u{i}" for i in range(n_users)], [f"i{i}" for i in range(n_items)]
    )


class TestFoldInALS:
    def test_new_user_folds_in_and_aligns_with_rated_item(self):
        model = _seed_als_model()
        # make item 1 the anti-item of item 0: a user who loves i0 must
        # score i0 far above i1 after fold-in
        model.item_factors[1] = -model.item_factors[0]
        trainer = FoldInALSTrainer([model], holdout_every=1_000_000)
        events = [rate_event("newu", "i0", 5.0, n) for n in range(6)]
        assert trainer.absorb(events) == 6
        assert "newu" in trainer.user_vocab
        uidx = trainer.user_vocab.index("newu")
        u = trainer.user_factors[uidx]
        assert np.all(np.isfinite(u)) and np.linalg.norm(u) > 0
        s0 = float(u @ trainer.item_factors[0])
        s1 = float(u @ trainer.item_factors[1])
        assert s0 > 0 > s1

    def test_foldin_matches_exact_normal_equation_solve(self):
        model = _seed_als_model()
        trainer = FoldInALSTrainer([model], reg=0.1, holdout_every=1_000_000)
        events = [
            rate_event("u0", "i0", 4.0, 0),
            rate_event("u0", "i2", 2.0, 1),
        ]
        trainer.absorb(events)
        V = model.item_factors[[0, 2]]
        r = np.asarray([4.0, 2.0], np.float32)
        A = V.T @ V + 0.1 * 2 * np.eye(4, dtype=np.float32)
        expected = np.linalg.solve(A, V.T @ r)
        got = trainer.user_factors[0]
        np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-4)

    def test_snapshot_returns_updated_model(self):
        model = _seed_als_model()
        trainer = FoldInALSTrainer([model], holdout_every=1_000_000)
        trainer.absorb([rate_event("newu", "i0", 5.0, 0)])
        (snap,) = trainer.snapshot()
        assert "newu" in snap.user_vocab
        assert snap.user_factors.shape[0] == 4
        # the snapshot is the servable/persistable form
        blob = model_io.serialize_models([snap])
        (back,) = model_io.deserialize_models(blob)
        assert back.user_vocab == snap.user_vocab

    def test_drift_guard_catches_corrupt_ratings(self):
        model = _seed_als_model()
        trainer = FoldInALSTrainer([model], holdout_every=1_000_000)
        trainer.absorb([rate_event("u0", "i0", 4.0, 0)])
        assert trainer.drift().ok
        corrupt = [rate_event("u1", "i1", 1e9, n) for n in range(3)]
        trainer.absorb(corrupt)
        report = trainer.drift()
        assert not report.ok
        assert report.metric == "factor-health"

    def test_holdout_examples_are_not_absorbed(self):
        model = _seed_als_model()
        trainer = FoldInALSTrainer([model], holdout_every=2)
        absorbed = trainer.absorb(
            [rate_event("u0", "i0", 3.0, n) for n in range(10)]
        )
        assert absorbed == 5
        assert len(trainer.holdout.held) == 5


class TestStreamingNaiveBayes:
    def _ev(self, label, features, n):
        return Event(
            event="example",
            entity_type="sample",
            entity_id=f"s{n}",
            properties=DataMap({"label": label, "features": list(features)}),
            event_time=t(n),
            creation_time=t(n),
        )

    def test_counts_update_and_model_predicts(self):
        trainer = StreamingNaiveBayesTrainer(holdout_every=1_000_000)
        events = [self._ev("spam", ("buy", "now"), n) for n in range(6)]
        events += [self._ev("ham", ("hello", "friend"), 10 + n) for n in range(4)]
        assert trainer.absorb(events) == 10
        (model,) = trainer.snapshot()
        assert model.predict(("buy", "now")) == "spam"
        assert model.predict(("hello", "friend")) == "ham"

    def test_matches_batch_trainer_exactly(self):
        from predictionio_tpu.e2.naive_bayes import (
            LabeledPoint,
            train_categorical_naive_bayes,
        )

        pts = [LabeledPoint("a", ("x", "y"))] * 3 + [LabeledPoint("b", ("x", "z"))] * 2
        events = [
            self._ev(p.label, p.features, n) for n, p in enumerate(pts)
        ]
        trainer = StreamingNaiveBayesTrainer(holdout_every=1_000_000)
        trainer.absorb(events)
        (stream_model,) = trainer.snapshot()
        batch_model = train_categorical_naive_bayes(pts)
        assert stream_model.priors == batch_model.priors
        assert stream_model.likelihoods == batch_model.likelihoods

    def test_drift_breach_on_label_flip(self):
        trainer = StreamingNaiveBayesTrainer(
            holdout_every=2, drift_min_samples=4, drift_max_divergence=0.5
        )
        clean = [self._ev("a", ("x",), n) for n in range(20)]
        trainer.absorb(clean)
        assert trainer.drift().ok
        # poison: the same feature now overwhelmingly labeled b flips the
        # folded model's predictions away from the seed's -> divergence
        poison = [self._ev("b", ("x",), 100 + n) for n in range(200)]
        trainer.absorb(poison)
        report = trainer.drift()
        assert not report.ok
        assert report.metric == "divergence"
        # a healthy consistent stream does NOT diverge from its seed
        healthy = StreamingNaiveBayesTrainer(holdout_every=2, drift_min_samples=4)
        healthy.absorb(clean)
        healthy.absorb([self._ev("a", ("x",), 500 + n) for n in range(50)])
        assert healthy.drift().ok


class TestSeededTrainers:
    def test_nb_with_stable_seed_suppresses_from_scratch_publish(self):
        from predictionio_tpu.e2.naive_bayes import (
            LabeledPoint,
            train_categorical_naive_bayes,
        )

        stable = train_categorical_naive_bayes(
            [LabeledPoint("a", ("x",))] * 5 + [LabeledPoint("b", ("y",))] * 5
        )
        trainer = StreamingNaiveBayesTrainer(
            stable, holdout_every=2, drift_min_samples=4
        )
        # a couple of events: held-out evidence insufficient -> a stable-
        # seeded trainer must NOT vouch for its from-scratch model
        ev = TestStreamingNaiveBayes()
        trainer.absorb([ev._ev("a", ("x",), n) for n in range(3)])
        assert not trainer.drift().ok
        # consistent stream fills the window; predictions agree with the
        # stable -> publishes flow again
        trainer.absorb(
            [ev._ev("a", ("x",), 10 + n) for n in range(10)]
            + [ev._ev("b", ("y",), 30 + n) for n in range(10)]
        )
        assert trainer.drift().ok
        # label-flip poison diverges from the STABLE model -> breach
        trainer.absorb([ev._ev("b", ("x",), 100 + n) for n in range(200)])
        report = trainer.drift()
        assert not report.ok and report.metric == "divergence"

    def test_cooccurrence_seeded_from_similarproduct_model(self):
        from predictionio_tpu.models.similarproduct.engine import CooccurrenceModel

        stable = CooccurrenceModel(
            top_map={0: [(1, 3)], 1: [(0, 3)]},
            item_vocab=["a", "b"],
            item_categories=[None, None],
        )
        trainer = trainer_for_models([stable], holdout_every=1_000_000)
        assert isinstance(trainer, StreamingCooccurrenceTrainer)
        trainer.absorb(
            [
                rate_event("u9", "a", 1, 0),
                rate_event("u9", "c", 1, 1),  # new item extends the vocab
            ]
        )
        (snap,) = trainer.snapshot()
        assert isinstance(snap, CooccurrenceModel)
        assert snap.item_vocab == ["a", "b", "c"]
        a, b, c = 0, 1, 2
        # seed counts merged with the fresh (a, c) pair
        assert dict(snap.top_map)[a] == [(b, 3), (c, 1)]
        assert (a, 1) in dict(snap.top_map)[c]
        assert snap.item_categories[c] is None


class TestStreamingCooccurrence:
    def test_incremental_counts_and_top_map(self):
        trainer = StreamingCooccurrenceTrainer(top_n=2, holdout_every=1_000_000)
        events = [
            rate_event("u1", "a", 1, 0),
            rate_event("u1", "b", 1, 1),
            rate_event("u1", "a", 1, 2),  # duplicate pair: ignored
            rate_event("u2", "a", 1, 3),
            rate_event("u2", "b", 1, 4),
            rate_event("u2", "c", 1, 5),
        ]
        assert trainer.absorb(events) == 5  # one duplicate dropped
        top = trainer.top_map()
        assert top["a"][0] == ("b", 2)
        assert ("c", 1) in top["a"]
        from predictionio_tpu.ops.cooccurrence import score_by_cooccurrence

        scores = score_by_cooccurrence(top, ["a"])
        assert scores["b"] == 2.0


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------


def _pipeline(tmp_path, levents, trainer, *, registry=None, stable_blob=True,
              engine_id="streameng", ring=None, incidents=None, **cfg_kw):
    """Memory-backed pipeline with a registry holding one stable version."""
    store = ArtifactStore(str(tmp_path / "registry"))
    if stable_blob:
        store.publish(
            ModelManifest(
                version="",
                engine_id=engine_id,
                engine_version="1",
                engine_variant="engine.json",
            ),
            model_io.serialize_models([{"seed": True}]),
        )
    batch_limit = cfg_kw.pop("batch_limit", 5)
    cfg_kw.setdefault("publish_min_events", 1)
    config = StreamConfig(engine_id=engine_id, **cfg_kw)
    instruments = StreamInstruments(registry or MetricsRegistry())
    tailer = EventTailer(levents, APP, batch_limit=batch_limit)
    pipeline = StreamPipeline(
        tailer,
        trainer,
        CursorStore(str(tmp_path / "cursors")),
        store,
        config,
        instruments=instruments,
        ring=ring,
        incidents=incidents,
    )
    return pipeline, store, instruments


class TestStreamPipeline:
    def test_publish_candidate_with_lineage_and_span(self, tmp_path):
        l = _levents()
        l.init(APP)
        for i in range(7):
            l.insert(rate_event(f"u{i}", "i0", 3.0, i), APP)
        trainer = RecordingTrainer()
        pipeline, store, ins = _pipeline(tmp_path, l, trainer)
        summary = pipeline.run_once()
        assert summary["drained"] == 7
        assert summary["published"] == "v000002"
        versions = store.list_versions("streameng")
        assert [m.version for m in versions] == ["v000001", "v000002"]
        m = versions[-1]
        assert m.parent_version == "v000001"  # lineage parent = stable
        span = m.data_span["stream"]
        assert span["events"] == 7
        assert span["trainer"] == "recording"
        assert span["spanId"].startswith("start..")
        # every stream publish carries its fold-in evidence too — both as
        # the manifest's train_profile (parity with the batch path) and
        # embedded in the stream span
        assert m.train_profile and m.train_profile["steps"] >= 1
        assert span["profile"] == m.train_profile
        assert "sweep" in m.train_profile["phases"]
        # staged as a candidate on the existing rollout path
        state = store.get_state("streameng")
        assert state.stable == "v000001"
        assert state.candidate == "v000002"
        assert ins.publishes.value() == 1
        assert ins.events.value() == 7
        # the blob is the trainer's snapshot
        assert model_io.deserialize_models(store.load_blob("streameng", "v000002")) == [
            {"absorbed": 7}
        ]

    def test_publish_min_events_batches_up(self, tmp_path):
        l = _levents()
        l.init(APP)
        for i in range(3):
            l.insert(rate_event(f"u{i}", "i0", 3.0, i), APP)
        pipeline, store, _ = _pipeline(
            tmp_path, l, RecordingTrainer(), publish_min_events=5
        )
        assert pipeline.run_once()["published"] is None
        for i in range(3):
            l.insert(rate_event(f"w{i}", "i0", 3.0, 10 + i), APP)
        assert pipeline.run_once()["published"] == "v000002"
        assert store.list_versions("streameng")[-1].data_span["stream"]["events"] == 6

    def test_drift_breach_suppresses_publish(self, tmp_path):
        l = _levents()
        l.init(APP)
        for i in range(4):
            l.insert(rate_event(f"u{i}", "i0", 3.0, i), APP)
        trainer = RecordingTrainer()
        trainer.ok = False
        pipeline, store, ins = _pipeline(tmp_path, l, trainer)
        summary = pipeline.run_once()
        assert summary["published"] is None
        assert summary["driftSuppressed"] is True
        assert ins.drift_suppressed.value() == 1
        assert [m.version for m in store.list_versions("streameng")] == ["v000001"]
        assert store.get_state("streameng").candidate == ""
        # cursor still advanced: the events were read and folded
        assert pipeline.cursor.events_read == 4
        # recovery: guard passes again -> the accumulated span publishes
        trainer.ok = True
        assert pipeline.run_once()["published"] == "v000002"

    def test_drift_breach_signals_ring_and_incident(self, tmp_path):
        """ISSUE 19 satellite: a breached guard is the lifecycle
        controller's primary sensor — one structured kind="drift" record
        on the telemetry ring (engine, trainer, guard, measured vs
        threshold) plus a rate-limited incident bundle, and the stream
        loop keeps folding regardless."""
        from predictionio_tpu.obs.tsring import TelemetryRing

        class Incidents:
            def __init__(self):
                self.calls = []

            def trigger(self, kind, context=None, texts=None):
                self.calls.append((kind, context))

        l = _levents()
        l.init(APP)
        for i in range(4):
            l.insert(rate_event(f"u{i}", "i0", 3.0, i), APP)
        trainer = RecordingTrainer()
        trainer.ok = False
        ring = TelemetryRing(str(tmp_path / "telemetry"), writer_id="stream")
        incidents = Incidents()
        pipeline, store, ins = _pipeline(
            tmp_path, l, trainer, ring=ring, incidents=incidents
        )
        summary = pipeline.run_once()
        assert summary["driftSuppressed"] is True
        drift = [r for r in ring.records() if r.get("kind") == "drift"]
        assert len(drift) == 1
        rec = drift[0]
        assert rec["engine"] == "streameng" and rec["trainer"] == "recording"
        assert rec["guard"] == "test" and rec["reason"] == "forced breach"
        assert rec["writer"] == "stream" and "seq" in rec and "t" in rec
        assert incidents.calls == [("stream-drift", {
            "engine": "streameng", "trainer": "recording", "guard": "test",
            "measured": None, "threshold": None, "reason": "forced breach",
        })]
        # a ring-less pipeline stays silent (the default wiring)
        trainer2 = RecordingTrainer()
        trainer2.ok = False
        l.insert(rate_event("w0", "i0", 3.0, 9), APP)
        p2, _, _ = _pipeline(
            tmp_path / "bare", l, trainer2, engine_id="streameng2"
        )
        assert p2.run_once()["driftSuppressed"] is True

    def test_crash_restart_resumes_without_skips_or_double_publish(self, tmp_path):
        """The tail-under-chaos rail: kill the pipeline mid-drain under
        fault injection, restart, and the cursor resumes with no skipped
        events and exactly one published candidate. Events the dead
        process folded but never PUBLISHED are re-folded on restart (the
        cursor rewinds to the publish floor) — they must not silently
        vanish from the speed layer."""
        l = _levents()
        l.init(APP)
        all_ids = [
            l.insert(rate_event(f"u{i % 4}", f"i{i % 2}", 3.0, i), APP)
            for i in range(12)
        ]
        flaky = FaultInjector(l)
        trainer1 = RecordingTrainer()
        pipeline, store, _ = _pipeline(
            tmp_path, flaky, trainer1, publish_min_events=999, batch_limit=5
        )
        # first drain lands, then the storage dies hard mid-catch-up
        pipeline.config.max_batches_per_cycle = 1
        pipeline.run_once()  # batch 1 absorbed + checkpointed, NOT published
        flaky.inject(methods="find_after", fail_count=10_000)
        with pytest.raises(ConnectionError):
            pipeline.run_once()  # killed mid-drain
        assert len(trainer1.ids) == 5  # only the checkpointed batch folded
        # restart: fresh process = fresh pipeline + trainer, same cursors;
        # batch 1 was never published, so it rewinds and re-folds
        flaky.clear()
        trainer2 = RecordingTrainer()
        pipeline2, store2, _ = _pipeline(
            tmp_path, l, trainer2, stable_blob=False, publish_min_events=1,
            batch_limit=5,
        )
        summary = pipeline2.run_once()
        # no skipped events: the restarted trainer saw EVERY event (the
        # unpublished tail re-read = at-least-once by design)
        assert sorted(set(trainer2.ids)) == sorted(all_ids)
        # exactly one published candidate covering the whole stream
        assert summary["published"] == "v000002"
        versions = store2.list_versions("streameng")
        assert [m.version for m in versions] == ["v000001", "v000002"]
        assert versions[-1].data_span["stream"]["events"] == 12

    def test_replayed_span_is_not_published_twice(self, tmp_path):
        """Exactly-once publish on at-least-once reads: a cursor rolled
        back past a published interval (the crash-between-publish-and-
        checkpoint window) replays the same events, derives the same span
        id, and recognizes the existing candidate instead of minting a
        duplicate."""
        l = _levents()
        l.init(APP)
        for i in range(6):
            l.insert(rate_event(f"u{i}", "i0", 3.0, i), APP)
        pipeline, store, _ = _pipeline(tmp_path, l, RecordingTrainer())
        assert pipeline.run_once()["published"] == "v000002"
        # simulate the lost checkpoint: cursor file reset to the pre-run
        # state, so the restarted pipeline re-reads the whole interval
        cursors = CursorStore(str(tmp_path / "cursors"))
        fresh = cursors.load(APP)
        fresh.position = None
        fresh.published_position = None
        fresh.last_published_version = ""
        fresh.last_published_span = ""
        cursors.save(fresh)
        trainer2 = RecordingTrainer()
        pipeline2, store2, ins2 = _pipeline(
            tmp_path, l, trainer2, stable_blob=False
        )
        summary = pipeline2.run_once()
        assert len(trainer2.ids) == 6  # interval re-read (at-least-once)
        assert summary["published"] == "v000002"  # recognized, not re-minted
        assert [m.version for m in store2.list_versions("streameng")] == [
            "v000001",
            "v000002",
        ]
        assert pipeline2.cursor.last_published_version == "v000002"

    def test_run_forever_pauses_on_open_breaker(self, tmp_path):
        l = _levents()
        l.init(APP)
        broken = FaultInjector(l)
        broken.inject(methods="find_after", fail_count=10_000)
        trainer = RecordingTrainer()
        pipeline, _, ins = _pipeline(tmp_path, broken, trainer)
        pipeline.tailer.policy = ResiliencePolicy(
            retry=RetryPolicy(max_attempts=2, backoff_base_s=0.0),
            breaker=CircuitBreaker(name="t", failure_threshold=1),
        )
        sleeps = []
        pipeline.run_forever(max_cycles=3, sleep=sleeps.append)
        assert ins.errors.value(stage="cycle") + ins.errors.value(stage="drain") == 3
        assert pipeline.config.breaker_pause_s in sleeps

    def test_standalone_metrics_endpoint_feeds_pio_top(self, tmp_path):
        """A standalone `pio stream --metrics-port` process serves its own
        /metrics; `pio top`'s parser digests it into the stream line."""
        import urllib.request

        from predictionio_tpu.stream import serve_metrics
        from predictionio_tpu.tools.top import parse_prometheus, summarize

        l = _levents()
        l.init(APP)
        for i in range(4):
            l.insert(rate_event(f"u{i}", "i0", 3.0, i), APP)
        registry = MetricsRegistry()
        pipeline, _, _ = _pipeline(tmp_path, l, RecordingTrainer(), registry=registry)
        pipeline.run_once()
        server = serve_metrics(registry, 0, host="127.0.0.1")
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as resp:
                text = resp.read().decode()
            s = summarize(parse_prometheus(text))
            assert s["stream"] is not None
            assert s["stream"]["events_total"] == 4
            assert s["stream"]["publishes_total"] == 1
            assert s["stream"]["lag_events"] == 0
        finally:
            server.shutdown()

    def test_trainer_for_models_selects_by_type(self):
        model = _seed_als_model()
        assert isinstance(trainer_for_models([model]), FoldInALSTrainer)
        from predictionio_tpu.e2.naive_bayes import train_categorical_naive_bayes
        from predictionio_tpu.e2.naive_bayes import LabeledPoint

        nb = train_categorical_naive_bayes([LabeledPoint("a", ("x",))])
        assert isinstance(trainer_for_models([nb]), StreamingNaiveBayesTrainer)
        with pytest.raises(ValueError):
            trainer_for_models([{"opaque": 1}])


# ---------------------------------------------------------------------------
# end to end: EventServer ingest -> StreamPipeline -> registry -> bake gate
# ---------------------------------------------------------------------------


def _memory_storage() -> Storage:
    return Storage(
        env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )


def _rec_engine():
    from predictionio_tpu.controller import Engine
    from predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithm,
        DataSource,
        Preparator,
        Query,
        Serving,
    )

    return Engine(
        DataSource, Preparator, {"als": ALSAlgorithm}, Serving, query_class=Query
    )


def _rec_params(app_name: str):
    from predictionio_tpu.controller import EmptyParams
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithmParams,
        DataSourceParams,
    )

    return EngineParams(
        data_source=(
            "",
            DataSourceParams(app_name=app_name, event_names=("rate",)),
        ),
        preparator=("", None),
        algorithms=[("als", ALSAlgorithmParams(rank=4, num_iterations=3, seed=1))],
        serving=("", None),
    )


def _rec_manifest():
    from predictionio_tpu.workflow.engine_loader import EngineManifest

    return EngineManifest(
        engine_id="streamtest",
        version="1",
        variant="engine.json",
        engine_factory="tests.test_stream._rec_engine",
    )


class TestEndToEndSpeedLayer:
    def test_ingest_stream_publish_bake_promote_and_drift_suppress(self, tmp_path):
        storage = _memory_storage()
        from predictionio_tpu.data.storage.base import AccessKey, App

        app_id = storage.get_meta_data_apps().insert(App(0, "streamapp"))
        key = storage.get_meta_data_access_keys().insert(AccessKey("", app_id, ()))

        from aiohttp.test_utils import TestClient, TestServer

        from predictionio_tpu.data.api.event_server import (
            EventServer,
            EventServerConfig,
        )
        from predictionio_tpu.workflow.core_workflow import run_train
        from predictionio_tpu.workflow.create_server import (
            ServerConfig,
            _query_server_from_registry,
        )

        engine = _rec_engine()
        manifest = _rec_manifest()
        registry_dir = str(tmp_path / "registry")
        rng = np.random.default_rng(0)

        async def body():
            ev_server = EventServer(storage=storage, config=EventServerConfig())
            ev_client = TestClient(TestServer(ev_server.make_app()))
            await ev_client.start_server()

            async def ingest(user, item, rating, n):
                resp = await ev_client.post(
                    f"/events.json?accessKey={key}",
                    json={
                        "event": "rate",
                        "entityType": "user",
                        "entityId": user,
                        "targetEntityType": "item",
                        "targetEntityId": item,
                        "properties": {"rating": rating},
                        "eventTime": t(n).isoformat(),
                    },
                )
                assert resp.status == 201, await resp.text()

            # 1) history lands through the EventServer, batch train = stable
            n = 0
            for u in range(6):
                for it in range(4):
                    await ingest(f"u{u}", f"i{it}", float(rng.integers(1, 6)), n)
                    n += 1
            run_train(
                engine,
                manifest,
                _rec_params("streamapp"),
                storage=storage,
                registry_dir=registry_dir,
            )
            store = ArtifactStore(registry_dir)
            assert store.get_state("streamtest").stable == "v000001"

            # 2) speed layer: cursor starts at the head (stable covers
            #    history), then FRESH events arrive for a brand new user
            levents = storage.get_l_events()
            tailer = EventTailer(levents, app_id, batch_limit=50)
            cursors = CursorStore(str(tmp_path / "cursors"))
            cursor = cursors.load(app_id)
            cursor.seed(tailer.head_position())
            cursors.save(cursor)
            for j in range(20):
                await ingest("newu", f"i{j % 2}", 5.0, 1000 + j)

            models = model_io.deserialize_models(
                store.load_blob("streamtest", "v000001")
            )
            trainer = trainer_for_models(models, holdout_every=10)
            staged: list[tuple[str, str, float]] = []
            pipeline = StreamPipeline(
                tailer,
                trainer,
                cursors,
                store,
                StreamConfig(
                    engine_id="streamtest",
                    engine_version="1",
                    engine_variant="engine.json",
                    mode="canary",
                    fraction=1.0,
                ),
                stage_hook=lambda v, m, f: staged.append((v, m, f)),
            )
            summary = pipeline.run_once()
            assert summary["published"] == "v000002"
            assert staged == [("v000002", "canary", 1.0)]
            m2 = store.get_manifest("streamtest", "v000002")
            assert m2.parent_version == "v000001"  # lineage
            assert m2.data_span["stream"]["events"] == 20  # train-span
            assert m2.data_span["stream"]["trainer"] == "als-foldin"

            # 3) the candidate arrives on the EXISTING rollout path and
            #    bakes to an auto-promote under the PR-4 gates
            server = _query_server_from_registry(
                engine,
                manifest,
                store,
                "v000001",
                storage,
                ServerConfig(
                    bake_window_s=0.05,
                    bake_min_requests=5,
                    bake_check_interval_s=0.02,
                    request_timeout_s=10.0,
                    max_p95_ratio=1000.0,
                    max_batch_size=4,
                ),
            )
            q_client = TestClient(TestServer(server.make_app()))
            await q_client.start_server()
            try:
                resp = await q_client.post(
                    "/models/candidate",
                    json={"version": "v000002", "mode": "canary", "fraction": 1.0},
                )
                assert resp.status == 200, await resp.text()
                for i in range(8):
                    resp = await q_client.post(
                        "/queries.json", json={"user": f"u{i % 6}", "num": 3}
                    )
                    assert resp.status == 200, await resp.text()
                deadline = time.monotonic() + 10.0
                while server.model_version != "v000002":
                    assert time.monotonic() < deadline, "auto-promote never fired"
                    await asyncio.sleep(0.02)
                while store.get_state("streamtest").stable != "v000002":
                    assert time.monotonic() < deadline, "registry pin never moved"
                    await asyncio.sleep(0.02)
                # the promoted speed-layer model KNOWS the stream-only user
                resp = await q_client.post(
                    "/queries.json", json={"user": "newu", "num": 3}
                )
                assert resp.status == 200
                assert (await resp.json())["itemScores"]  # non-empty
            finally:
                await q_client.close()

            # 4) drift-injected run: corrupted events (poisoned ratings)
            #    suppress the publish and bump the counter
            for j in range(12):
                await ingest(f"u{j % 6}", f"i{j % 4}", 1e9, 2000 + j)
            summary = pipeline.run_once()
            assert summary["published"] is None
            assert summary["driftSuppressed"] is True
            assert pipeline.instruments.drift_suppressed.value() == 1
            assert [m.version for m in store.list_versions("streamtest")] == [
                "v000001",
                "v000002",
            ]
            await ev_client.close()

        asyncio.run(body())
