"""Admin API + dashboard route tests (ref AdminAPI.scala, Dashboard.scala)."""

import asyncio
import datetime as dt

from aiohttp.test_utils import TestClient, TestServer

from predictionio_tpu.data.storage.base import (
    EvaluationInstance,
    EvaluationInstanceStatus,
)
from predictionio_tpu.tools.admin_api import AdminServer
from predictionio_tpu.tools.dashboard import Dashboard

UTC = dt.timezone.utc


def with_client(app, fn):
    async def body():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client)
        finally:
            await client.close()

    asyncio.run(body())


class TestAdminAPI:
    def test_app_lifecycle(self, memory_storage):
        server = AdminServer(storage=memory_storage)

        async def body(client):
            resp = await client.get("/")
            assert resp.status == 200
            assert (await resp.json())["status"] == "alive"

            resp = await client.post("/cmd/app", json={"name": "adminapp"})
            assert resp.status == 201
            data = await resp.json()
            assert data["name"] == "adminapp" and data["accessKey"]

            resp = await client.post("/cmd/app", json={"name": "adminapp"})
            assert resp.status == 409

            resp = await client.get("/cmd/app")
            listing = await resp.json()
            assert [a["name"] for a in listing] == ["adminapp"]
            assert listing[0]["accessKeys"]

            resp = await client.delete("/cmd/app/adminapp/data")
            assert resp.status == 200
            resp = await client.delete("/cmd/app/adminapp")
            assert resp.status == 200
            resp = await client.delete("/cmd/app/adminapp")
            assert resp.status == 404

        with_client(server.make_app(), body)

    def test_new_app_requires_name(self, memory_storage):
        server = AdminServer(storage=memory_storage)

        async def body(client):
            resp = await client.post("/cmd/app", json={})
            assert resp.status == 400

        with_client(server.make_app(), body)


class TestDashboard:
    def test_lists_completed_evaluations(self, memory_storage):
        evis = memory_storage.get_meta_data_evaluation_instances()
        iid = evis.insert(
            EvaluationInstance(
                id="",
                status=EvaluationInstanceStatus.EVALCOMPLETED,
                start_time=dt.datetime(2024, 1, 1, tzinfo=UTC),
                end_time=dt.datetime(2024, 1, 2, tzinfo=UTC),
                evaluation_class="my.Evaluation",
                evaluator_results="[Metric] best: 0.9",
                evaluator_results_html="<h2>results</h2>",
                evaluator_results_json='{"bestScore": 0.9}',
            )
        )
        dash = Dashboard(storage=memory_storage)

        async def body(client):
            resp = await client.get("/")
            assert resp.status == 200
            page = await resp.text()
            assert "my.Evaluation" in page and "best: 0.9" in page

            resp = await client.get(f"/engine_instances/{iid}/evaluator_results.html")
            assert (await resp.text()) == "<h2>results</h2>"

            resp = await client.get(f"/engine_instances/{iid}/evaluator_results.json")
            assert (await resp.json())["bestScore"] == 0.9

            resp = await client.get("/engine_instances/nope/evaluator_results.json")
            assert resp.status == 404

        with_client(dash.make_app(), body)
