"""Property-replay golden tests (ref LEventAggregatorSpec.scala semantics)."""

import datetime as dt

from predictionio_tpu.data.aggregator import (
    aggregate_properties,
    aggregate_properties_single,
)
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event

UTC = dt.timezone.utc


def t(n):
    return dt.datetime(2024, 1, 1, 0, 0, n, tzinfo=UTC)


def ev(name, entity_id, props, n):
    return Event(
        event=name,
        entity_type="user",
        entity_id=entity_id,
        properties=DataMap(props),
        event_time=t(n),
    )


def test_set_merges_latest_wins():
    result = aggregate_properties(
        [
            ev("$set", "u1", {"a": 1, "b": 2}, 1),
            ev("$set", "u1", {"b": 3, "c": 4}, 2),
        ]
    )
    pm = result["u1"]
    assert pm.fields == {"a": 1, "b": 3, "c": 4}
    assert pm.first_updated == t(1)
    assert pm.last_updated == t(2)


def test_order_is_by_event_time_not_arrival():
    result = aggregate_properties(
        [
            ev("$set", "u1", {"b": 3}, 2),
            ev("$set", "u1", {"a": 1, "b": 2}, 1),
        ]
    )
    assert result["u1"].fields == {"a": 1, "b": 3}


def test_unset_removes_keys():
    result = aggregate_properties(
        [
            ev("$set", "u1", {"a": 1, "b": 2}, 1),
            ev("$unset", "u1", {"a": None}, 2),
        ]
    )
    assert result["u1"].fields == {"b": 2}


def test_unset_before_any_set_is_noop():
    result = aggregate_properties([ev("$unset", "u1", {"a": 1}, 1)])
    assert "u1" not in result


def test_delete_drops_entity():
    result = aggregate_properties(
        [
            ev("$set", "u1", {"a": 1}, 1),
            ev("$delete", "u1", {}, 2),
        ]
    )
    assert result == {}


def test_set_after_delete_resurrects():
    result = aggregate_properties(
        [
            ev("$set", "u1", {"a": 1}, 1),
            ev("$delete", "u1", {}, 2),
            ev("$set", "u1", {"b": 2}, 3),
        ]
    )
    assert result["u1"].fields == {"b": 2}
    # first/lastUpdated span all special events, including pre-delete ones
    assert result["u1"].first_updated == t(1)
    assert result["u1"].last_updated == t(3)


def test_non_special_events_ignored():
    result = aggregate_properties(
        [
            ev("$set", "u1", {"a": 1}, 1),
            ev("rate", "u1", {"rating": 5}, 2),
        ]
    )
    assert result["u1"].fields == {"a": 1}
    assert result["u1"].last_updated == t(1)


def test_multiple_entities_grouped():
    result = aggregate_properties(
        [
            ev("$set", "u1", {"a": 1}, 1),
            ev("$set", "u2", {"b": 2}, 2),
        ]
    )
    assert set(result) == {"u1", "u2"}


def test_aggregate_single():
    pm = aggregate_properties_single(
        [
            ev("$set", "u1", {"a": 1}, 1),
            ev("$set", "u1", {"b": 2}, 2),
        ]
    )
    assert pm is not None
    assert pm.fields == {"a": 1, "b": 2}
    assert aggregate_properties_single([ev("buy", "u1", {}, 1)]) is None
