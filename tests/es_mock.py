"""In-process mock Elasticsearch for driver contract tests.

The reference tests its ES driver against a docker-hosted service
(SURVEY.md section 4); no service exists in this sandbox, so this emulates
the REST subset the driver speaks: document CRUD, ``_search`` with
bool/term/terms/range/exists filters + sort + size, ``_count``,
``_update`` scripted counter upsert, ``_delete_by_query``, index create/
delete. State is per-server, in-memory.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any


class _State:
    def __init__(self):
        self.indices: dict[str, dict[str, dict]] = {}
        # per-index mapped field names: explicit properties from the index-
        # creation body plus dynamic mappings added as documents arrive —
        # real ES 400s a sort on an UNMAPPED field (e.g. any sorted query
        # against a fresh empty index) unless the sort spec carries
        # ``unmapped_type``, and the mock must reproduce that to catch it
        self.mappings: dict[str, set[str]] = {}
        self.scrolls: dict[str, dict] = {}  # scroll_id -> {docs, pos, size}
        self.scroll_seq = 0
        self.lock = threading.RLock()

    def note_doc_fields(self, index: str, doc: dict) -> None:
        self.mappings.setdefault(index, set()).update(doc.keys())


def _get_field(doc: dict, field: str):
    return doc.get(field)


def _matches(doc: dict, query: dict) -> bool:
    if not query or "match_all" in query:
        return True
    if "term" in query:
        ((field, value),) = query["term"].items()
        return _get_field(doc, field) == value
    if "terms" in query:
        ((field, values),) = query["terms"].items()
        return _get_field(doc, field) in values
    if "range" in query:
        ((field, spec),) = query["range"].items()
        v = _get_field(doc, field)
        if v is None:
            return False
        if "gte" in spec and not v >= spec["gte"]:
            return False
        if "gt" in spec and not v > spec["gt"]:
            return False
        if "lte" in spec and not v <= spec["lte"]:
            return False
        if "lt" in spec and not v < spec["lt"]:
            return False
        return True
    if "exists" in query:
        return _get_field(doc, query["exists"]["field"]) is not None
    if "bool" in query:
        b = query["bool"]
        for f in b.get("filter", []):
            if not _matches(doc, f):
                return False
        for f in b.get("must_not", []):
            if _matches(doc, f):
                return False
        for f in b.get("must", []):
            if not _matches(doc, f):
                return False
        return True
    raise ValueError(f"mock ES: unsupported query {query}")


class _Handler(BaseHTTPRequestHandler):
    state: _State  # injected by make_server

    def log_message(self, *args):  # silence
        pass

    def _reply(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        return json.loads(raw) if raw else {}

    def _raw_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _route(self):
        path = self.path.split("?")[0]
        parts = [p for p in path.split("/") if p]
        st = self.state
        with st.lock:
            # /_bulk — ndjson action/doc pairs ({"index": {"_index", "_id"}})
            if parts == ["_bulk"] and self.command == "POST":
                lines = [
                    json.loads(ln)
                    for ln in self._raw_body().decode().splitlines()
                    if ln.strip()
                ]
                items = []
                i = 0
                while i < len(lines):
                    action = lines[i]
                    if "index" in action:
                        meta = action["index"]
                        doc = lines[i + 1]
                        st.indices.setdefault(meta["_index"], {})[meta["_id"]] = doc
                        st.note_doc_fields(meta["_index"], doc)
                        items.append({"index": {"_id": meta["_id"], "status": 201}})
                        i += 2
                    elif "delete" in action:  # no source line follows
                        meta = action["delete"]
                        table = st.indices.setdefault(meta["_index"], {})
                        existed = table.pop(meta["_id"], None) is not None
                        items.append(
                            {
                                "delete": {
                                    "_id": meta["_id"],
                                    "status": 200 if existed else 404,
                                }
                            }
                        )
                        i += 1
                    else:
                        return self._reply(400, {"error": "unsupported action"})
                return self._reply(200, {"errors": False, "items": items})
            # /{index}/_create/{id} — atomic create-if-absent, 409 on exists
            if len(parts) == 3 and parts[1] == "_create" and self.command == "PUT":
                index, _, doc_id = parts
                table = st.indices.setdefault(index, {})
                if doc_id in table:
                    return self._reply(409, {"error": "version_conflict"})
                table[doc_id] = self._body()
                st.note_doc_fields(index, table[doc_id])
                return self._reply(201, {"result": "created", "_id": doc_id})
            # /{index}/_doc/{id}
            if len(parts) == 3 and parts[1] == "_doc":
                index, _, doc_id = parts
                table = st.indices.setdefault(index, {})
                if self.command == "PUT":
                    table[doc_id] = self._body()
                    st.note_doc_fields(index, table[doc_id])
                    return self._reply(200, {"result": "updated", "_id": doc_id})
                if self.command == "GET":
                    if doc_id in table:
                        return self._reply(
                            200,
                            {"found": True, "_id": doc_id, "_source": table[doc_id]},
                        )
                    return self._reply(404, {"found": False})
                if self.command == "DELETE":
                    if doc_id in table:
                        del table[doc_id]
                        return self._reply(200, {"result": "deleted"})
                    return self._reply(404, {"result": "not_found"})
            # /{index}/_update/{id} — scripted counter upsert
            if len(parts) == 3 and parts[1] == "_update" and self.command == "POST":
                index, _, doc_id = parts
                body = self._body()
                table = st.indices.setdefault(index, {})
                if doc_id not in table:
                    table[doc_id] = dict(body.get("upsert", {}))
                else:
                    src = body.get("script", {}).get("source", "")
                    m = re.match(r"ctx\._source\.(\w+) \+= (\d+)", src)
                    if not m:
                        return self._reply(400, {"error": "unsupported script"})
                    field, delta = m.group(1), int(m.group(2))
                    table[doc_id][field] = table[doc_id].get(field, 0) + delta
                return self._reply(
                    200, {"result": "updated", "get": {"_source": table[doc_id]}}
                )
            # /_search/scroll — scroll continuation
            if parts == ["_search", "scroll"] and self.command == "POST":
                sid = self._body().get("scroll_id")
                ctx = st.scrolls.get(sid)
                if ctx is None:
                    return self._reply(404, {"error": "search_context_missing"})
                page = ctx["docs"][ctx["pos"] : ctx["pos"] + ctx["size"]]
                ctx["pos"] += len(page)
                return self._reply(
                    200,
                    {
                        "_scroll_id": sid,
                        "hits": {
                            "total": {"value": len(ctx["docs"])},
                            "hits": [{"_source": d} for d in page],
                        },
                    },
                )
            if parts == ["_search", "scroll"] and self.command == "DELETE":
                for sid in self._body().get("scroll_id", []):
                    st.scrolls.pop(sid, None)
                return self._reply(200, {"succeeded": True})
            # /{index}/_search?scroll=... — sliced scroll initiation: the
            # "slice" clause partitions the index disjointly by doc-id hash
            # (real ES slices by shard/_id route; semantics match: the n
            # slices are disjoint and jointly exhaustive)
            if (
                len(parts) == 2
                and parts[1] == "_search"
                and self.command == "POST"
                and "scroll=" in (self.path.split("?", 1) + [""])[1]
            ):
                import zlib

                index = parts[0]
                if index not in st.indices:
                    return self._reply(404, {"error": "index_not_found"})
                body = self._body()
                sl = body.get("slice")
                docs = [
                    d
                    for key, d in st.indices[index].items()
                    if _matches(d, body.get("query", {}))
                    and (
                        sl is None
                        or zlib.crc32(str(key).encode()) % sl["max"] == sl["id"]
                    )
                ]
                size = body.get("size", 10)
                st.scroll_seq += 1
                sid = f"scroll{st.scroll_seq}"
                st.scrolls[sid] = {"docs": docs, "pos": size, "size": size}
                return self._reply(
                    200,
                    {
                        "_scroll_id": sid,
                        "hits": {
                            "total": {"value": len(docs)},
                            "hits": [{"_source": d} for d in docs[:size]],
                        },
                    },
                )
            # /{index}/_search
            if len(parts) == 2 and parts[1] == "_search" and self.command == "POST":
                index = parts[0]
                if index not in st.indices:
                    return self._reply(404, {"error": "index_not_found"})
                body = self._body()
                docs = [
                    d
                    for d in st.indices[index].values()
                    if _matches(d, body.get("query", {}))
                ]
                sort_specs = body.get("sort", [])
                mapped = st.mappings.get(index, set())
                for spec in sort_specs:
                    ((field, opts),) = spec.items()
                    # real-ES behavior: sorting on a field with no mapping
                    # (fresh empty index, or field never seen) is HTTP 400
                    # unless the spec carries unmapped_type
                    if field not in mapped and "unmapped_type" not in opts:
                        return self._reply(
                            400,
                            {
                                "error": {
                                    "type": "search_phase_execution_exception",
                                    "reason": f"No mapping found for [{field}] "
                                    "in order to sort on",
                                }
                            },
                        )
                for spec in reversed(sort_specs):
                    ((field, opts),) = spec.items()
                    docs.sort(
                        key=lambda d: (d.get(field) is None, d.get(field)),
                        reverse=opts.get("order") == "desc",
                    )
                cursor = body.get("search_after")
                if cursor is not None:
                    # drop docs at-or-before the cursor in sort order
                    def _past(doc):
                        for spec, cur in zip(sort_specs, cursor):
                            ((field, opts),) = spec.items()
                            v = doc.get(field)
                            if v == cur:
                                continue
                            gt = v is not None and cur is not None and v > cur
                            return gt != (opts.get("order") == "desc")
                        return False  # equal tuple: not past the cursor

                    docs = [d for d in docs if _past(d)]
                docs = docs[: body.get("size", 10)]
                return self._reply(
                    200,
                    {
                        "hits": {
                            "total": {"value": len(docs)},
                            "hits": [{"_source": d} for d in docs],
                        }
                    },
                )
            # /{index}/_count
            if len(parts) == 2 and parts[1] == "_count" and self.command == "POST":
                index = parts[0]
                if index not in st.indices:
                    return self._reply(404, {"error": "index_not_found"})
                return self._reply(200, {"count": len(st.indices[index])})
            # /{index}/_delete_by_query
            if (
                len(parts) == 2
                and parts[1] == "_delete_by_query"
                and self.command == "POST"
            ):
                index = parts[0]
                if index not in st.indices:
                    return self._reply(404, {"error": "index_not_found"})
                q = self._body().get("query", {})
                table = st.indices[index]
                victims = [k for k, d in table.items() if _matches(d, q)]
                for k in victims:
                    del table[k]
                return self._reply(200, {"deleted": len(victims)})
            # /{index} create / delete
            if len(parts) == 1:
                index = parts[0]
                if self.command == "PUT":
                    if index in st.indices:
                        return self._reply(
                            400, {"error": "resource_already_exists_exception"}
                        )
                    st.indices[index] = {}
                    # explicit mappings from the creation body ARE mapped
                    # even while the index is empty (dynamic-template rules
                    # are not — they materialize per arriving document)
                    props = (
                        self._body().get("mappings", {}).get("properties", {})
                    )
                    st.mappings.setdefault(index, set()).update(props.keys())
                    return self._reply(200, {"acknowledged": True})
                if self.command == "DELETE":
                    if index in st.indices:
                        del st.indices[index]
                        return self._reply(200, {"acknowledged": True})
                    return self._reply(404, {"error": "index_not_found"})
        return self._reply(400, {"error": f"mock ES: no route {self.command} {path}"})

    do_GET = do_PUT = do_POST = do_DELETE = _route


def make_server() -> tuple[ThreadingHTTPServer, str]:
    """Start a mock ES on an ephemeral port; returns (server, base_url)."""
    state = _State()
    handler = type("Handler", (_Handler,), {"state": state})
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, f"http://127.0.0.1:{server.server_port}"


if __name__ == "__main__":
    # standalone mode: serve until killed, printing the base URL first —
    # this is how the real-service contract lane is proven in-repo
    # (``PIO_TEST_ES_URL`` pointed at an EXTERNAL process, see
    # tests/test_real_service_lane.py) without a dockerized Elasticsearch
    import sys

    srv, base_url = make_server()
    print(base_url, flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.shutdown()
        sys.exit(0)
