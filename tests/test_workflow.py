"""Workflow persistence tests (ref CoreWorkflow + engine-loader behavior)."""

import json
import textwrap

import pytest

from predictionio_tpu.controller import EmptyParams, EngineParams
from predictionio_tpu.data.storage.base import EngineInstanceStatus
from predictionio_tpu.workflow import model_io
from predictionio_tpu.workflow.core_workflow import (
    load_models_for_instance,
    run_train,
)
from predictionio_tpu.workflow.engine_loader import (
    EngineLoadError,
    EngineManifest,
    load_engine,
)
from tests.sample_engine import AlgoParams, DSParams, Model0
from tests.test_engine import make_engine, params


def manifest():
    return EngineManifest(
        engine_id="sample",
        version="1",
        variant="engine.json",
        engine_factory="tests.test_engine.make_engine",
    )


class TestRunTrain:
    def test_train_persists_instance_and_model(self, memory_storage):
        instance_id = run_train(
            make_engine(), manifest(), params(), storage=memory_storage
        )
        inst = memory_storage.get_meta_data_engine_instances().get(instance_id)
        assert inst.status == EngineInstanceStatus.COMPLETED
        assert float(inst.spark_conf["train_wall_clock_sec"]) >= 0
        assert json.loads(inst.data_source_params)["id"] == 1
        blob = memory_storage.get_model_data_models().get(instance_id)
        assert blob is not None
        models = model_io.deserialize_models(blob.models)
        assert models == [Model0(3, 1, 2)]

    def test_programmatic_distributed_init_takes_worker_path(
        self, memory_storage, monkeypatch
    ):
        """A deployment that initializes jax.distributed programmatically
        (no PIO_COORDINATOR/JAX_COORDINATOR_ADDRESS env contract) must
        still put non-zero processes on the worker path — otherwise every
        process writes engine-instance metadata and models concurrently
        (advisor r4). Detection keys on the already-imported jax module,
        so no backend init is forced on pure-host engines."""
        import jax

        monkeypatch.delenv("PIO_COORDINATOR", raising=False)
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        instance_id = run_train(
            make_engine(), manifest(), params(), storage=memory_storage
        )
        assert instance_id == ""  # worker: trained but never wrote metadata
        assert not memory_storage.get_meta_data_engine_instances().get_all()

    def test_plain_jax_import_stays_on_coordinator_path(
        self, memory_storage, monkeypatch
    ):
        """jax being merely *imported* (it always is — controller.algorithm
        imports it at module level) must NOT trigger a process_count()
        probe, which would initialize the XLA backend for pure-host
        engines and contend for an exclusively-held device (code-review
        r5): without distributed init, the env-less train is single-host."""
        import jax

        monkeypatch.delenv("PIO_COORDINATOR", raising=False)
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)

        def boom():  # pragma: no cover - the assertion is that it never runs
            raise AssertionError("process_count must not be consulted")

        monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False)
        monkeypatch.setattr(jax, "process_count", boom)
        instance_id = run_train(
            make_engine(), manifest(), params(), storage=memory_storage
        )
        assert instance_id  # single-host coordinator path wrote metadata

    def test_profile_dir_writes_xla_trace(self, memory_storage, tmp_path, monkeypatch):
        """PIO_PROFILE_DIR wraps engine.train in a jax profiler trace (the
        perf-attribution tool the reference lacks, SURVEY.md §5); the
        trace artifacts must land in the directory and training still
        completes normally."""
        import os

        trace_dir = tmp_path / "trace"
        monkeypatch.setenv("PIO_PROFILE_DIR", str(trace_dir))
        instance_id = run_train(
            make_engine(), manifest(), params(), storage=memory_storage
        )
        inst = memory_storage.get_meta_data_engine_instances().get(instance_id)
        assert inst.status == EngineInstanceStatus.COMPLETED
        produced = [
            os.path.join(root, f)
            for root, _, files in os.walk(trace_dir)
            for f in files
        ]
        assert produced, "no trace artifacts written"

    def test_get_latest_completed_finds_it(self, memory_storage):
        run_train(make_engine(), manifest(), params(), storage=memory_storage)
        iid2 = run_train(make_engine(), manifest(), params(), storage=memory_storage)
        latest = memory_storage.get_meta_data_engine_instances().get_latest_completed(
            "sample", "1", "engine.json"
        )
        assert latest.id == iid2

    def test_failure_marks_failed(self, memory_storage):
        ep = params()
        ep.data_source = ("ds", DSParams(id=1, fail_sanity=True))
        with pytest.raises(AssertionError):
            run_train(make_engine(), manifest(), ep, storage=memory_storage)
        instances = memory_storage.get_meta_data_engine_instances().get_all()
        assert [i.status for i in instances] == [EngineInstanceStatus.FAILED]
        assert (
            memory_storage.get_meta_data_engine_instances().get_latest_completed(
                "sample", "1", "engine.json"
            )
            is None
        )

    def test_load_models_for_instance(self, memory_storage):
        iid = run_train(make_engine(), manifest(), params(), storage=memory_storage)
        models = load_models_for_instance(
            make_engine(), params(), iid, storage=memory_storage
        )
        assert models == [Model0(3, 1, 2)]


class TestModelIO:
    def test_roundtrip_with_jax_arrays(self):
        import jax.numpy as jnp
        import numpy as np

        model = {"w": jnp.arange(8.0), "meta": "x", "n": 3}
        from predictionio_tpu.controller import model_to_host

        blob = model_io.serialize_models([model_to_host(model)])
        (restored,) = model_io.deserialize_models(blob)
        assert isinstance(restored["w"], np.ndarray)
        np.testing.assert_array_equal(restored["w"], np.arange(8.0))
        assert restored["meta"] == "x"

    def test_bad_magic(self):
        with pytest.raises(ValueError):
            model_io.deserialize_models(b"garbage")


class TestEngineLoader:
    def test_load_engine_dir(self, tmp_path):
        (tmp_path / "myengine.py").write_text(
            textwrap.dedent(
                """
                from predictionio_tpu.controller import Engine
                from tests.sample_engine import (
                    Algo0, DataSource0, Preparator0, Serving0)

                def engine_factory():
                    return Engine(
                        {"ds": DataSource0}, {"prep": Preparator0},
                        {"a": Algo0}, {"s": Serving0})
                """
            )
        )
        (tmp_path / "engine.json").write_text(
            json.dumps(
                {
                    "id": "default",
                    "description": "test engine",
                    "engineFactory": "myengine.engine_factory",
                    "datasource": {"name": "ds", "params": {"id": 4}},
                    "preparator": {"name": "prep", "params": {"id": 5}},
                    "algorithms": [{"name": "a", "params": {"id": 6}}],
                    "serving": {"name": "s"},
                }
            )
        )
        man, engine = load_engine(str(tmp_path))
        assert man.engine_factory == "myengine.engine_factory"
        ep = engine.engine_params_from_variant(man.variant_json)
        from predictionio_tpu.workflow.context import WorkflowContext

        models = engine.train(WorkflowContext(), ep)
        assert models == [Model0(6, 4, 5)]

    def test_template_min_version_enforced(self, tmp_path):
        (tmp_path / "engine.json").write_text(
            json.dumps({"engineFactory": "x.y"})
        )
        (tmp_path / "template.json").write_text(
            json.dumps({"pio": {"version": {"min": "99.0.0"}}})
        )
        with pytest.raises(EngineLoadError):
            load_engine(str(tmp_path))

    def test_missing_variant(self, tmp_path):
        with pytest.raises(EngineLoadError):
            load_engine(str(tmp_path))


class TestEngineIdentity:
    """Two engine dirs with the template-default id must not share a deploy
    lineage (regression: deploy once served another engine's model)."""

    def _scaffold(self, tmp_path, name):
        import json as _json

        d = tmp_path / name
        d.mkdir()
        (d / "engine.json").write_text(
            _json.dumps(
                {
                    "id": "default",
                    "engineFactory": "tests.test_engine.make_engine",
                    "datasource": {"name": "ds", "params": {"id": 1}},
                    "preparator": {"name": "prep", "params": {"id": 2}},
                    "algorithms": [{"name": "a", "params": {"id": 3}}],
                    "serving": {"name": "s"},
                }
            )
        )
        return str(d)

    def test_distinct_dirs_distinct_ids(self, tmp_path):
        from predictionio_tpu.workflow.engine_loader import load_manifest

        m1 = load_manifest(self._scaffold(tmp_path, "rec-a"))
        m2 = load_manifest(self._scaffold(tmp_path, "rec-b"))
        assert m1.engine_id != m2.engine_id

    def test_explicit_id_wins(self, tmp_path):
        import json as _json

        from predictionio_tpu.workflow.engine_loader import load_manifest

        d = tmp_path / "explicit"
        d.mkdir()
        (d / "engine.json").write_text(
            _json.dumps({"id": "my-engine", "engineFactory": "x.y"})
        )
        assert load_manifest(str(d)).engine_id == "my-engine"


class TestFakeRun:
    """Ref FakeWorkflow.scala:18-109 — arbitrary func under the workflow env,
    result never persisted (noSave)."""

    def test_func_runs_and_nothing_persisted(self, memory_storage):
        from predictionio_tpu.workflow.core_workflow import run_evaluation
        from predictionio_tpu.workflow.fake_workflow import FakeRun

        seen = {}

        def f(ctx):
            seen["mode"] = ctx.mode
            return 42

        instance_id, result = run_evaluation(
            FakeRun(f), storage=memory_storage, batch="hello"
        )
        assert seen["mode"] == "evaluation"
        assert result.value == 42 and result.no_save
        inst = memory_storage.get_meta_data_evaluation_instances().get(instance_id)
        # instance record exists but results were never written back
        assert inst is not None
        assert inst.evaluator_results == ""
        assert inst.status != "EVALCOMPLETED"

    def test_subclass_style(self, memory_storage):
        from predictionio_tpu.workflow.core_workflow import run_evaluation
        from predictionio_tpu.workflow.fake_workflow import FakeRun

        class Hello(FakeRun):
            @staticmethod
            def func(ctx):
                return "hi"

        _, result = run_evaluation(Hello(), storage=memory_storage)
        assert result.value == "hi"

    def test_no_func_raises(self):
        from predictionio_tpu.workflow.context import WorkflowContext
        from predictionio_tpu.workflow.fake_workflow import FakeRun

        with pytest.raises(ValueError):
            FakeRun().run(WorkflowContext(mode="evaluation"))

    def test_plain_function_class_attribute(self, memory_storage):
        """`func = my_fn` without @staticmethod (the natural spelling) must
        receive the WorkflowContext, not a bound FakeRun instance
        (code-review r4: descriptor binding turned it into a method)."""
        from predictionio_tpu.workflow.context import WorkflowContext
        from predictionio_tpu.workflow.fake_workflow import FakeRun

        def my_fn(ctx):
            return ctx.mode

        class Hello(FakeRun):
            func = my_fn

        result = Hello().run(WorkflowContext(mode="evaluation"))
        assert result.value == "evaluation"

    def test_lambda_class_attribute(self):
        from predictionio_tpu.workflow.context import WorkflowContext
        from predictionio_tpu.workflow.fake_workflow import FakeRun

        class Hello(FakeRun):
            func = lambda ctx: ctx.mode  # noqa: E731

        assert Hello().run(WorkflowContext(mode="evaluation")).value == "evaluation"

    def test_callable_instance_class_attribute(self):
        """A callable INSTANCE (defines __call__, no __get__) assigned as
        `func` must be invoked, not passed to descriptor binding (advisor
        r4: raw.__get__ raised AttributeError for >=2-positional
        callables)."""
        from predictionio_tpu.workflow.context import WorkflowContext
        from predictionio_tpu.workflow.fake_workflow import FakeRun

        class TwoArgCallable:
            def __call__(self, ctx, extra=None):
                return ctx.mode

        class Hello(FakeRun):
            func = TwoArgCallable()

        assert Hello().run(WorkflowContext(mode="evaluation")).value == "evaluation"

    def test_conventional_method_spelling(self):
        """def func(self, ctx) — the ordinary method spelling — must still
        bind and receive both self and the context (arity decides)."""
        from predictionio_tpu.workflow.context import WorkflowContext
        from predictionio_tpu.workflow.fake_workflow import FakeRun

        class Hello(FakeRun):
            tag = "m"

            def func(self, ctx):
                return f"{self.tag}:{ctx.mode}"

        assert Hello().run(WorkflowContext(mode="evaluation")).value == "m:evaluation"


class TestRemoteLog:
    """Ref CreateServer.scala:423-434,595-611 — --log-url ships serving
    errors to an HTTP collector as log_prefix + JSON{engineInstance, message}."""

    def test_query_error_shipped_to_collector(self, memory_storage):
        import asyncio

        from aiohttp import web
        from aiohttp.test_utils import TestClient, TestServer

        from predictionio_tpu.workflow.create_server import QueryServer, ServerConfig
        from tests.test_engine import make_engine, params

        received = []

        async def collect(request):
            received.append(await request.text())
            return web.json_response({})

        engine = make_engine()
        ep = params()

        async def body():
            collector = web.Application()
            collector.router.add_post("/log", collect)
            cserver = TestServer(collector)
            await cserver.start_server()
            try:
                url = f"http://{cserver.host}:{cserver.port}/log"
                qs = QueryServer(
                    engine=engine,
                    engine_params=ep,
                    models=[object()],
                    manifest=manifest(),
                    instance_id="inst-1",
                    storage=memory_storage,
                    config=ServerConfig(log_url=url, log_prefix="PFX"),
                )
                client = TestClient(TestServer(qs.make_app()))
                await client.start_server()
                try:
                    resp = await client.post("/queries.json", json={"bogus": 1})
                    assert resp.status == 400
                    # the remote log POST is fire-and-forget; let it land
                    for _ in range(50):
                        if received:
                            break
                        await asyncio.sleep(0.02)
                finally:
                    await client.close()
            finally:
                await cserver.close()

        asyncio.get_event_loop_policy().new_event_loop().run_until_complete(body())
        assert received, "collector never received the error log"
        body_text = received[0]
        assert body_text.startswith("PFX")
        payload = json.loads(body_text.removeprefix("PFX"))
        assert payload["engineInstance"] == "inst-1"
        assert "Stack Trace" in payload["message"]
