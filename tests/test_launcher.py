"""Multi-host launcher end-to-end tests.

Reference parity: the reference exercises its process-runner path through the
integration suite (``/root/reference/tests/pio_tests/tests.py:52-100`` launches
a real eventserver and drives the CLI as subprocesses). Here the equivalent is
``MultiHostLauncher`` spawning real worker processes that rendezvous over the
``PIO_COORDINATOR`` contract (``/root/reference/tools/src/main/scala/org/apache/
predictionio/tools/Runner.scala:185-334`` is the launch/supervise model).

Covers:
  - 2-process local rendezvous: both ranks build a global 4-device mesh,
    run one sharded jit reduction spanning processes, and exit 0.
  - fail-fast supervision: ``--fail-rank 1`` makes rank 1 exit nonzero while
    rank 0 blocks in rendezvous; the launcher must terminate the survivor
    and report ``LaunchResult.ok == False``.
"""

from __future__ import annotations

import os
import sys
import threading

from predictionio_tpu.parallel.launcher import LaunchResult, MultiHostLauncher

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "dist_worker.py")


def _run_with_watchdog(launcher: MultiHostLauncher, timeout_s: float) -> LaunchResult:
    """Run the launcher; a watchdog kills the fleet if it wedges so a broken
    rendezvous fails the test instead of hanging the suite."""
    timer = threading.Timer(timeout_s, launcher.terminate)
    timer.start()
    try:
        return launcher.run()
    finally:
        timer.cancel()


def _clean_env() -> dict[str, str]:
    # the workers set their own JAX_PLATFORMS/XLA_FLAGS; scrub any inherited
    # coordinator triplet so a nested test run can't confuse the rendezvous
    return {
        "PIO_COORDINATOR": "",
        "PIO_NUM_PROCESSES": "",
        "PIO_PROCESS_ID": "",
        "JAX_PLATFORMS": "cpu",
    }


def test_two_process_rendezvous():
    launcher = MultiHostLauncher(
        command=[sys.executable, WORKER],
        num_hosts=2,
        env_extra=_clean_env(),
        stream_logs=True,
    )
    result = _run_with_watchdog(launcher, timeout_s=120.0)
    assert result.ok, f"rendezvous workers failed: rcs={result.returncodes}"
    assert result.returncodes == [0, 0]


def test_fail_fast_terminates_survivor():
    launcher = MultiHostLauncher(
        command=[sys.executable, WORKER, "--fail-rank", "1"],
        num_hosts=2,
        env_extra=_clean_env(),
        stream_logs=True,
    )
    result = _run_with_watchdog(launcher, timeout_s=120.0)
    assert not result.ok
    # rank 1 simulated its failure (rc=3); rank 0 was blocked in rendezvous
    # and must have been terminated by the supervisor, not left running
    assert result.returncodes[1] == 3
    assert result.returncodes[0] != 0, (
        "surviving rank should have been terminated by fail-fast supervision"
    )
