"""The examples/custom_engine walkthrough must actually work end-to-end:
load via the engine loader, train through run_train, serve through the
engine's decode/serve path (the same plumbing `pio train`/`deploy` uses)."""

import datetime as dt
import os

import numpy as np

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.workflow.core_workflow import (
    load_models_for_instance,
    run_train,
)
from predictionio_tpu.workflow.engine_loader import load_engine

UTC = dt.timezone.utc
ENGINE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples",
    "custom_engine",
)


def _seed(storage, app_id):
    lev = storage.get_l_events()
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    # i0: many old views; i1: few recent buys (wins on decay+weight);
    # i2: one recent view
    for k in range(10):
        lev.insert(
            Event(event="view", entity_type="user", entity_id=f"u{k}",
                  target_entity_type="item", target_entity_id="i0",
                  event_time=t0),
            app_id,
        )
    recent = t0 + dt.timedelta(days=60)
    for k in range(3):
        lev.insert(
            Event(event="buy", entity_type="user", entity_id=f"u{k}",
                  target_entity_type="item", target_entity_id="i1",
                  event_time=recent),
            app_id,
        )
    lev.insert(
        Event(event="view", entity_type="user", entity_id="u0",
              target_entity_type="item", target_entity_id="i2",
              event_time=recent),
        app_id,
    )
    # an event type the data source must ignore
    lev.insert(
        Event(event="rate", entity_type="user", entity_id="u0",
              target_entity_type="item", target_entity_id="i9",
              properties=DataMap({"rating": 5.0}), event_time=recent),
        app_id,
    )


def test_walkthrough_engine_end_to_end(memory_storage):
    from predictionio_tpu.data.storage.base import App

    app_id = memory_storage.get_meta_data_apps().insert(App(0, "MyApp1"))
    _seed(memory_storage, app_id)

    manifest, engine = load_engine(ENGINE_DIR)
    params = engine.engine_params_from_variant(manifest.variant_json)
    instance_id = run_train(
        engine, manifest, params, storage=memory_storage
    )
    assert instance_id

    models = load_models_for_instance(
        engine, params, instance_id, storage=memory_storage
    )
    _, _, algorithms, serving = engine.make_components(params)
    algo = algorithms[0]
    query = engine.decode_query({"num": 2})
    result = algo.predict(models[0], query)
    encoded = engine.encode_result(serving.serve(query, [result]))
    items = [s["item"] for s in encoded["itemScores"]]
    # 60-day-old views decayed ~2^-8.6 with half-life 7d; recent weighted
    # buys dominate
    assert items[0] == "i1"
    assert "i9" not in items  # ignored event type never enters the model
    assert len(items) == 2

    blk = engine.decode_query({"num": 2, "blacklist": ["i1"]})
    res2 = algo.predict(models[0], blk)
    assert all(s.item != "i1" for s in res2.item_scores)


def test_walkthrough_evaluation(memory_storage):
    """`pio eval engine.evaluation` sweeps half-life variants with the
    HitAtK metric over k folds and persists a best score."""
    import sys

    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import run_evaluation

    app_id = memory_storage.get_meta_data_apps().insert(App(0, "MyApp1"))
    _seed(memory_storage, app_id)
    sys.path.insert(0, ENGINE_DIR)
    try:
        import engine as example_engine  # noqa: PLC0415 - the walkthrough module

        evaluation = example_engine.evaluation()
        ctx = WorkflowContext(mode="evaluation", _storage=memory_storage)
        iid, result = run_evaluation(
            evaluation, ctx=ctx, storage=memory_storage
        )
        assert 0.0 <= result.best_score <= 1.0
        inst = memory_storage.get_meta_data_evaluation_instances().get(iid)
        assert inst.status == "EVALCOMPLETED"
    finally:
        sys.path.remove(ENGINE_DIR)
