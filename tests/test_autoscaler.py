"""SLO-driven elasticity tests (docs/fleet.md §Autoscaling, ISSUE 13).

Three tiers, mirroring test_fleet.py:

- policy units — the :class:`ScalingPolicy` decision engine driven by a
  fake clock and hand-built telemetry-ring records: scale-out on burn /
  sustained queue depth / sheds, scale-in on sustained idle, hysteresis
  and cooldown suppression, min/max envelope clamps with cpu-fallback
  spill, and mid-bake deferral — no process, no socket, no sleep;
- membership integration — runtime replica add/retire through the
  gateway's locked membership funnel (new requests stop routing, an
  in-flight request to a retiring replica completes, retired gauges drop
  from the exposition) and the supervisor's spawn-at-runtime/graceful
  retire with fake clocks and procs;
- e2e (slow, run by scripts/run_chaos.sh) — a spike trace against a
  REAL 1->3->1 fleet: zero client-visible 5xx during both the scale-out
  and the drain-based scale-in, scaling decisions landing in the
  telemetry ring, and an incident bundle when the envelope saturates.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

import pytest

from predictionio_tpu.fleet.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    DEFER,
    Decision,
    FleetShape,
    HOLD,
    SCALE_IN,
    SCALE_OUT,
    ScalingPolicy,
    registry_rollout_probe,
)
from predictionio_tpu.fleet.supervisor import (
    REPLICA_CLASS_CPU,
    REPLICA_CLASS_DEVICE,
    Supervisor,
    SupervisorConfig,
    WorkerSpec,
)
from predictionio_tpu.obs.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rec(
    t: float,
    burn: float = 0.0,
    qd: float = 0.0,
    healthy: int = 1,
    shed: float = 0.0,
    inflight: float = 0.0,
) -> dict:
    """One fake fleet snapshot, shaped like Gateway.fleet_snapshot()."""
    return {
        "kind": "fleet",
        "t": t,
        "replicas": {f"r{i}": {"healthy": True} for i in range(healthy)},
        "gauges": {"queue_depth": qd, "inflight": inflight},
        "counters": {"no_replica": shed, "load_shed": 0.0},
        "slo": {
            "fleet-latency": {
                "alerting": False,
                "burn": {"300": burn, "3600": burn / 2.0},
            }
        },
    }


def _policy(**kw) -> ScalingPolicy:
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("confirm_s", 10.0)
    kw.setdefault("idle_sustain_s", 60.0)
    kw.setdefault("scale_out_cooldown_s", 30.0)
    kw.setdefault("scale_in_cooldown_s", 60.0)
    return ScalingPolicy(AutoscalerConfig(**kw))


NOW = 10_000.0


class TestScalingPolicyScaleOut:
    def test_sustained_burn_scales_out(self):
        p = _policy()
        recs = [_rec(NOW - 8, burn=2.0), _rec(NOW - 4, burn=2.0), _rec(NOW - 1, burn=2.0)]
        d = p.decide(recs, FleetShape(1, 0), False, NOW)
        assert (d.action, d.reason, d.replica_class) == (
            SCALE_OUT,
            "burn",
            REPLICA_CLASS_DEVICE,
        )

    def test_one_pressured_record_is_probe_noise(self):
        """Hysteresis: a single hot snapshot (one probe interval) must
        not resize the fleet."""
        p = _policy()
        recs = [_rec(NOW - 8, burn=0.0), _rec(NOW - 1, burn=5.0)]
        d = p.decide(recs, FleetShape(1, 0), False, NOW)
        assert d.action == HOLD
        # ... and a lone record in the window is never enough
        d = p.decide([_rec(NOW - 1, burn=5.0)], FleetShape(1, 0), False, NOW)
        assert d.action == HOLD

    def test_sustained_queue_depth_scales_out(self):
        p = _policy(queue_depth_high=8.0)
        recs = [
            _rec(NOW - 6, qd=20.0, healthy=2),
            _rec(NOW - 1, qd=24.0, healthy=2),
        ]
        d = p.decide(recs, FleetShape(2, 0), False, NOW)
        assert (d.action, d.reason) == (SCALE_OUT, "queue-depth")
        # the same depth spread over enough replicas is NOT pressure
        recs = [
            _rec(NOW - 6, qd=20.0, healthy=4),
            _rec(NOW - 1, qd=24.0, healthy=4),
        ]
        assert p.decide(recs, FleetShape(4, 0), False, NOW).action == HOLD

    def test_fresh_shed_triggers_alone_without_confirmation(self):
        """A shed already cost users 503s: a fresh shed delta triggers
        even when the newest record samples calm (clients backing off
        must not veto the response)."""
        p = _policy()
        recs = [_rec(NOW - 30, shed=0.0), _rec(NOW - 1, shed=5.0)]
        d = p.decide(recs, FleetShape(1, 0), False, NOW)
        assert (d.action, d.reason) == (SCALE_OUT, "shed")

    def test_cooldown_suppresses_back_to_back_scale_out(self):
        p = _policy(scale_out_cooldown_s=30.0)
        recs = [_rec(NOW - 8, burn=2.0), _rec(NOW - 1, burn=2.0)]
        d = p.decide(recs, FleetShape(1, 0), False, NOW)
        assert d.action == SCALE_OUT
        p.note_applied(d, NOW)
        recs2 = [_rec(NOW + 2, burn=2.0), _rec(NOW + 9, burn=2.0)]
        assert p.decide(recs2, FleetShape(2, 0), False, NOW + 10).action == HOLD
        assert (
            p.decide(recs2, FleetShape(2, 0), False, NOW + 10).reason
            == "cooldown-out"
        )
        # past the cooldown the same pressure acts again
        recs3 = [_rec(NOW + 32, burn=2.0), _rec(NOW + 39, burn=2.0)]
        assert p.decide(recs3, FleetShape(2, 0), False, NOW + 40).action == SCALE_OUT

    def test_unapplied_decision_starts_no_cooldown(self):
        """A resize the executor failed to apply must stay actionable:
        only note_applied starts the cooldown clock."""
        p = _policy()
        recs = [_rec(NOW - 8, burn=2.0), _rec(NOW - 1, burn=2.0)]
        assert p.decide(recs, FleetShape(1, 0), False, NOW).action == SCALE_OUT
        assert p.decide(recs, FleetShape(1, 0), False, NOW).action == SCALE_OUT

    def test_max_clamp_spills_to_cpu_fallback_then_saturates(self):
        p = _policy(max_replicas=2, cpu_fallback_max=1)
        recs = [_rec(NOW - 8, burn=2.0), _rec(NOW - 1, burn=2.0)]
        d = p.decide(recs, FleetShape(2, 0), False, NOW)
        assert (d.action, d.replica_class) == (SCALE_OUT, REPLICA_CLASS_CPU)
        d = p.decide(recs, FleetShape(2, 1), False, NOW)
        assert (d.action, d.reason) == (HOLD, "saturated")

    def test_confirm_fraction_tolerates_aliased_cold_samples(self):
        """One cold instant sampled inside an otherwise hot window must
        not veto the scale-out (live-verify finding: the gateway's
        instantaneous gauges alias under bursty scheduling)."""
        p = _policy(confirm_fraction=0.8)
        recs = [_rec(NOW - 9 + i, burn=2.0) for i in range(9)]
        recs[4] = _rec(NOW - 5, burn=0.0)  # 8/9 hot ≈ 0.89 >= 0.8
        d = p.decide(recs, FleetShape(1, 0), False, NOW)
        assert d.action == SCALE_OUT
        # ...but a half-cold window is still no trend
        for i in range(0, 9, 2):
            recs[i] = _rec(NOW - 9 + i, burn=0.0)
        assert p.decide(recs, FleetShape(1, 0), False, NOW).action == HOLD

    def test_inflight_peak_signal_beats_instant_aliasing(self):
        """The per-tick PEAK concurrency pressures even when every
        instant sample landed on an idle moment."""
        p = _policy(inflight_high_per_replica=16.0)
        recs = [_rec(NOW - 6, healthy=1), _rec(NOW - 1, healthy=1)]
        for r in recs:
            r["gauges"]["inflight"] = 0.0
            r["gauges"]["inflight_peak"] = 24.0
        d = p.decide(recs, FleetShape(1, 0), False, NOW)
        assert (d.action, d.reason) == (SCALE_OUT, "inflight")
        # and a nonzero peak BLOCKS the idle detector symmetrically
        p2 = _policy(idle_sustain_s=60.0, idle_inflight_per_replica=1.0)
        idle = [_rec(t) for t in range(int(NOW - 70), int(NOW), 10)]
        for r in idle:
            r["gauges"]["inflight_peak"] = 9.0
        assert p2.decide(idle, FleetShape(3, 0), False, NOW).action == HOLD

    def test_cpu_fallback_disabled_saturates_at_device_max(self):
        p = _policy(max_replicas=2, cpu_fallback_max=0)
        recs = [_rec(NOW - 8, burn=2.0), _rec(NOW - 1, burn=2.0)]
        d = p.decide(recs, FleetShape(2, 0), False, NOW)
        assert (d.action, d.reason) == (HOLD, "saturated")


class TestScalingPolicyScaleIn:
    def _idle_records(self, start: float, end: float, step: float = 10.0):
        t, out = start, []
        while t <= end:
            out.append(_rec(t))
            t += step
        return out

    def test_sustained_idle_scales_in(self):
        p = _policy(idle_sustain_s=60.0)
        recs = self._idle_records(NOW - 70, NOW - 1)
        d = p.decide(recs, FleetShape(3, 0), False, NOW)
        assert (d.action, d.replica_class) == (SCALE_IN, REPLICA_CLASS_DEVICE)

    def test_idle_window_must_be_covered(self):
        """Two cold records ten seconds apart must not vouch for a
        minute of idleness."""
        p = _policy(idle_sustain_s=60.0)
        recs = [_rec(NOW - 12), _rec(NOW - 2)]
        assert p.decide(recs, FleetShape(3, 0), False, NOW).action == HOLD

    def test_warm_burn_blocks_scale_in(self):
        p = _policy(idle_sustain_s=60.0, idle_burn_max=0.25)
        recs = self._idle_records(NOW - 70, NOW - 1)
        recs[-1] = _rec(NOW - 1, burn=0.5)
        assert p.decide(recs, FleetShape(3, 0), False, NOW).action == HOLD

    def test_sheds_in_window_block_scale_in(self):
        p = _policy(idle_sustain_s=60.0)
        recs = self._idle_records(NOW - 70, NOW - 1)
        recs[-1]["counters"]["no_replica"] = 2.0
        assert p.decide(recs, FleetShape(3, 0), False, NOW).action == HOLD

    def test_min_clamp_holds_at_floor(self):
        p = _policy(min_replicas=1)
        recs = self._idle_records(NOW - 70, NOW - 1)
        d = p.decide(recs, FleetShape(1, 0), False, NOW)
        assert (d.action, d.reason) == (HOLD, "at-floor")

    def test_cpu_fallback_retires_first(self):
        p = _policy(cpu_fallback_max=2)
        recs = self._idle_records(NOW - 70, NOW - 1)
        d = p.decide(recs, FleetShape(2, 1), False, NOW)
        assert (d.action, d.replica_class) == (SCALE_IN, REPLICA_CLASS_CPU)

    def test_scale_in_cooldown_counts_any_resize(self):
        """An idle dip right after a scale-out must not whipsaw the
        fleet back down."""
        p = _policy(idle_sustain_s=60.0, scale_in_cooldown_s=120.0)
        out = p.decide(
            [_rec(NOW - 8, burn=2.0), _rec(NOW - 1, burn=2.0)],
            FleetShape(1, 0),
            False,
            NOW,
        )
        p.note_applied(out, NOW)
        recs = self._idle_records(NOW + 10, NOW + 80)
        d = p.decide(recs, FleetShape(2, 0), False, NOW + 81)
        assert (d.action, d.reason) == (HOLD, "cooldown-in")


class TestScalingPolicyMidBake:
    def test_resize_mid_bake_is_deferred_then_fires(self):
        p = _policy()
        recs = [_rec(NOW - 8, burn=2.0), _rec(NOW - 1, burn=2.0)]
        d = p.decide(recs, FleetShape(1, 0), rollout_active=True, now=NOW)
        assert d.action == DEFER
        assert d.reason.startswith("mid-bake")
        assert p.pending is not None
        # still baking: stays deferred (pending survives)
        d = p.decide([], FleetShape(1, 0), rollout_active=True, now=NOW + 30)
        assert d.action == HOLD and p.pending is not None
        # bake ended: the DEFERRED resize fires even though the signal
        # that wanted it is stale (records empty)
        d = p.decide([], FleetShape(1, 0), rollout_active=False, now=NOW + 60)
        assert d.action == SCALE_OUT and d.deferred is True
        p.note_applied(d, NOW + 60)
        assert p.pending is None

    def test_deferred_resize_reclamped_against_current_shape(self):
        """The fleet may have drifted while baking (crash, park): a
        deferral that no longer fits the envelope dissolves instead of
        over-scaling."""
        p = _policy(max_replicas=2)
        recs = [_rec(NOW - 8, burn=2.0), _rec(NOW - 1, burn=2.0)]
        p.decide(recs, FleetShape(1, 0), rollout_active=True, now=NOW)
        assert p.pending is not None
        d = p.decide([], FleetShape(2, 0), rollout_active=False, now=NOW + 60)
        assert (d.action, d.reason) == (HOLD, "saturated")
        assert p.pending is None

    def test_scale_in_mid_bake_is_deferred_too(self):
        p = _policy(idle_sustain_s=60.0)
        recs = [_rec(t) for t in range(int(NOW - 70), int(NOW), 10)]
        d = p.decide(recs, FleetShape(3, 0), rollout_active=True, now=NOW)
        assert d.action == DEFER and p.pending.action == SCALE_IN

    def test_defer_is_an_episode_not_a_tick_counter(self):
        """The same resize re-wanted on later ticks of the same bake
        updates the pending slot silently: one DEFER per deferral, so
        the counter/ring record the Autoscaler emits count resizes
        deferred, not ticks spent baking."""
        p = _policy()
        recs = [_rec(NOW - 8, burn=2.0), _rec(NOW - 1, burn=2.0)]
        assert p.decide(recs, FleetShape(1, 0), True, NOW).action == DEFER
        later = [_rec(NOW + 2, burn=2.0), _rec(NOW + 9, burn=2.0)]
        d = p.decide(later, FleetShape(1, 0), True, NOW + 10)
        assert (d.action, d.reason) == (HOLD, "mid-bake-pending")
        assert p.pending is not None and p.pending.action == SCALE_OUT

    def test_deferred_scale_in_dissolves_into_a_fresh_spike(self):
        """The world moved while the bake ran: a scale-in deferred during
        an idle spell must NOT retire capacity into a spike that arrived
        mid-bake — a contradicted deferral dissolves."""
        p = _policy(idle_sustain_s=60.0)
        idle = [_rec(t) for t in range(int(NOW - 70), int(NOW), 10)]
        assert p.decide(idle, FleetShape(3, 0), True, NOW).action == DEFER
        spike = [
            _rec(NOW + 50, burn=3.0),
            _rec(NOW + 55, burn=3.0),
            _rec(NOW + 59, burn=3.0),
        ]
        d = p.decide(spike, FleetShape(3, 0), False, NOW + 60)
        assert d.action == HOLD and "contradicted" in d.reason
        assert p.pending is None
        # ...and the spike itself acts normally on the NEXT tick (given
        # envelope headroom)
        assert p.decide(spike, FleetShape(2, 0), False, NOW + 60).action == SCALE_OUT

    def test_deferred_scale_out_dissolves_when_fleet_went_idle(self):
        p = _policy(idle_sustain_s=60.0)
        hot = [_rec(NOW - 8, burn=2.0), _rec(NOW - 1, burn=2.0)]
        assert p.decide(hot, FleetShape(2, 0), True, NOW).action == DEFER
        idle = [
            _rec(t) for t in range(int(NOW + 100), int(NOW + 170), 10)
        ]
        d = p.decide(idle, FleetShape(2, 0), False, NOW + 170)
        assert d.action == HOLD and "contradicted" in d.reason
        assert p.pending is None


class TestScalingPolicyShedBaseline:
    def test_stale_shed_outside_confirm_window_never_retriggers(self):
        """Sheds from minutes ago must not ratchet the fleet up off one
        transiently-pressured record: the shed delta baselines against
        the newest record just OUTSIDE the confirm window."""
        p = _policy(confirm_s=10.0)
        recs = [
            _rec(NOW - 400, shed=5.0),  # old incident, long recovered
            _rec(NOW - 60, shed=5.0),
            _rec(NOW - 15, shed=5.0),  # newest pre-window record
            _rec(NOW - 1, burn=5.0, shed=5.0),  # one hot record, no NEW shed
        ]
        d = p.decide(recs, FleetShape(1, 0), False, NOW)
        assert d.action == HOLD  # one pressured record stays probe noise

    def test_fresh_shed_inside_confirm_window_triggers(self):
        p = _policy(confirm_s=10.0)
        recs = [
            _rec(NOW - 15, shed=5.0),
            _rec(NOW - 1, burn=5.0, shed=8.0),  # 3 NEW sheds in-window
        ]
        d = p.decide(recs, FleetShape(1, 0), False, NOW)
        assert (d.action, d.reason) == (SCALE_OUT, "shed")


# ---------------------------------------------------------------------------
# supervisor: spawn-at-runtime + graceful retire (fake clock + proc)
# ---------------------------------------------------------------------------


class TestSupervisorElasticity:
    def _sup(self, **cfg_kw):
        from tests.test_fleet import FakeClock, FakeProc

        clock = FakeClock()
        spawned: list = []

        def spawn(spec):
            p = FakeProc(ignore_term=cfg_kw.pop("_ignore_term", False))
            spawned.append(p)
            return p

        ignore = cfg_kw.pop("ignore_term", False)
        if ignore:

            def spawn(spec):  # noqa: F811 - deliberate override
                from tests.test_fleet import FakeProc as FP

                p = FP(ignore_term=True)
                spawned.append(p)
                return p

        sup = Supervisor(
            spawn,
            [WorkerSpec("w0", 9000)],
            SupervisorConfig(**cfg_kw),
            clock=clock,
        )
        return sup, spawned, clock

    def test_add_worker_spawns_and_supervises(self):
        sup, spawned, clock = self._sup(backoff_base_s=0.0)
        sup.start()
        sup.add_worker(WorkerSpec("w1", 9001, REPLICA_CLASS_CPU))
        assert len(spawned) == 2
        assert [s.name for s in sup.live_specs()] == ["w0", "w1"]
        # the restart policy covers the added worker too
        spawned[-1].exit(1)
        sup.tick()  # reap
        sup.tick()  # respawn (zero backoff)
        assert len(spawned) == 3

    def test_add_worker_rejects_duplicate_name(self):
        sup, spawned, clock = self._sup()
        sup.start()
        with pytest.raises(ValueError):
            sup.add_worker(WorkerSpec("w0", 9001))

    def test_retire_terminates_drains_and_reaps(self):
        sup, spawned, clock = self._sup(term_grace_s=10.0)
        sup.start()
        sup.add_worker(WorkerSpec("w1", 9001))
        assert sup.retire_worker("w1") is True
        assert spawned[1].terminated
        # not reaped yet (exit honored by FakeProc.terminate -> rc=-15)
        sup.tick()
        assert [s.name for s in sup.live_specs()] == ["w0"]
        assert all(w["name"] != "w1" for w in sup.snapshot())
        # retire is a completion, never a crash: no respawn ever
        clock.advance(1000.0)
        sup.tick()
        assert len(spawned) == 2
        assert sup.metrics.get("pio_fleet_retired_total").total() == 1

    def test_retire_escalates_to_kill_past_grace(self):
        sup, spawned, clock = self._sup(term_grace_s=5.0, ignore_term=True)
        sup.start()
        sup.retire_worker("w0")
        sup.tick()
        assert not spawned[0].killed
        clock.advance(6.0)
        sup.tick()  # grace expired: SIGKILL
        assert spawned[0].killed
        sup.tick()  # killed proc reaped
        assert sup.snapshot() == []

    def test_retired_worker_gauges_drop_from_exposition(self):
        """Satellite (federation/top staleness): a retired replica's
        pio_fleet_worker_up/parked series must DROP, not render as a
        live-but-down worker forever."""
        sup, spawned, clock = self._sup()
        sup.start()
        sup.add_worker(WorkerSpec("w1", 9001))
        text = sup.metrics.render_prometheus()
        assert 'pio_fleet_worker_up{replica="w1"}' in text
        sup.retire_worker("w1")
        sup.tick()
        text = sup.metrics.render_prometheus()
        assert 'pio_fleet_worker_up{replica="w1"}' not in text
        assert 'pio_fleet_worker_parked{replica="w1"}' not in text
        assert 'pio_fleet_worker_up{replica="w0"}' in text

    def test_live_specs_excludes_parked_and_retiring(self):
        sup, spawned, clock = self._sup(
            term_grace_s=1e9, ignore_term=True, crash_loop_budget=0
        )
        sup.start()
        sup.add_worker(WorkerSpec("w1", 9001))
        sup.add_worker(WorkerSpec("w2", 9002))
        sup.retire_worker("w1")  # retiring (proc ignores SIGTERM)
        spawned[2].exit(1)
        sup.tick()  # w2 over the zero crash budget: parked
        assert [s.name for s in sup.live_specs()] == ["w0"]


# ---------------------------------------------------------------------------
# gateway: dynamic membership + class-aware routing
# ---------------------------------------------------------------------------


class TestGatewayMembership:
    def test_added_replica_earns_routing_via_probe(self):
        from tests.test_fleet import FakeReplica, _gateway_rig

        replicas, run = _gateway_rig(1)
        late = FakeReplica("late")

        async def body(gw, client):
            late.ready = False  # still booting: probes must not admit it
            url = await late.start()
            added = gw.add_replica(url)
            assert added.healthy is False  # joins unrouted
            await asyncio.sleep(0.15)  # probe passes run and keep it out
            # unhealthy member: traffic keeps flowing to the old replica
            for i in range(4):
                assert (
                    await client.post(
                        "/queries.json", json={"user": f"u{i}"}
                    )
                ).status == 200
            assert late.queries == 0
            late.ready = True
            await asyncio.sleep(0.15)  # a probe pass admits it
            assert added.healthy is True
            for i in range(12):
                await client.post("/queries.json", json={"user": f"x{i}"})
            assert late.queries > 0
            await late.stop()

        run(body)

    def test_duplicate_add_raises(self):
        from tests.test_fleet import _gateway_rig

        replicas, run = _gateway_rig(1)

        async def body(gw, client):
            with pytest.raises(ValueError):
                gw.add_replica(gw.replicas[0].url)

        run(body)

    def test_retire_stops_new_routing_but_inflight_completes(self):
        """The scale-in ordering invariant: membership first, process
        second — a request already proxied to the retiring replica is
        answered, new requests never route there."""
        from tests.test_fleet import _gateway_rig

        replicas, run = _gateway_rig(2)
        for fake in replicas:
            fake.delay_s = 0.4  # every answer is slow: any pick parks

        async def body(gw, client):
            # park a slow request on some replica, then retire it mid-flight
            victim = slow = None
            for i in range(40):
                fut = asyncio.ensure_future(
                    client.post("/queries.json", json={"user": f"u{i}"})
                )
                await asyncio.sleep(0.05)
                busy = [r for r in gw.replicas if r.inflight > 0]
                if busy:
                    victim, slow = busy[0], fut
                    break
                resp = await fut
                assert resp.status == 200
            assert slow is not None, "no replica ever saw a request"
            assert gw.retire_replica(victim.name) is victim
            resp = await slow
            assert resp.status == 200  # in-flight completed, not torn down
            # new traffic all lands on the survivor
            survivor = gw.replicas[0]
            before = {r.name for r in gw.replicas}
            assert victim.name not in before and len(gw.replicas) == 1
            for i in range(10):
                resp = await client.post(
                    "/queries.json", json={"user": f"z{i}"}
                )
                assert resp.status == 200
            assert survivor.healthy

        run(body)

    def test_retired_replica_series_drop_from_metrics(self):
        from tests.test_fleet import _gateway_rig

        replicas, run = _gateway_rig(2)

        async def body(gw, client):
            for i in range(6):
                await client.post("/queries.json", json={"user": f"u{i}"})
            victim = gw.replicas[1]
            text = gw.metrics.render_prometheus()
            assert f'pio_fleet_replica_up{{replica="{victim.name}"}}' in text
            assert (
                f'pio_breaker_state{{breaker="replica:{victim.name}"}}' in text
            )
            gw.retire_replica(victim.name)
            text = gw.metrics.render_prometheus()
            assert f'replica="{victim.name}"' not in "".join(
                line
                for line in text.splitlines()
                if line.startswith(
                    ("pio_fleet_replica_up", "pio_fleet_replica_inflight")
                )
            )
            assert (
                f'pio_breaker_state{{breaker="replica:{victim.name}"}}'
                not in text
            )
            # monotonic history survives: the per-attempt counter stays
            assert f'replica="{victim.name}"' in "".join(
                line
                for line in text.splitlines()
                if line.startswith("pio_fleet_requests_total")
            )
            assert gw.metrics.get("pio_fleet_replicas").value() == 1.0

        run(body)

    def test_top_fleet_line_drops_retired_replica(self):
        """Satellite: `pio top --fleet` must not render a retired replica
        from its leftover ejection/readmission counters."""
        from predictionio_tpu.tools.top import parse_prometheus, summarize
        from tests.test_fleet import _gateway_rig

        replicas, run = _gateway_rig(2)

        async def body(gw, client):
            victim = gw.replicas[1]
            # leave a counter trace for the victim, then retire it
            gw._m_ejections.inc(replica=victim.name)
            gw._m_readmissions.inc(replica=victim.name)
            gw.retire_replica(victim.name)
            summary = summarize(parse_prometheus(gw.metrics.render_prometheus()))
            fleet = summary["fleet"]
            assert victim.name not in fleet["replicas"]
            assert fleet["replicas_total"] == 1.0

        run(body)

    def test_cpu_fallback_gets_overflow_only(self):
        from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig

        gw = Gateway(
            GatewayConfig(
                replica_urls=(
                    "http://127.0.0.1:9101",
                    "http://127.0.0.1:9102",
                ),
                replica_classes=(REPLICA_CLASS_DEVICE, REPLICA_CLASS_CPU),
                cpu_overflow_inflight=4,
            )
        )
        device, cpu = gw.replicas
        # idle fleet: every pick lands on the device replica
        for i in range(8):
            assert gw.pick_replica(f"u{i}").worker_class == REPLICA_CLASS_DEVICE
        assert gw.metrics.get("pio_fleet_overflow_picks_total").total() == 0
        # saturate the device class: picks spill to cpu-fallback and are
        # counted as overflow (degraded answer, not a shed)
        device.inflight = 4
        meta: dict = {}
        picked = gw.pick_replica("u-spill", meta=meta)
        assert picked is cpu and meta.get("overflow") is True
        assert gw.metrics.get("pio_fleet_overflow_picks_total").total() == 1
        # device headroom back: routing returns to the fast path
        device.inflight = 0
        assert gw.pick_replica("u-back").worker_class == REPLICA_CLASS_DEVICE

    def test_fleet_snapshot_is_side_effect_free_on_the_peak(self):
        """Incident captures read fleet_snapshot too: a capture mid-spike
        must not consume the inflight high-water mark out from under the
        telemetry ring (only the telemetry tick resets it)."""
        from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig

        gw = Gateway(GatewayConfig(replica_urls=("http://127.0.0.1:9121",)))
        gw._inflight_peak = 7
        assert gw.fleet_snapshot()["gauges"]["inflight_peak"] == 7.0
        # a second read (the incident capture) still sees the peak
        assert gw.fleet_snapshot()["gauges"]["inflight_peak"] == 7.0

    def test_saturated_everything_still_routes_least_loaded(self):
        from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig

        gw = Gateway(
            GatewayConfig(
                replica_urls=(
                    "http://127.0.0.1:9111",
                    "http://127.0.0.1:9112",
                ),
                replica_classes=(REPLICA_CLASS_DEVICE, REPLICA_CLASS_CPU),
                cpu_overflow_inflight=2,
            )
        )
        device, cpu = gw.replicas
        device.inflight = 3
        cpu.inflight = 7
        assert gw.pick_replica("u") is device  # queueing beats shedding


# ---------------------------------------------------------------------------
# the control loop: ring -> policy -> supervisor + gateway
# ---------------------------------------------------------------------------


class FakeRing:
    def __init__(self):
        self.records_list: list[dict] = []

    def append(self, record: dict) -> int:
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec["seq"] = len(self.records_list)
        self.records_list.append(rec)
        return rec["seq"]

    def window(self, seconds: float) -> list[dict]:
        cutoff = time.time() - seconds
        return [r for r in self.records_list if r["t"] >= cutoff]

    def records(self) -> list[dict]:
        return list(self.records_list)


class FakeIncidents:
    def __init__(self):
        self.triggers: list[tuple[str, dict]] = []

    def trigger(self, kind, context=None, texts=None):
        self.triggers.append((kind, context or {}))
        return "/fake/bundle"


def _autoscaler_rig(n_fake_replicas: int = 4, **policy_kw):
    """Real Supervisor (fake procs) + real Gateway (fake replica servers)
    + FakeRing; yields (autoscaler, ring, incidents, gw, sup, run)."""
    from tests.test_fleet import FakeProc, FakeReplica

    policy_kw.setdefault("min_replicas", 1)
    policy_kw.setdefault("max_replicas", 3)
    policy_kw.setdefault("confirm_s", 10.0)
    policy_kw.setdefault("idle_sustain_s", 20.0)
    policy_kw.setdefault("scale_out_cooldown_s", 0.0)
    policy_kw.setdefault("scale_in_cooldown_s", 0.0)
    fakes = [FakeReplica(f"f{i}") for i in range(n_fake_replicas)]

    async def start(body):
        from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig

        urls = [await f.start() for f in fakes]
        metrics = MetricsRegistry()
        spawned: list = []

        def spawn(spec):
            p = FakeProc()
            spawned.append(p)
            return p

        sup = Supervisor(
            spawn, [WorkerSpec("w0", 9000)], SupervisorConfig(), metrics=metrics
        )
        gw = Gateway(
            GatewayConfig(
                replica_urls=(urls[0],), probe_interval_s=0.05
            ),
            metrics=metrics,
        )
        slot = [1]

        def spec_factory(worker_class: str) -> WorkerSpec:
            i = slot[0]
            slot[0] += 1
            from urllib.parse import urlsplit

            port = int(urlsplit(urls[i]).port)
            return WorkerSpec(f"w{i}", port, worker_class)

        ring = FakeRing()
        incidents = FakeIncidents()
        auto = Autoscaler(
            ScalingPolicy(AutoscalerConfig(**policy_kw)),
            sup,
            gw,
            spec_factory,
            ring=ring,
            metrics=metrics,
            incidents=incidents,
        )
        sup.start()
        try:
            await body(auto, ring, incidents, gw, sup)
        finally:
            for f in fakes:
                await f.stop()

    def run(body):
        asyncio.run(start(body))

    return run


class TestAutoscalerLoop:
    def _pressure(self, ring: FakeRing, n: int = 3):
        now = time.time()
        for i in range(n):
            ring.append(_rec(now - (n - i), burn=3.0))

    def test_tick_scale_out_goes_through_both_funnels(self):
        run = _autoscaler_rig()

        async def body(auto, ring, incidents, gw, sup):
            self._pressure(ring)
            decision = auto.tick()
            assert decision.action == SCALE_OUT
            assert [s.name for s in sup.live_specs()] == ["w0", "w1"]
            assert len(gw.replicas) == 2  # joined (unhealthy until probed)
            scaling = [r for r in ring.records() if r.get("kind") == "scaling"]
            assert scaling and scaling[-1]["decision"]["action"] == SCALE_OUT
            assert scaling[-1]["shape"]["device"] == 2
            m = auto.metrics.get("pio_autoscaler_scale_outs_total")
            assert m.value(worker_class=REPLICA_CLASS_DEVICE) == 1

        run(body)

    def test_scale_in_retires_gateway_before_supervisor(self):
        run = _autoscaler_rig()

        async def body(auto, ring, incidents, gw, sup):
            self._pressure(ring)
            auto.tick()  # out to 2
            order: list[str] = []
            orig_retire_replica = gw.retire_replica
            orig_retire_worker = sup.retire_worker

            def spy_gw(url):
                order.append("gateway")
                return orig_retire_replica(url)

            def spy_sup(name):
                order.append("supervisor")
                return orig_retire_worker(name)

            gw.retire_replica = spy_gw
            sup.retire_worker = spy_sup
            now = time.time()
            ring.records_list.clear()
            for i in range(5):
                ring.append(_rec(now - 20 + i * 5, healthy=2))
            decision = auto.tick()
            assert decision.action == SCALE_IN
            assert order == ["gateway", "supervisor"]
            sup.tick()  # reap the drained worker
            assert [s.name for s in sup.live_specs()] == ["w0"]
            assert len(gw.replicas) == 1

        run(body)

    def test_saturation_fires_incident_once_per_episode(self):
        run = _autoscaler_rig(max_replicas=1)

        async def body(auto, ring, incidents, gw, sup):
            self._pressure(ring)
            auto.tick()
            assert [k for k, _ in incidents.triggers] == ["autoscaler-saturated"]
            self._pressure(ring)
            auto.tick()  # still saturated: same episode, no second bundle
            assert len(incidents.triggers) == 1
            assert auto.metrics.get("pio_autoscaler_saturated_total").total() == 2
            # pressure clears, then returns: a NEW episode captures again
            ring.records_list.clear()
            now = time.time()
            for i in range(3):
                ring.append(_rec(now - 3 + i))
            auto.tick()
            ring.records_list.clear()  # stale idle records out of the window
            self._pressure(ring)
            auto.tick()
            assert len(incidents.triggers) == 2

        run(body)

    def test_mid_bake_defers_and_counts(self):
        rollout = {"active": True}
        run = _autoscaler_rig()

        async def body(auto, ring, incidents, gw, sup):
            auto._rollout_probe = lambda: rollout["active"]
            self._pressure(ring)
            decision = auto.tick()
            assert decision.action == DEFER
            assert auto.metrics.get("pio_autoscaler_deferred_total").total() == 1
            assert len(sup.live_specs()) == 1  # nothing resized
            scaling = [r for r in ring.records() if r.get("kind") == "scaling"]
            assert scaling[-1]["decision"]["action"] == DEFER
            # bake ends: the deferred resize fires on the next tick even
            # though the pressure records have gone stale
            rollout["active"] = False
            ring.records_list[:] = [
                r for r in ring.records_list if r.get("kind") == "scaling"
            ]
            decision = auto.tick()
            assert decision.action == SCALE_OUT and decision.deferred
            assert len(sup.live_specs()) == 2

        run(body)

    def test_rollout_probe_reads_registry_state(self, tmp_path):
        from predictionio_tpu.registry import ArtifactStore, ModelManifest

        store = ArtifactStore(str(tmp_path))
        for blob in (b"one", b"two"):
            store.publish(
                ModelManifest(
                    version="",
                    engine_id="e",
                    engine_version="1",
                    engine_variant="v",
                ),
                blob,
            )
        probe = registry_rollout_probe(str(tmp_path))
        assert probe() is False
        versions = sorted(m.version for m in store.list_versions("e"))
        store.stage_candidate("e", versions[-1], fraction=0.2)
        assert probe() is True  # mid-bake
        store.promote("e")
        assert probe() is False  # bake over: deferred resizes may fire

    def test_rollout_probe_is_the_shared_registry_helper(self):
        """ISSUE 19 satellite: the probe moved to registry/probe.py so the
        autoscaler and the lifecycle controller share ONE definition of
        'a rollout is baking'. The autoscaler import path must keep
        resolving to the same function (existing importers + the fleet
        launcher), not a diverged copy."""
        from predictionio_tpu.fleet.autoscaler import (
            registry_rollout_probe as via_autoscaler,
        )
        from predictionio_tpu.registry import (
            registry_rollout_probe as via_registry,
        )
        from predictionio_tpu.registry.probe import (
            registry_rollout_probe as canonical,
        )

        assert via_autoscaler is via_registry is canonical

    def test_autoscaler_shape_metric_tracks_classes(self):
        run = _autoscaler_rig(max_replicas=1, cpu_fallback_max=2)

        async def body(auto, ring, incidents, gw, sup):
            self._pressure(ring)
            decision = auto.tick()  # device at max: cpu-fallback spill
            assert decision.replica_class == REPLICA_CLASS_CPU
            auto.metrics._run_collectors()
            m = auto.metrics.get("pio_autoscaler_replicas")
            assert m.value(worker_class=REPLICA_CLASS_DEVICE) == 1.0
            assert m.value(worker_class=REPLICA_CLASS_CPU) == 1.0
            assert gw.replicas[-1].worker_class == REPLICA_CLASS_CPU

        run(body)


class TestBuildAutoscalerValidation:
    def _args(self, **kw):
        import types

        base = dict(
            fleet=2,
            fleet_min=None,
            fleet_max=None,
            cpu_fallback_max=None,
            autoscale_interval=None,
            registry_dir=None,
        )
        base.update(kw)
        return types.SimpleNamespace(**base)

    def _build(self, args):
        from tests.test_fleet import FakeProc

        from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig
        from predictionio_tpu.fleet.launch import build_autoscaler

        metrics = MetricsRegistry()
        sup = Supervisor(
            lambda spec: FakeProc(),
            [WorkerSpec("w0", 9000)],
            SupervisorConfig(),
            metrics=metrics,
        )
        gw = Gateway(
            GatewayConfig(replica_urls=("http://127.0.0.1:9000",)),
            metrics=MetricsRegistry(),
        )
        ring = FakeRing()
        return build_autoscaler(
            args, sup, gw, lambda cls: WorkerSpec("w9", 9009, cls), ring,
            metrics, {},
        )

    def test_defaults_give_boot_size_headroom(self):
        auto = self._build(self._args(fleet=3))
        assert auto.policy.config.max_replicas == 6  # 2x boot size
        assert auto.policy.config.min_replicas == 1

    def test_explicit_zero_is_rejected_not_silently_defaulted(self):
        with pytest.raises(ValueError):
            self._build(self._args(fleet_min=0))
        with pytest.raises(ValueError):
            self._build(self._args(autoscale_interval=0))

    def test_fleet_max_below_boot_size_rejected(self):
        """Booting above the ceiling would pin every pressured tick on
        'saturated' while the operator believes the envelope binds."""
        with pytest.raises(ValueError):
            self._build(self._args(fleet=4, fleet_max=2))

    def test_min_above_max_rejected(self):
        with pytest.raises(ValueError):
            self._build(self._args(fleet_min=5, fleet_max=3))


class TestWorkerArgvElasticity:
    def test_autoscale_flags_never_leak_into_worker_argv(self):
        """A worker recursively autoscaling would be a fork bomb: every
        elasticity flag is parent-only."""
        from predictionio_tpu.fleet.launch import worker_argv

        argv = [
            "deploy",
            "--engine-dir", "eng",
            "--fleet", "2",
            "--autoscale",
            "--fleet-min", "1",
            "--fleet-max=4",
            "--cpu-fallback-max", "2",
            "--autoscale-interval", "0.5",
            "--port", "8000",
        ]
        out = worker_argv(argv, 8003, 1.0)
        for flag in (
            "--autoscale",
            "--fleet-min",
            "--fleet-max",
            "--cpu-fallback-max",
            "--autoscale-interval",
        ):
            assert not any(a.startswith(flag) for a in out), (flag, out)
        assert "--engine-dir" in out and "eng" in out
        assert out[out.index("--port") + 1] == "8003"


# ---------------------------------------------------------------------------
# pio top: the autoscaler line + history scaling markers
# ---------------------------------------------------------------------------


class TestTopAutoscaler:
    def _metrics_text(self) -> str:
        from tests.test_fleet import FakeProc

        m = MetricsRegistry()
        from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig

        sup = Supervisor(
            lambda spec: FakeProc(),
            [WorkerSpec("w0", 9000)],
            SupervisorConfig(),
            metrics=m,
        )
        gw = Gateway(
            GatewayConfig(replica_urls=("http://127.0.0.1:9000",)), metrics=m
        )
        auto = Autoscaler(
            ScalingPolicy(
                AutoscalerConfig(min_replicas=1, max_replicas=4, cpu_fallback_max=2)
            ),
            sup,
            gw,
            lambda cls: WorkerSpec("w9", 9009, cls),
            metrics=m,
        )
        sup.start()
        auto._m_outs.inc(worker_class=REPLICA_CLASS_DEVICE)
        auto._m_deferred.inc()
        return m.render_prometheus()

    def test_summary_and_render_carry_autoscaler_line(self):
        from predictionio_tpu.tools.top import parse_prometheus, render, summarize

        summary = summarize(parse_prometheus(self._metrics_text()))
        scaler = summary["autoscaler"]
        assert scaler["max_replicas"] == 4.0
        assert scaler["cpu_fallback_max"] == 2.0
        assert scaler["scale_outs_total"] == 1.0
        assert scaler["deferred_total"] == 1.0
        screen = render(summary, "http://gw")
        assert "autoscaler" in screen
        assert "[1..4]" in screen
        assert "deferred 1" in screen

    def test_summary_none_without_autoscaler(self):
        from predictionio_tpu.tools.top import parse_prometheus, summarize

        summary = summarize(parse_prometheus("pio_requests_total 1\n"))
        assert summary["autoscaler"] is None

    def test_history_renders_scaling_markers(self):
        from predictionio_tpu.tools.top import render_history

        now = time.time()
        records = [
            _rec(now - 30, qd=4.0),
            {
                "kind": "scaling",
                "t": now - 20,
                "decision": {
                    "action": "scale-out",
                    "reason": "burn",
                    "class": "device",
                },
                "shape": {"device": 2, "cpu": 0},
            },
            _rec(now - 10, qd=0.0, healthy=2),
        ]
        screen = render_history(records, 60.0)
        assert "scaling    1 decision(s)" in screen
        assert "scale-out device (burn) -> device 2" in screen
        # the scaling record must NOT pollute the snapshot series
        assert "2 snapshots" in screen


# ---------------------------------------------------------------------------
# e2e: spike trace against a real 1->3->1 fleet (the chaos stage)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestElasticFleetE2E:
    """Real worker processes (scripts/fleet_smoke.py --worker), real
    gateway, real telemetry ring, real autoscaler. A flood drives the
    fleet 1->3 (zero 5xx throughout), pressure at the envelope snapshots
    an autoscaler-saturated incident bundle, then idle drains it back to
    1 via SIGTERM (zero 5xx during the drain too). Scaling decisions
    must land in the on-disk ring."""

    def test_spike_scale_out_saturate_and_drain_in(self, tmp_path):
        import aiohttp  # noqa: F401 - fail fast if the env lacks it

        from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig
        from predictionio_tpu.fleet.launch import build_obs_plane
        from predictionio_tpu.fleet.worklog import spawn_with_log
        from predictionio_tpu.obs.incidents import list_bundles
        from tests.test_fleet import TestKillMidRolloutE2E  # noqa: F401

        import socket

        def free_port() -> int:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        ports = [free_port() for _ in range(6)]
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        obs_dir = str(tmp_path / "obs")
        metrics = MetricsRegistry()
        obs = build_obs_plane(obs_dir, metrics)
        worker_script = os.path.join(REPO, "scripts", "fleet_smoke.py")

        def spawn(spec):
            return spawn_with_log(
                [sys.executable, worker_script, "--worker", str(spec.port)],
                obs["logbook"],
                spec.name,
                env=env,
                cwd=REPO,
            )

        sup = Supervisor(
            spawn,
            [WorkerSpec("w0", ports[0])],
            SupervisorConfig(poll_interval_s=0.1, term_grace_s=10.0),
            metrics=metrics,
            logbook=obs["logbook"],
            on_crash=obs["on_crash"],
        )
        gw = Gateway(
            GatewayConfig(
                ip="127.0.0.1",
                port=free_port(),
                replica_urls=(WorkerSpec("w0", ports[0]).url,),
                probe_interval_s=0.2,
                probe_timeout_s=2.0,
                request_timeout_s=15.0,
                telemetry_interval_s=0.25,
                slo_windows=((10.0, 10.0), (30.0, 5.0)),
            ),
            metrics=metrics,
            telemetry=obs["telemetry"],
            incidents=obs["incidents"],
        )
        slot = [1]

        def spec_factory(worker_class: str) -> WorkerSpec:
            i = slot[0]
            slot[0] += 1
            return WorkerSpec(f"w{i}", ports[i], worker_class)

        auto = Autoscaler(
            ScalingPolicy(
                AutoscalerConfig(
                    min_replicas=1,
                    max_replicas=3,
                    tick_interval_s=0.5,
                    burn_threshold=1.0,
                    queue_depth_high=2.0,
                    inflight_high_per_replica=6.0,
                    confirm_s=2.0,
                    idle_sustain_s=5.0,
                    queue_depth_low=1.0,
                    idle_inflight_per_replica=2.0,
                    idle_burn_max=0.5,
                    scale_out_cooldown_s=4.0,
                    scale_in_cooldown_s=6.0,
                )
            ),
            sup,
            gw,
            spec_factory,
            ring=obs["telemetry"],
            metrics=metrics,
            incidents=obs["incidents"],
        )
        results: dict = {"statuses": [], "errors": []}
        try:
            asyncio.run(self._drive(sup, gw, auto, results))
        finally:
            sup.stop()
            obs["telemetry"].close()
        fivexx = [s for s in results["statuses"] if s >= 500]
        assert fivexx == [], (
            f"{len(fivexx)} client-visible 5xx during elasticity "
            f"(of {len(results['statuses'])})"
        )
        assert results["errors"] == []
        assert results["peak_replicas"] == 3, results
        assert results["steady_replicas"] == 1, results
        # scaling decisions are telemetry: both directions in the ring
        from predictionio_tpu.obs.tsring import TelemetryRing

        ring = TelemetryRing(os.path.join(obs_dir, "telemetry"))
        actions = [
            r["decision"]["action"]
            for r in ring.records()
            if r.get("kind") == "scaling"
        ]
        assert SCALE_OUT in actions and SCALE_IN in actions, actions
        # envelope saturation left an incident bundle
        triggers = [
            r.trigger
            for r in list_bundles(os.path.join(obs_dir, "incidents"))
        ]
        assert "autoscaler-saturated" in triggers, triggers
        # retired workers' gauges dropped from the exposition
        text = metrics.render_prometheus()
        for line in text.splitlines():
            if line.startswith(("pio_fleet_worker_up{", "pio_fleet_replica_up{")):
                assert 'replica="w0"' in line or ":%d" % ports[0] in line, line

    async def _drive(self, sup, gw, auto, results) -> None:
        import aiohttp

        sup.start()
        sup_task = asyncio.ensure_future(sup.run())
        auto_task = asyncio.ensure_future(auto.run())
        await gw.start()
        gw_url = f"http://127.0.0.1:{gw.config.port}"
        session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=20)
        )

        async def query(i: int) -> None:
            try:
                async with session.post(
                    f"{gw_url}/queries.json",
                    json={"user": f"u{i % 200}", "num": 5},
                ) as resp:
                    await resp.read()
                    results["statuses"].append(resp.status)
            except Exception as exc:
                results["errors"].append(repr(exc))

        async def flood_until(stop: asyncio.Event, concurrency: int):
            counter = [0]

            async def loop():
                while not stop.is_set():
                    counter[0] += 1
                    await query(counter[0])

            await asyncio.gather(*(loop() for _ in range(concurrency)))

        async def trickle(duration_s: float):
            stop_at = time.monotonic() + duration_s
            i = 0
            while time.monotonic() < stop_at:
                i += 1
                await query(i)
                await asyncio.sleep(0.25)

        try:
            # worker 0 ready (pays the jax import)
            deadline = time.monotonic() + 120.0
            while True:
                try:
                    async with session.get(f"{gw_url}/healthz") as resp:
                        if (await resp.json()).get("replicasHealthy", 0) >= 1:
                            break
                except Exception:
                    pass
                assert time.monotonic() < deadline, "w0 never ready"
                await asyncio.sleep(0.25)
            # flood CONTINUOUSLY until the fleet reaches the envelope
            # (scale-out under load, zero 5xx) and pressure at the
            # envelope records a saturation episode — a bursty load
            # would tear the policy's confirm window between bursts.
            # 24-way closed loop: 24/3 replicas = 8 in flight each,
            # above the policy's threshold even at the envelope, so the
            # saturation episode is reachable, not racy
            stop_flood = asyncio.Event()
            flood_task = asyncio.ensure_future(flood_until(stop_flood, 24))
            deadline = time.monotonic() + 90.0
            peak = 1
            try:
                while time.monotonic() < deadline:
                    peak = max(peak, len(sup.live_specs()))
                    if peak >= 3 and auto.metrics.get(
                        "pio_autoscaler_saturated_total"
                    ).total():
                        break
                    await asyncio.sleep(0.5)
            finally:
                stop_flood.set()
                await asyncio.gather(flood_task, return_exceptions=True)
            results["peak_replicas"] = peak
            results["saturated"] = auto.metrics.get(
                "pio_autoscaler_saturated_total"
            ).total()
            # decay: light load while the idle detector drains the fleet
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                await trickle(2.0)
                if len(sup.live_specs()) == 1 and len(sup.snapshot()) == 1:
                    break
            results["steady_replicas"] = len(sup.live_specs())
        finally:
            for t in (auto_task, sup_task):
                t.cancel()
            await asyncio.gather(auto_task, sup_task, return_exceptions=True)
            await session.close()
            await gw.stop()
