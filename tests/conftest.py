"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md section 4): Spark
``master=local[*]`` becomes ``JAX_PLATFORMS=cpu`` with
``--xla_force_host_platform_device_count=8`` so mesh/sharding logic is
exercised without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: env may point at a TPU tunnel
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# A site-installed TPU-tunnel PJRT plugin (sitecustomize) may have imported
# jax already (so the env vars above are stale) and registered a backend
# whose device init can block even when the platform is cpu. Re-point the
# live jax config at cpu and drop non-CPU backend factories.
try:
    import jax as _jax
    from jax._src import xla_bridge as _xb

    _jax.config.update("jax_platforms", "cpu")
    # Drop only non-standard plugin platforms (e.g. the axon tunnel): their
    # device init can block, but standard names must stay registered because
    # libraries register per-platform lowerings for them at import time.
    _standard = {"cpu", "gpu", "cuda", "rocm", "tpu", "METAL"}
    for _name in [n for n in _xb._backend_factories if n not in _standard]:
        _xb._backend_factories.pop(_name, None)
except Exception:
    pass

import atexit  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

# Template DataSources read through the snapshot cache by default; tests
# must never write shards into the developer's ~/.pio_store
_snap_dir = tempfile.mkdtemp(prefix="pio_test_snapshots_")
os.environ["PIO_SNAPSHOT_DIR"] = _snap_dir
atexit.register(shutil.rmtree, _snap_dir, ignore_errors=True)

import pytest  # noqa: E402

from predictionio_tpu.data.storage.memory import MemoryStorageClient  # noqa: E402
from predictionio_tpu.data.storage.registry import Storage  # noqa: E402


@pytest.fixture
def memory_storage(monkeypatch):
    """An isolated Storage wired entirely to the in-memory backend."""
    storage = Storage(
        env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )
    monkeypatch.setattr(Storage, "_singleton", storage)
    return storage


@pytest.fixture
def sqlite_storage(tmp_path, monkeypatch):
    """An isolated Storage on a throwaway SQLite file."""
    storage = Storage(
        env={
            "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "pio.db"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
        }
    )
    monkeypatch.setattr(Storage, "_singleton", storage)
    return storage
