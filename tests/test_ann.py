"""ANN subsystem tests (predictionio_tpu/ann, docs/ann.md).

Four layers, matching the lifecycle: index build/serialization mechanics
(determinism, padded-bucket edge cases, int8), the measured recall
harness (recall@10 vs exact ACROSS nprobe settings — measured, never
asserted blind), registry lifecycle (attach/verify/GC, refresh vs
drift-rebuild, the stream refresh -> candidate -> promote e2e), and the
serving integration (twotower + similarproduct dispatch through a pinned
index, filters, fallback, recall shadow sampling, metrics/doctor/top).
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from predictionio_tpu.ann import (
    AnnConfig,
    build_index,
    default_clusters,
    default_nprobe,
    deserialize_index,
    refresh_index,
    serialize_index,
)
from predictionio_tpu.ann import lifecycle
from predictionio_tpu.ann.index import AnnFormatError, bucket_capacity
from predictionio_tpu.ann.metrics import AnnInstruments
from predictionio_tpu.ann.search import AnnSearcher
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.registry import ArtifactStore, ModelManifest
from predictionio_tpu.registry.store import ArtifactIntegrityError
from predictionio_tpu.workflow import model_io


def clustered_corpus(n, f, modes=32, noise=0.1, seed=0):
    """Synthetic item table with real cluster structure (normalized rows
    — the shape trained retrieval embeddings have)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(modes, f))
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    vecs = centers[rng.integers(0, modes, n)] + noise * rng.normal(size=(n, f))
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    return vecs.astype(np.float32)


def exact_topk(vecs, q, k):
    return np.argsort(-(q @ vecs.T), axis=1, kind="stable")[:, :k]


def measured_recall(items, exact_idx, k):
    rows = len(exact_idx)
    hits = sum(
        len(set(map(int, items[r, :k])) & set(map(int, exact_idx[r, :k])))
        for r in range(rows)
    )
    return hits / float(rows * k)


# ---------------------------------------------------------------------------
# build mechanics
# ---------------------------------------------------------------------------


class TestBuild:
    def test_deterministic_bytes(self):
        vecs = clustered_corpus(2000, 8)
        cfg = AnnConfig(min_items=0)
        a = serialize_index(build_index(vecs, cfg, model_version="v1"))
        b = serialize_index(build_index(vecs, cfg, model_version="v1"))
        # content addressing in the registry dedupes identical rebuilds
        assert a == b

    def test_serialization_roundtrip(self):
        vecs = clustered_corpus(1500, 8)
        idx = build_index(vecs, AnnConfig(min_items=0), model_version="v7")
        rt = deserialize_index(serialize_index(idx))
        assert rt.model_version == "v7"
        assert rt.n_items == idx.n_items and rt.nprobe == idx.nprobe
        np.testing.assert_array_equal(rt.centroids, idx.centroids)
        np.testing.assert_array_equal(rt.bucket_ids, idx.bucket_ids)
        np.testing.assert_array_equal(rt.bucket_vecs, idx.bucket_vecs)
        np.testing.assert_array_equal(rt.nearest_assign, idx.nearest_assign)
        assert rt.config == idx.config

    def test_corrupt_blob_raises_format_error(self):
        idx = build_index(clustered_corpus(300, 4), AnnConfig(min_items=0))
        blob = serialize_index(idx)
        with pytest.raises(AnnFormatError):
            deserialize_index(b"NOTANINDEX" + blob)
        with pytest.raises(AnnFormatError):
            deserialize_index(blob[: len(blob) // 2])  # truncated arrays

    def test_every_item_in_exactly_one_bucket(self):
        vecs = clustered_corpus(3000, 8)
        idx = build_index(vecs, AnnConfig(min_items=0))
        ids = idx.bucket_ids[idx.bucket_ids >= 0]
        assert sorted(ids.tolist()) == list(range(3000))

    def test_skewed_corpus_spills_instead_of_inflating_cap(self):
        # everything in ONE natural cluster: the fattest-cluster rule
        # would pad every bucket to ~n; the balanced rule must hold the
        # 2x-mean capacity and spill
        rng = np.random.default_rng(3)
        vecs = (
            np.ones((2048, 8), np.float32)
            + 0.001 * rng.normal(size=(2048, 8)).astype(np.float32)
        )
        idx = build_index(vecs, AnnConfig(min_items=0, clusters=64))
        assert idx.bucket_cap == bucket_capacity(2048, 64)
        ids = idx.bucket_ids[idx.bucket_ids >= 0]
        assert sorted(ids.tolist()) == list(range(2048))  # nothing lost
        per_bucket = (idx.bucket_ids >= 0).sum(axis=1)
        assert per_bucket.max() <= idx.bucket_cap

    def test_fewer_items_than_clusters(self):
        vecs = clustered_corpus(10, 4)
        idx = build_index(vecs, AnnConfig(min_items=0, clusters=64))
        assert idx.clusters == 10  # clamped to the corpus
        ids = idx.bucket_ids[idx.bucket_ids >= 0]
        assert sorted(ids.tolist()) == list(range(10))

    def test_single_cluster(self):
        vecs = clustered_corpus(40, 4)
        idx = build_index(vecs, AnnConfig(min_items=0, clusters=1, nprobe=1))
        s = AnnSearcher(idx)
        _, items, counts = AnnSearcher.fetch(s.search_async(vecs[:4].copy(), 5))
        assert measured_recall(items, exact_topk(vecs, vecs[:4], 5), 5) == 1.0
        assert (counts == 40).all()  # one bucket = the whole corpus

    def test_int8_quantization_layout(self):
        vecs = clustered_corpus(500, 8)
        idx = build_index(vecs, AnnConfig(min_items=0, quantize_int8=True))
        assert idx.quantized and idx.bucket_vecs.dtype == np.int8
        pads = idx.bucket_ids < 0
        assert (idx.bucket_scale[pads] == 0).all()
        assert (idx.bucket_vecs[pads] == 0).all()
        # dequantized real rows approximate the originals
        real = ~pads
        deq = idx.bucket_vecs[real].astype(np.float32) * idx.bucket_scale[
            real
        ][:, None]
        orig = vecs[idx.bucket_ids[real]]
        assert float(np.abs(deq - orig).max()) < 0.02

    def test_hbm_bytes_counts_every_resident_array(self):
        idx = build_index(
            clustered_corpus(500, 8), AnnConfig(min_items=0, quantize_int8=True)
        )
        expected = (
            idx.centroids.nbytes
            + idx.bucket_ids.nbytes
            + idx.bucket_vecs.nbytes
            + idx.bucket_scale.nbytes
        )
        assert idx.hbm_bytes() == expected

    def test_default_sizing_rules(self):
        assert default_clusters(100_000) == 2048
        assert default_nprobe(2048) == 16
        assert default_nprobe(8192) == 64
        assert default_nprobe(8) == 8  # floor clamped to cluster count
        cfg = AnnConfig().resolved(100_000)
        assert cfg.clusters == 2048 and cfg.nprobe == 16


# ---------------------------------------------------------------------------
# recall harness — measured across nprobe settings
# ---------------------------------------------------------------------------


class TestRecallHarness:
    N, F, K = 6000, 16, 10

    @pytest.fixture(scope="class")
    def corpus(self):
        vecs = clustered_corpus(self.N, self.F, modes=32, seed=1)
        rng = np.random.default_rng(2)
        q = vecs[rng.integers(0, self.N, 64)].copy()
        return vecs, q, exact_topk(vecs, q, self.K)

    def test_recall_curve_across_nprobe(self, corpus):
        """The tradeoff is MEASURED: recall grows with nprobe, clears
        0.95 at the default, and the real candidate count stays <=10% of
        the corpus — the acceptance rails, held by measurement."""
        vecs, q, exact = corpus
        curve = {}
        fracs = {}
        for nprobe in (2, 8, 16):
            idx = build_index(
                vecs, AnnConfig(min_items=0, clusters=512, nprobe=nprobe)
            )
            _, items, counts = AnnSearcher.fetch(
                AnnSearcher(idx).search_async(q.copy(), self.K)
            )
            curve[nprobe] = measured_recall(items, exact, self.K)
            fracs[nprobe] = float(counts.mean()) / self.N
        assert curve[2] <= curve[8] + 0.02 <= curve[16] + 0.04
        assert curve[16] >= 0.95, f"measured recall curve: {curve}"
        assert fracs[16] <= 0.10, f"candidate fraction: {fracs}"

    def test_default_config_meets_acceptance(self, corpus):
        vecs, q, exact = corpus
        idx = build_index(vecs, AnnConfig(min_items=0))
        _, items, counts = AnnSearcher.fetch(
            AnnSearcher(idx).search_async(q.copy(), self.K)
        )
        assert measured_recall(items, exact, self.K) >= 0.95
        assert float(counts.mean()) / self.N <= 0.10

    def test_int8_rescore_recall(self, corpus):
        import jax.numpy as jnp

        vecs, q, exact = corpus
        idx = build_index(vecs, AnnConfig(min_items=0, quantize_int8=True))
        s = AnnSearcher(idx, exact_table=jnp.asarray(vecs))
        _, items, _ = AnnSearcher.fetch(s.search_async(q.copy(), self.K))
        assert measured_recall(items, exact, self.K) >= 0.95

    def test_masked_search_never_returns_masked_items(self, corpus):
        vecs, q, _ = corpus
        mask = np.ones((len(q), self.N), bool)
        mask[:, : self.N // 2] = False
        scores, items, _ = AnnSearcher.fetch(
            AnnSearcher(build_index(vecs, AnnConfig(min_items=0))).search_async(
                q.copy(), self.K, mask=mask
            )
        )
        finite = np.isfinite(scores)
        assert finite.any()
        assert (items[finite] >= self.N // 2).all()

    def test_int8_exclusion_works_and_filters(self, corpus):
        """Exclusion compares ids, never vectors — the int8 path must
        honor it (the similarproduct filter-less dispatch always sends
        its query items as exclusions)."""
        import jax.numpy as jnp

        vecs, _, _ = corpus
        idx = build_index(vecs, AnnConfig(min_items=0, quantize_int8=True))
        s = AnnSearcher(idx, exact_table=jnp.asarray(vecs))
        rng = np.random.default_rng(11)
        qi = rng.integers(0, self.N, 8)
        excl = np.full((8, 2), -1, np.int32)
        excl[:, 0] = qi
        scores, items, _ = AnnSearcher.fetch(
            s.search_async(vecs[qi].copy(), self.K, exclude=excl)
        )
        assert not any(int(qi[r]) in set(items[r].tolist()) for r in range(8))
        # mask stays the exact fallback's job on int8
        with pytest.raises(ValueError):
            s.search_async(
                vecs[qi].copy(), self.K, mask=np.ones((8, self.N), bool)
            )

    def test_exclusion_never_returns_excluded_ids(self, corpus):
        vecs, _, _ = corpus
        rng = np.random.default_rng(5)
        qi = rng.integers(0, self.N, 16)
        excl = np.full((16, 2), -1, np.int32)
        excl[:, 0] = qi
        _, items, _ = AnnSearcher.fetch(
            AnnSearcher(build_index(vecs, AnnConfig(min_items=0))).search_async(
                vecs[qi].copy(), self.K, exclude=excl
            )
        )
        assert not any(int(qi[r]) in set(items[r].tolist()) for r in range(16))

    def test_counts_measure_real_candidates_not_padding(self, corpus):
        vecs, q, _ = corpus
        idx = build_index(vecs, AnnConfig(min_items=0, clusters=256, nprobe=4))
        _, _, counts = AnnSearcher.fetch(
            AnnSearcher(idx).search_async(q.copy(), self.K)
        )
        assert (counts <= 4 * idx.bucket_cap).all()
        assert (counts > 0).all()

    def test_supports_bounds_k_by_probe_pool(self, corpus):
        vecs, _, _ = corpus
        idx = build_index(vecs, AnnConfig(min_items=0, clusters=256, nprobe=2))
        s = AnnSearcher(idx)
        assert s.supports(10)
        assert not s.supports(2 * idx.bucket_cap + 1)

    def test_device_array_query_composes_without_host_roundtrip(self, corpus):
        import jax.numpy as jnp

        vecs, q, exact = corpus
        idx = build_index(vecs, AnnConfig(min_items=0))
        _, items, _ = AnnSearcher.fetch(
            AnnSearcher(idx).search_async(jnp.asarray(q), self.K)
        )
        assert measured_recall(items, exact, self.K) >= 0.95


# ---------------------------------------------------------------------------
# refresh / rebuild
# ---------------------------------------------------------------------------


class TestRefresh:
    def test_incremental_refresh_covers_new_items(self):
        vecs = clustered_corpus(2000, 8, seed=4)
        idx = build_index(vecs, AnnConfig(min_items=0), model_version="v1")
        grown = np.vstack([vecs, clustered_corpus(200, 8, seed=9)]).astype(
            np.float32
        )
        new, report = refresh_index(idx, grown, model_version="v2")
        assert report["path"] == "refresh"
        assert new.built_from == "refresh" and new.model_version == "v2"
        assert new.n_items == 2200
        ids = new.bucket_ids[new.bucket_ids >= 0]
        assert sorted(ids.tolist()) == list(range(2200))
        np.testing.assert_array_equal(new.centroids, idx.centroids)  # no k-means

    def test_drift_guard_triggers_full_rebuild(self):
        vecs = clustered_corpus(2000, 8, seed=4)
        idx = build_index(vecs, AnnConfig(min_items=0), model_version="v1")
        shifted = clustered_corpus(2000, 8, seed=77)  # unrelated geometry
        new, report = refresh_index(idx, shifted, model_version="v2")
        assert report["path"] == "rebuild" and report["reason"] == "drift-guard"
        assert report["drift"] > idx.config.refresh_drift
        assert new.built_from == "rebuild"

    def test_dim_change_forces_rebuild(self):
        idx = build_index(clustered_corpus(1000, 8), AnnConfig(min_items=0))
        new, report = refresh_index(idx, clustered_corpus(1000, 16))
        assert report["reason"] == "dim-changed" and new.dim == 16


# ---------------------------------------------------------------------------
# registry lifecycle
# ---------------------------------------------------------------------------


def _publish_similar_model(store, engine_id, vecs):
    from predictionio_tpu.models.similarproduct.engine import SimilarModel

    model = SimilarModel(
        vecs.copy(), [f"i{j}" for j in range(len(vecs))], [None] * len(vecs)
    )
    manifest = store.publish(
        ModelManifest(
            version="",
            engine_id=engine_id,
            engine_version="1",
            engine_variant="engine.json",
        ),
        model_io.serialize_models([model]),
    )
    return manifest, model


class TestRegistryLifecycle:
    def test_build_for_version_respects_min_items(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        vecs = clustered_corpus(300, 8)
        m, model = _publish_similar_model(store, "eng", vecs)
        assert (
            lifecycle.build_for_version(
                store, "eng", m.version, [model], AnnConfig(min_items=1000)
            )
            is None
        )
        assert not store.get_manifest("eng", m.version).ann_index
        meta = lifecycle.build_for_version(
            store, "eng", m.version, [model], AnnConfig(min_items=1000), force=True
        )
        assert meta and meta["items"] == 300 and meta["sha256"]

    def test_attach_verifies_and_serves(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        vecs = clustered_corpus(400, 8)
        m, model = _publish_similar_model(store, "eng", vecs)
        assert lifecycle.attach_from_registry(store, "eng", m.version, [model]) is None
        lifecycle.build_for_version(
            store, "eng", m.version, [model], AnnConfig(min_items=0), force=True
        )
        fresh = model_io.deserialize_models(store.load_blob("eng", m.version))
        serving = lifecycle.attach_from_registry(store, "eng", m.version, fresh)
        assert serving is not None
        assert getattr(fresh[0], lifecycle.ATTR) is serving
        assert serving.index.model_version == m.version

    def test_attach_rejects_item_count_mismatch(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        vecs = clustered_corpus(400, 8)
        m, model = _publish_similar_model(store, "eng", vecs)
        lifecycle.build_for_version(
            store, "eng", m.version, [model], AnnConfig(min_items=0), force=True
        )
        from predictionio_tpu.models.similarproduct.engine import SimilarModel

        shrunk = SimilarModel(vecs[:100].copy(), [f"i{j}" for j in range(100)], [None] * 100)
        assert (
            lifecycle.attach_from_registry(store, "eng", m.version, [shrunk]) is None
        )

    def test_corrupted_index_blob_fails_verification_not_serving(self, tmp_path):
        import os

        store = ArtifactStore(str(tmp_path))
        vecs = clustered_corpus(400, 8)
        m, model = _publish_similar_model(store, "eng", vecs)
        lifecycle.build_for_version(
            store, "eng", m.version, [model], AnnConfig(min_items=0), force=True
        )
        sha = store.get_manifest("eng", m.version).ann_index["sha256"]
        path = store._blob_path("eng", sha)
        blob = open(path, "rb").read()
        with open(path, "wb") as fh:  # bit-flip
            fh.write(blob[:100] + bytes([blob[100] ^ 0xFF]) + blob[101:])
        with pytest.raises(ArtifactIntegrityError):
            store.load_ann_blob("eng", m.version)
        # the serving attach degrades to exact instead of crashing the lane
        assert lifecycle.attach_from_registry(store, "eng", m.version, [model]) is None

    def test_gc_keeps_referenced_ann_blobs_and_drops_orphaned(self, tmp_path):
        import os

        store = ArtifactStore(str(tmp_path))
        vecs = clustered_corpus(300, 8)
        manifests = []
        for seed in range(3):
            m, model = _publish_similar_model(
                store, "eng", clustered_corpus(300, 8, seed=seed)
            )
            lifecycle.build_for_version(
                store, "eng", m.version, [model], AnnConfig(min_items=0), force=True
            )
            manifests.append(store.get_manifest("eng", m.version))
        store.promote("eng", manifests[-1].version)
        removed = store.gc("eng", keep_last=1)
        # v000002 is neither pinned nor newest-1 -> its ann blob must go
        assert "v000002" in removed
        gone = manifests[1].ann_index["sha256"]
        assert not os.path.exists(store._blob_path("eng", gone))
        # the promoted stable keeps its index artifact
        assert store.load_ann_blob("eng", manifests[-1].version) is not None


# ---------------------------------------------------------------------------
# stream refresh -> candidate -> promote e2e
# ---------------------------------------------------------------------------


class TestStreamRefreshE2E:
    def _rate_event(self, user, item, rating, n):
        import datetime as dt

        from predictionio_tpu.data.datamap import DataMap
        from predictionio_tpu.data.event import Event

        when = dt.datetime(2024, 3, 1, 0, 0, 0, n, tzinfo=dt.timezone.utc)
        return Event(
            event="rate",
            entity_type="user",
            entity_id=user,
            target_entity_type="item",
            target_entity_id=item,
            properties=DataMap({"rating": rating}),
            event_time=when,
            creation_time=when,
        )

    def test_stream_publish_carries_refreshed_index_to_promote(self, tmp_path):
        from predictionio_tpu.data.storage.memory import MemoryStorageClient
        from predictionio_tpu.models.recommendation.engine import ALSModel
        from predictionio_tpu.stream.cursor import CursorStore
        from predictionio_tpu.stream.pipeline import (
            StreamConfig,
            StreamInstruments,
            StreamPipeline,
        )
        from predictionio_tpu.stream.tailer import EventTailer
        from predictionio_tpu.stream.trainers import FoldInALSTrainer

        rng = np.random.default_rng(0)
        n_users, n_items, rank = 20, 60, 4
        seed_model = ALSModel(
            rng.normal(size=(n_users, rank)).astype(np.float32),
            rng.normal(size=(n_items, rank)).astype(np.float32),
            [f"u{i}" for i in range(n_users)],
            [f"i{i}" for i in range(n_items)],
        )
        store = ArtifactStore(str(tmp_path / "registry"))
        stable = store.publish(
            ModelManifest(
                version="",
                engine_id="streameng",
                engine_version="1",
                engine_variant="engine.json",
            ),
            model_io.serialize_models([seed_model]),
        )
        # the batch train built the stable's index
        meta = lifecycle.build_for_version(
            store, "streameng", stable.version, [seed_model],
            AnnConfig(min_items=0), force=True,
        )
        assert meta["builtFrom"] == "train"

        levents = MemoryStorageClient().l_events()
        levents.init(1)
        for i in range(12):
            levents.insert(
                self._rate_event(f"u{i % 5}", f"i{i % 7}", 4.0, i), 1
            )
        trainer = FoldInALSTrainer([seed_model])
        instruments = StreamInstruments(MetricsRegistry())
        pipeline = StreamPipeline(
            EventTailer(levents, 1, batch_limit=50),
            trainer,
            CursorStore(str(tmp_path / "cursors")),
            store,
            StreamConfig(engine_id="streameng", publish_min_events=1),
            instruments=instruments,
        )
        summary = pipeline.run_once()
        candidate = summary["published"]
        assert candidate == "v000002"
        state = store.get_state("streameng")
        assert state.stable == stable.version
        assert state.candidate == candidate
        # the candidate's manifest pins a REFRESHED index with lineage
        cm = store.get_manifest("streameng", candidate)
        assert cm.ann_index and cm.ann_index["builtFrom"] in ("refresh", "rebuild")
        assert cm.ann_index["modelVersion"] == candidate
        assert (
            instruments.ann.refreshes.value() + instruments.ann.rebuilds.value()
            == 1
        )
        # candidate models serve through the candidate's own index
        models = model_io.deserialize_models(store.load_blob("streameng", candidate))
        serving = lifecycle.attach_from_registry(store, "streameng", candidate, models)
        assert serving is not None
        assert serving.index.n_items == len(models[0].item_vocab)
        # ... and the normal rollout path promotes it, index included
        store.promote("streameng")
        assert store.get_state("streameng").stable == candidate
        assert store.load_ann_blob("streameng", candidate) is not None

    def test_no_parent_index_means_no_refresh(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        vecs = clustered_corpus(200, 8)
        m, model = _publish_similar_model(store, "eng", vecs)
        report = lifecycle.refresh_for_publish(
            store, "eng", m.version, m.version, [model]
        )
        assert report is None


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------


class TestSimilarproductServing:
    N, F = 3000, 8

    @pytest.fixture(scope="class")
    def served(self):
        from predictionio_tpu.models.similarproduct.engine import (
            ALSAlgorithm,
            SimilarModel,
        )

        vecs = clustered_corpus(self.N, self.F, seed=6)
        vocab = [f"i{j}" for j in range(self.N)]
        cats = [
            frozenset({"even"} if j % 2 == 0 else {"odd"}) for j in range(self.N)
        ]
        plain = SimilarModel(vecs.copy(), list(vocab), list(cats))
        indexed = SimilarModel(vecs.copy(), list(vocab), list(cats))
        idx = build_index(vecs, AnnConfig(min_items=0), model_version="v1")
        serving = lifecycle.AnnServing(idx, indexed, recall_sample_every=0)
        setattr(indexed, lifecycle.ATTR, serving)
        return ALSAlgorithm(None), plain, indexed, vocab

    def test_ann_path_matches_exact(self, served):
        from predictionio_tpu.models.similarproduct.engine import Query

        algo, plain, indexed, vocab = served
        rng = np.random.default_rng(8)
        queries = [
            Query(items=(vocab[int(j)],), num=10)
            for j in rng.integers(0, self.N, 24)
        ]
        exact = algo.predict_batch(plain, queries)
        ann = algo.predict_batch(indexed, queries)
        hits = total = 0
        for a, e in zip(ann, exact):
            ai = {s.item for s in a.item_scores}
            hits += sum(1 for s in e.item_scores if s.item in ai)
            total += len(e.item_scores)
        assert total and hits / total >= 0.9
        for a, q in zip(ann, queries):
            assert all(s.item not in q.items for s in a.item_scores)

    def test_filtered_queries_route_through_masked_search(self, served):
        from predictionio_tpu.models.similarproduct.engine import Query

        algo, _plain, indexed, vocab = served
        q = Query(items=(vocab[5],), num=10, categories=frozenset({"odd"}))
        (res,) = algo.predict_batch(indexed, [q])
        assert res.item_scores
        for s in res.item_scores:
            assert int(s.item[1:]) % 2 == 1  # category filter honored

    def test_blacklist_honored_on_ann_path(self, served):
        from predictionio_tpu.models.similarproduct.engine import Query

        algo, plain, indexed, vocab = served
        (probe,) = algo.predict_batch(plain, [Query(items=(vocab[5],), num=3)])
        banned = frozenset(s.item for s in probe.item_scores)
        (res,) = algo.predict_batch(
            indexed, [Query(items=(vocab[5],), num=10, black_list=banned)]
        )
        assert res.item_scores
        assert all(s.item not in banned for s in res.item_scores)

    def test_metrics_and_recall_sampling(self, served):
        from predictionio_tpu.models.similarproduct.engine import Query

        algo, _plain, indexed, vocab = served
        serving = getattr(indexed, lifecycle.ATTR)
        ins = AnnInstruments(MetricsRegistry())
        serving.bind(ins)
        serving._sample_every = 1  # every batch shadow-scores exact
        algo.predict_batch(indexed, [Query(items=(vocab[1],), num=10)])
        assert ins.queries.value() == 1
        assert ins.probes.value() == serving.searcher.nprobe
        assert ins.candidates.value() > 0
        assert 0 < ins.candidates_frac.value() <= 0.10
        assert ins.recall_samples.value() == 1
        assert ins.recall_sampled.value() >= 0.9

    def test_int8_index_serves_the_filterless_dispatch(self, served):
        """An int8-quantized pinned index must keep answering the hot
        (filter-less, exclusion-based) path — and filtered queries fall
        back to exact instead of erroring."""
        from predictionio_tpu.models.similarproduct.engine import (
            Query,
            SimilarModel,
        )

        algo, plain, _indexed, vocab = served
        vecs = plain.item_factors
        q8model = SimilarModel(
            vecs.copy(), list(vocab), list(plain.item_categories)
        )
        idx = build_index(
            vecs, AnnConfig(min_items=0, quantize_int8=True), model_version="v8"
        )
        serving = lifecycle.AnnServing(idx, q8model, recall_sample_every=0)
        setattr(q8model, lifecycle.ATTR, serving)
        ins = AnnInstruments(MetricsRegistry())
        serving.bind(ins)
        queries = [Query(items=(vocab[7],), num=10)]
        exact = algo.predict_batch(plain, queries)
        res = algo.predict_batch(q8model, queries)
        assert res[0].item_scores
        assert vocab[7] not in {s.item for s in res[0].item_scores}
        overlap = {s.item for s in res[0].item_scores} & {
            s.item for s in exact[0].item_scores
        }
        assert len(overlap) >= 8
        assert ins.queries.value() == 1
        # filtered query on the int8 index: exact fallback, counted
        (fres,) = algo.predict_batch(
            q8model,
            [Query(items=(vocab[7],), num=10, categories=frozenset({"odd"}))],
        )
        assert fres.item_scores
        assert all(int(s.item[1:]) % 2 == 1 for s in fres.item_scores)
        assert ins.fallbacks.value() == 1

    def test_oversized_k_falls_back_to_exact_and_counts(self, served):
        from predictionio_tpu.models.similarproduct.engine import Query

        algo, plain, indexed, vocab = served
        serving = getattr(indexed, lifecycle.ATTR)
        ins = AnnInstruments(MetricsRegistry())
        serving.bind(ins)
        big = serving.searcher.candidate_pool() + 1
        res = algo.predict_batch(indexed, [Query(items=(vocab[2],), num=big)])
        exact = algo.predict_batch(plain, [Query(items=(vocab[2],), num=big)])
        assert [s.item for s in res[0].item_scores] == [
            s.item for s in exact[0].item_scores
        ]
        assert ins.fallbacks.value() == 1
        assert ins.queries.value() == 0


class TestTwoTowerServing:
    @pytest.fixture(scope="class")
    def served(self):
        from predictionio_tpu.models.twotower.engine import (
            TwoTowerAlgorithm,
            TwoTowerModelState,
        )
        from predictionio_tpu.models.twotower.model import TwoTower, TwoTowerConfig

        import jax

        n_users, n_items = 50, 2500
        config = TwoTowerConfig(
            n_users=n_users, n_items=n_items, embed_dim=8, hidden=(8,), out_dim=8
        )
        model = TwoTower(config)
        rng = jax.random.PRNGKey(0)
        import jax.numpy as jnp

        params = model.init(
            rng, jnp.zeros((2,), jnp.int32), jnp.zeros((2,), jnp.int32), None
        )["params"]
        params = jax.tree_util.tree_map(np.asarray, params)
        ids = jnp.arange(n_items, dtype=jnp.int32)
        item_emb = np.asarray(
            model.apply({"params": params}, ids, method=TwoTower.embed_items)
        )

        def state():
            return TwoTowerModelState(
                config=config,
                params=params,
                item_embeddings=item_emb,
                user_vocab=[f"u{i}" for i in range(n_users)],
                item_vocab=[f"i{i}" for i in range(n_items)],
                losses=[],
            )

        plain, indexed = state(), state()
        idx = build_index(
            item_emb, AnnConfig(min_items=0), model_version="v1"
        )
        serving = lifecycle.AnnServing(idx, indexed, recall_sample_every=0)
        setattr(indexed, lifecycle.ATTR, serving)
        return TwoTowerAlgorithm(None), plain, indexed

    def test_ann_path_matches_exact(self, served):
        from predictionio_tpu.models.twotower.engine import Query

        algo, plain, indexed = served
        queries = [Query(user=f"u{i}", num=10) for i in range(16)]
        exact = algo.predict_batch(plain, queries)
        ann = algo.predict_batch(indexed, queries)
        hits = total = 0
        for a, e in zip(ann, exact):
            ai = {s.item for s in a.item_scores}
            hits += sum(1 for s in e.item_scores if s.item in ai)
            total += len(e.item_scores)
        assert total and hits / total >= 0.9

    def test_unknown_user_answers_empty_without_device(self, served):
        from predictionio_tpu.models.twotower.engine import Query

        algo, _plain, indexed = served
        res = algo.predict_batch(indexed, [Query(user="nobody", num=5)])
        assert res[0].item_scores == ()

    def test_recall_shadow_sampling_records_gauge(self, served):
        from predictionio_tpu.models.twotower.engine import Query

        algo, _plain, indexed = served
        serving = getattr(indexed, lifecycle.ATTR)
        ins = AnnInstruments(MetricsRegistry())
        serving.bind(ins)
        serving._sample_every = 1
        serving._batches = 0
        algo.predict_batch(indexed, [Query(user="u3", num=10)])
        assert ins.recall_samples.value() == 1
        assert ins.recall_sampled.value() >= 0.9

    def test_warmup_covers_ann_and_exact(self, served):
        algo, _plain, indexed = served
        algo.warmup_serving(indexed, max_batch=4)  # must not raise


# ---------------------------------------------------------------------------
# capacity planner + doctor + top
# ---------------------------------------------------------------------------


class TestCapacityPlanner:
    def test_estimate_matches_build_rule(self):
        from predictionio_tpu.obs import xray

        est = xray.estimate_ann(100_000, 32)
        assert est["clusters"] == default_clusters(100_000)
        assert est["bucketCap"] == bucket_capacity(100_000, est["clusters"])
        # the estimate prices the same arrays the build lays out
        idx = build_index(
            clustered_corpus(4000, 8), AnnConfig(min_items=0)
        )
        est2 = xray.estimate_ann(4000, 8, idx.clusters, idx.nprobe)
        assert est2["bucketCap"] == idx.bucket_cap
        assert est2["perDeviceBytes"] == (
            idx.centroids.nbytes + idx.bucket_ids.nbytes + idx.bucket_vecs.nbytes
        )

    def test_estimate_validates_input(self):
        from predictionio_tpu.obs import xray

        with pytest.raises(ValueError):
            xray.estimate_ann(0, 8)

    def test_doctor_ann_prices_and_gates(self, capsys):
        from predictionio_tpu.tools.cli import build_parser, cmd_doctor

        args = build_parser().parse_args(
            ["doctor", "--capacity", "100000", "100000", "32",
             "--ann", "0,0", "--hbm-bytes", "16GB"]
        )
        assert cmd_doctor(args) == 0
        out = json.loads(capsys.readouterr().out.rsplit("\n", 2)[0])
        assert out["ann"]["clusters"] == 2048
        assert out["perDeviceBytesTotal"] > out["capacity"]["per_device_bytes"]
        assert out["fits"] is True

        args = build_parser().parse_args(
            ["doctor", "--capacity", "1000", "1000", "8",
             "--ann", "64,16", "--hbm-bytes", "1KB"]
        )
        assert cmd_doctor(args) == 1  # over budget exits nonzero
        capsys.readouterr()

    def test_doctor_ann_requires_capacity(self, capsys):
        from predictionio_tpu.tools.cli import build_parser, cmd_doctor

        args = build_parser().parse_args(["doctor", "--ann", "0,0"])
        assert cmd_doctor(args) == 1
        capsys.readouterr()

    def test_doctor_inventory_lists_pinned_index(self, tmp_path, capsys):
        from predictionio_tpu.tools.cli import _doctor_ann_inventory

        store = ArtifactStore(str(tmp_path))
        vecs = clustered_corpus(300, 8)
        m, model = _publish_similar_model(store, "inveng", vecs)
        lifecycle.build_for_version(
            store, "inveng", m.version, [model], AnnConfig(min_items=0), force=True
        )
        _doctor_ann_inventory(str(tmp_path))
        out = capsys.readouterr().out
        assert "ann indexes" in out and "300 items" in out and m.version in out


class TestTopAnnLine:
    def _scrape(self, registry):
        from predictionio_tpu.tools import top

        return top.parse_prometheus(registry.render_prometheus())

    def test_silent_until_an_index_is_pinned(self):
        from predictionio_tpu.tools import top

        registry = MetricsRegistry()
        AnnInstruments(registry)  # eager zero registration
        summary = top.summarize(self._scrape(registry))
        assert summary["ann"] is None
        assert "ann " not in top.render(summary, "http://x")

    def test_renders_index_and_live_counters(self):
        from predictionio_tpu.tools import top

        registry = MetricsRegistry()
        ins = AnnInstruments(registry)
        ins.index_items.set(100_000, version="v000003")
        ins.index_clusters.set(2048, version="v000003")
        ins.queries.inc(200)
        ins.probes.inc(3200)
        ins.candidates_frac.set(0.0077)
        ins.recall_samples.inc(3)
        ins.recall_sampled.set(0.996)
        ins.fallbacks.inc(2)
        summary = top.summarize(self._scrape(registry))
        ann = summary["ann"]
        assert ann["queries_total"] == 200
        assert ann["probes_per_query"] == 16.0
        assert ann["indexes"]["v000003"]["items"] == 100_000
        screen = top.render(summary, "http://x")
        assert "ann" in screen and "v000003" in screen
        assert "probes/q 16.0" in screen and "recall~0.996" in screen

    def test_reload_retires_stale_version_gauges(self):
        """sync_indexes must zero a version's gauge series once no live
        lane pins it — `pio top` would otherwise list every version a
        long-running server ever served as simultaneously pinned."""
        from predictionio_tpu.tools import top

        registry = MetricsRegistry()
        ins = AnnInstruments(registry)
        ins.sync_indexes({"v1": (1000.0, 64.0)})
        ins.sync_indexes({"v2": (1200.0, 64.0)})  # reload: v1 retired
        summary = top.summarize(self._scrape(registry))
        assert set(summary["ann"]["indexes"]) == {"v2"}
        # both lanes pinned during a rollout: both render
        ins.sync_indexes({"v2": (1200.0, 64.0), "v3": (1300.0, 64.0)})
        summary = top.summarize(self._scrape(registry))
        assert set(summary["ann"]["indexes"]) == {"v2", "v3"}

    def test_json_mode_carries_ann_fields(self):
        from predictionio_tpu.tools import top

        registry = MetricsRegistry()
        ins = AnnInstruments(registry)
        ins.index_items.set(500, version="v1")
        text = registry.render_prometheus()
        outs = []
        top.run_top(
            "http://a",
            iterations=1,
            fetch=lambda u: text,
            out=outs.append,
            json_mode=True,
        )
        payload = json.loads(outs[0])
        assert payload["ann"]["indexes"]["v1"]["items"] == 500


class TestBenchContractAnn:
    def test_compare_directions_for_ann_fields(self):
        import bench

        assert bench._compare_direction("serving_ann_p50_ms") == 1
        assert bench._compare_direction("serving_ann_candidates_frac") == 1
        assert bench._compare_direction("serving_ann_recall_at_10") == -1
        # informational fields must NOT gate
        assert bench._compare_direction("serving_ann_build_s") == 0

    def test_recall_decay_trips_the_gate(self):
        import bench

        prior = {"serving_ann_recall_at_10": 0.99, "serving_ann_p50_ms": 5.0}
        good = bench.compare_bench(
            {"serving_ann_recall_at_10": 0.98, "serving_ann_p50_ms": 5.1}, [prior]
        )
        assert good["compare_ok"]
        bad = bench.compare_bench(
            {"serving_ann_recall_at_10": 0.60, "serving_ann_p50_ms": 5.0}, [prior]
        )
        assert not bad["compare_ok"]
