"""Worker script for the multi-host launcher tests: rendezvous over the
PIO_COORDINATOR contract, build a mesh spanning both processes, run one
sharded jit step over a global array, and verify the cross-process result.

Run by tests/test_launcher.py via MultiHostLauncher — never by pytest
directly (no test_ prefix)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from predictionio_tpu.utils.platform import ensure_cpu_if_requested

ensure_cpu_if_requested()

import jax  # noqa: E402


def main() -> int:
    if "--fail-rank" in sys.argv:
        rank = int(os.environ.get("PIO_PROCESS_ID", "0"))
        fail_rank = int(sys.argv[sys.argv.index("--fail-rank") + 1])
        if rank == fail_rank:
            print(f"rank {rank}: simulated failure", flush=True)
            return 3
        # the surviving rank blocks in rendezvous; the launcher must
        # terminate it once the failing rank exits

    from predictionio_tpu.parallel.distributed import (
        maybe_initialize_distributed,
    )

    assert maybe_initialize_distributed(), "coordinator env contract missing"

    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n_proc = jax.process_count()
    n_dev = jax.device_count()
    assert n_dev == 2 * n_proc, f"expected {2 * n_proc} global devices, got {n_dev}"

    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    local = np.arange(2, dtype=np.float32) + 10.0 * jax.process_index()
    garr = jax.make_array_from_process_local_data(sharding, local, (n_dev,))

    @jax.jit
    def step(x):
        return (x * 2).sum()  # cross-process reduction

    expected = float(
        sum((np.arange(2) + 10.0 * p).sum() * 2 for p in range(n_proc))
    )
    out = float(step(garr))
    assert out == expected, f"sharded step: {out} != {expected}"

    # shard_columns with UNEVEN per-process row counts: processes must
    # coordinate one global shape (an uncoordinated build inferred a
    # different global shape per process), and the mask column must select
    # exactly the real rows even though pads sit mid-global-array
    from predictionio_tpu.parallel.ingest import shard_columns

    rank = jax.process_index()
    local_rows = 3 if rank == 0 else 5
    vals = np.full((local_rows,), float(rank + 1), np.float32)
    cols, n_local = shard_columns(
        mesh, {"v": vals}, axis="data", mask_name="ok"
    )
    assert n_local == local_rows

    @jax.jit
    def masked_sum(v, ok):
        return (v * ok.astype(v.dtype)).sum()

    got = float(masked_sum(cols["v"], cols["ok"]))
    want = float(sum((3 if p == 0 else 5) * (p + 1) for p in range(n_proc)))
    assert got == want, f"masked shard_columns sum: {got} != {want}"
    print(
        f"rank {jax.process_index()}/{n_proc}: sharded step ok ({out}), "
        f"uneven shard_columns ok ({got})",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
