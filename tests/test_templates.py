"""Template tests: classification, similar-product, e-commerce, two-tower
(ref per-template engine behaviors in examples/)."""

import numpy as np
import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.workflow.context import WorkflowContext

APP = "tplapp"


def seed_app(storage):
    app_id = storage.get_meta_data_apps().insert(App(0, APP))
    return app_id, storage.get_l_events()


def ctx(storage):
    return WorkflowContext(mode="training", _storage=storage, app_name=APP)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


class TestClassification:
    def seed(self, storage):
        app_id, levents = seed_app(storage)
        rng = np.random.default_rng(0)
        events = []
        for u in range(60):
            plan = float(u % 2)
            # attrs correlate with plan
            base = np.array([3.0, 0.0, 3.0]) if plan else np.array([0.0, 3.0, 0.0])
            attrs = rng.poisson(base + 0.3)
            events.append(
                Event(
                    event="$set",
                    entity_type="user",
                    entity_id=f"u{u}",
                    properties=DataMap(
                        {
                            "plan": plan,
                            "attr0": float(attrs[0]),
                            "attr1": float(attrs[1]),
                            "attr2": float(attrs[2]),
                        }
                    ),
                )
            )
        levents.insert_batch(events, app_id)

    def variant(self, algos):
        return {
            "datasource": {"params": {"appName": APP, "evalK": 3}},
            "algorithms": algos,
        }

    def test_train_and_predict_both_algos(self, memory_storage):
        from predictionio_tpu.models.classification import engine_factory
        from predictionio_tpu.models.classification.engine import Query

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            self.variant(
                [
                    {"name": "naive", "params": {"lambda": 1.0}},
                    {"name": "randomforest", "params": {"numTrees": 5}},
                ]
            )
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        assert len(models) == 2
        _, _, algos, _ = engine.make_components(ep)
        for algo, model in zip(algos, models):
            plan1 = algo.predict(model, Query(4.0, 0.0, 4.0))
            plan0 = algo.predict(model, Query(0.0, 4.0, 0.0))
            assert plan1.label == 1.0
            assert plan0.label == 0.0

    def test_eval_precision(self, memory_storage):
        from predictionio_tpu.eval import AverageMetric, MetricEvaluator
        from predictionio_tpu.models.classification import engine_factory

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            self.variant([{"name": "naive", "params": {}}])
        )

        class Accuracy(AverageMetric):
            def calculate_score(self, ei, q, p, a):
                return 1.0 if p.label == a.label else 0.0

        result = MetricEvaluator(Accuracy()).evaluate_base(
            ctx(memory_storage), engine, [ep]
        )
        assert result.best_score > 0.8  # separable synthetic data


# ---------------------------------------------------------------------------
# similar-product
# ---------------------------------------------------------------------------


class TestSimilarProduct:
    def seed(self, storage):
        app_id, levents = seed_app(storage)
        rng = np.random.default_rng(1)
        events = []
        # two item clusters: users view within one cluster
        for u in range(40):
            cluster = u % 2
            for _ in range(12):
                i = int(rng.integers(0, 10)) + cluster * 10
                events.append(
                    Event(
                        event="view",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                    )
                )
        # item category properties
        for i in range(20):
            events.append(
                Event(
                    event="$set",
                    entity_type="item",
                    entity_id=f"i{i}",
                    properties=DataMap(
                        {"categories": ["even" if i % 2 == 0 else "odd"]}
                    ),
                )
            )
        levents.insert_batch(events, app_id)

    def variant(self, name="als", params=None):
        return {
            "datasource": {"params": {"appName": APP}},
            "algorithms": [
                {"name": name, "params": params or {"rank": 8, "numIterations": 8}}
            ],
        }

    def make(self, memory_storage, name="als", params=None):
        from predictionio_tpu.models.similarproduct import engine_factory

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(self.variant(name, params))
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        _, _, algos, _ = engine.make_components(ep)
        return engine, algos[0], models[0]

    def test_als_similar_items_same_cluster(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        _, algo, model = self.make(memory_storage)
        result = algo.predict(model, Query(items=("i1",), num=5))
        assert len(result.item_scores) == 5
        items = [s.item for s in result.item_scores]
        assert "i1" not in items  # query item excluded
        same_cluster = sum(1 for it in items if int(it[1:]) < 10)
        assert same_cluster >= 4  # mostly same cluster

    def test_filters(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        _, algo, model = self.make(memory_storage)
        r = algo.predict(
            model, Query(items=("i1",), num=10, white_list=frozenset({"i2", "i3"}))
        )
        assert {s.item for s in r.item_scores} <= {"i2", "i3"}
        r = algo.predict(
            model, Query(items=("i1",), num=10, black_list=frozenset({"i2"}))
        )
        assert "i2" not in {s.item for s in r.item_scores}
        r = algo.predict(
            model, Query(items=("i1",), num=10, categories=frozenset({"even"}))
        )
        assert all(int(s.item[1:]) % 2 == 0 for s in r.item_scores)
        r = algo.predict(
            model,
            Query(items=("i1",), num=10, category_black_list=frozenset({"even"})),
        )
        assert all(int(s.item[1:]) % 2 == 1 for s in r.item_scores)

    def test_unknown_query_items(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        _, algo, model = self.make(memory_storage)
        assert algo.predict(model, Query(items=("ghost",), num=5)).item_scores == ()

    def test_batch_nonpositive_num_returns_empty(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        _, algo, model = self.make(memory_storage)
        # a num<=0 query sharing a batch with a real one must come back
        # empty, not sliced as scores[:, :num] with a negative num
        rs = algo.predict_batch(
            model, [Query(items=("i1",), num=-1), Query(items=("i2",), num=5)]
        )
        assert rs[0].item_scores == ()
        assert len(rs[1].item_scores) == 5

    def test_cooccurrence_algorithm(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        _, algo, model = self.make(memory_storage, name="cooccurrence", params={"n": 10})
        result = algo.predict(model, Query(items=("i1",), num=5))
        assert len(result.item_scores) > 0
        items = [int(s.item[1:]) for s in result.item_scores]
        assert all(i < 10 for i in items)  # cooccur within cluster
        # scores are integer counts summed
        assert all(s.score >= 1 for s in result.item_scores)


# ---------------------------------------------------------------------------
# e-commerce
# ---------------------------------------------------------------------------


class TestECommerce:
    def seed(self, storage):
        app_id, levents = seed_app(storage)
        rng = np.random.default_rng(2)
        events = []
        for u in range(30):
            cluster = u % 2
            for _ in range(10):
                i = int(rng.integers(0, 8)) + cluster * 8
                events.append(
                    Event(
                        event="rate",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties=DataMap({"rating": float(rng.integers(3, 6))}),
                    )
                )
        # buys make i0 the most popular
        for u in range(10):
            events.append(
                Event(
                    event="buy",
                    entity_type="user",
                    entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id="i0",
                )
            )
        levents.insert_batch(events, app_id)
        return app_id

    def make(self, memory_storage, **extra):
        from predictionio_tpu.models.ecommerce import engine_factory

        app_id = self.seed(memory_storage)
        engine = engine_factory()
        params = {
            "appName": APP,
            "unseenOnly": False,
            "seenEvents": ["buy", "view"],
            "similarEvents": ["view"],
            "rank": 8,
            "numIterations": 8,
            **extra,
        }
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP}},
                "algorithms": [{"name": "ecomm", "params": params}],
            }
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        _, _, algos, _ = engine.make_components(ep)
        return c, algos[0], models[0], app_id

    def test_known_user(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, _ = self.make(memory_storage)
        r = algo.predict_with_context(c, model, Query(user="u0", num=4))
        assert len(r.item_scores) == 4

    def test_cold_user_popularity_fallback(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, _ = self.make(memory_storage)
        r = algo.predict_with_context(c, model, Query(user="stranger", num=3))
        assert r.item_scores[0].item == "i0"  # most-bought item first

    def test_batch_nonpositive_num_returns_empty(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, _ = self.make(memory_storage)
        # a num<=0 query sharing a batch with a real one must come back
        # empty, not sliced as scores[:num] with a negative num
        rs = algo.predict_batch(
            model, [Query(user="u0", num=-1), Query(user="u1", num=4)]
        )
        assert rs[0].item_scores == ()
        assert len(rs[1].item_scores) == 4

    def test_cold_user_recent_views(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, app_id = self.make(memory_storage)
        # new user views items in cluster 1
        memory_storage.get_l_events().insert_batch(
            [
                Event(
                    event="view",
                    entity_type="user",
                    entity_id="newbie",
                    target_entity_type="item",
                    target_entity_id=f"i{8 + j}",
                )
                for j in range(3)
            ],
            app_id,
        )
        r = algo.predict_with_context(c, model, Query(user="newbie", num=5))
        in_cluster = sum(1 for s in r.item_scores if int(s.item[1:]) >= 8)
        assert in_cluster >= 3

    def test_unseen_only_filters_seen(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, app_id = self.make(memory_storage, unseenOnly=True)
        # u0 bought i0 (seeded); with unseenOnly the result must omit i0
        r = algo.predict_with_context(c, model, Query(user="u0", num=16))
        assert "i0" not in {s.item for s in r.item_scores}

    def test_unavailable_items_constraint(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, app_id = self.make(memory_storage)
        memory_storage.get_l_events().insert(
            Event(
                event="$set",
                entity_type="constraint",
                entity_id="unavailableItems",
                properties=DataMap({"items": ["i1", "i2"]}),
            ),
            app_id,
        )
        r = algo.predict_with_context(c, model, Query(user="u0", num=16))
        assert {"i1", "i2"} & {s.item for s in r.item_scores} == set()

    def _counting_ctx(self, c):
        """Wrap the context's LEventStore so find_by_entity calls are counted."""
        calls = {"n": 0}
        store = c.l_event_store()
        orig = store.find_by_entity

        def counted(*a, **kw):
            calls["n"] += 1
            return orig(*a, **kw)

        store.find_by_entity = counted
        c.l_event_store = lambda: store
        return calls

    def test_lookup_cache_hot_path_zero_storage_reads(self, memory_storage):
        """VERDICT r2 weak #3: with the TTL cache opted in (default is 0 =
        reference's always-live reads) and warm, repeat predicts do ZERO
        storage round trips (the reference pays them per query)."""
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, _ = self.make(memory_storage, unseenOnly=True, cacheTtlS=5)
        calls = self._counting_ctx(c)
        algo.predict_with_context(c, model, Query(user="u0", num=4))
        first = calls["n"]
        assert first >= 1  # cold predict did the live lookups
        for _ in range(5):
            algo.predict_with_context(c, model, Query(user="u0", num=4))
        assert calls["n"] == first  # warm predicts: zero storage reads

    def test_lookup_cache_ttl_zero_restores_live_reads(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, _ = self.make(memory_storage, unseenOnly=True, cacheTtlS=0)
        calls = self._counting_ctx(c)
        algo.predict_with_context(c, model, Query(user="u0", num=4))
        first = calls["n"]
        algo.predict_with_context(c, model, Query(user="u0", num=4))
        assert calls["n"] == 2 * first  # reference semantics: live every query

    def test_lookup_cache_expires(self, memory_storage):
        import time as _time

        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, app_id = self.make(memory_storage, cacheTtlS=0.05)
        r = algo.predict_with_context(c, model, Query(user="u0", num=16))
        assert "i3" in {s.item for s in r.item_scores}
        memory_storage.get_l_events().insert(
            Event(
                event="$set",
                entity_type="constraint",
                entity_id="unavailableItems",
                properties=DataMap({"items": ["i3"]}),
            ),
            app_id,
        )
        _time.sleep(0.06)  # past the TTL: next predict re-reads the constraint
        r = algo.predict_with_context(c, model, Query(user="u0", num=16))
        assert "i3" not in {s.item for s in r.item_scores}


# ---------------------------------------------------------------------------
# two-tower
# ---------------------------------------------------------------------------


class TestTwoTower:
    def test_loss_masks_duplicate_item_collisions(self):
        """In-batch softmax correction (round 4): when every batch item is
        the SAME catalog item, all off-diagonal 'negatives' are the true
        item itself — masked out, the user->item direction has one effective
        class and contributes ~zero loss; unmasked it would be ~log(B)."""
        import jax
        import jax.numpy as jnp

        from predictionio_tpu.models.twotower.model import (
            TwoTower,
            TwoTowerConfig,
            loss_fn,
        )

        cfg = TwoTowerConfig(n_users=16, n_items=4, embed_dim=8, hidden=(8,), out_dim=4)
        model = TwoTower(cfg)
        B = 8
        users = jnp.arange(B, dtype=jnp.int32)
        items = jnp.zeros((B,), jnp.int32)  # all the same item
        params = model.init(jax.random.PRNGKey(0), users, items)["params"]
        loss = float(loss_fn(model, params, users, items, cfg.temperature))
        # l1 ~ 0 (single unmasked class); l2 (item->user) still a real
        # B-way softmax, so total = 0.5*(~0 + l2) < 0.5*log(B) + slack,
        # whereas the uncorrected symmetric loss would be ~log(B) = 2.08
        assert loss < 0.5 * float(jnp.log(jnp.asarray(float(B)))) + 0.2, loss

    def test_logq_correction_changes_gradient_for_skewed_items(self):
        import jax
        import jax.numpy as jnp

        from predictionio_tpu.models.twotower.model import (
            TwoTower,
            TwoTowerConfig,
            loss_fn,
        )

        cfg = TwoTowerConfig(n_users=16, n_items=8, embed_dim=8, hidden=(8,), out_dim=4)
        model = TwoTower(cfg)
        B = 8
        users = jnp.arange(B, dtype=jnp.int32)
        items = jnp.arange(B, dtype=jnp.int32) % 8
        params = model.init(jax.random.PRNGKey(0), users, items)["params"]
        logq = jnp.log(jnp.asarray([0.5, 0.1, 0.1, 0.1, 0.05, 0.05, 0.05, 0.05]))
        base = float(loss_fn(model, params, users, items, cfg.temperature))
        corrected = float(
            loss_fn(model, params, users, items, cfg.temperature, None, logq)
        )
        assert base != corrected  # the debiasing term is live

    @pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
    def test_context_parallel_encoder_matches_single_device(self, sp_impl):
        """The history encoder's sequence-parallel attention (ring /
        ulysses over the mesh's model axis, dp-composed over data) must
        produce the same embeddings as the single-device fused path —
        same params, same inputs, attention carries no parameters."""
        import dataclasses as dc

        import jax
        import jax.numpy as jnp

        from predictionio_tpu.models.twotower.model import TwoTower, TwoTowerConfig
        from predictionio_tpu.parallel.mesh import make_mesh

        mesh = make_mesh("data=2,model=4")
        cfg = TwoTowerConfig(
            n_users=16, n_items=12, embed_dim=8, hidden=(8,), out_dim=4,
            history_len=8, n_heads=4, context_parallel=True, sp_impl=sp_impl,
        )
        ref_cfg = dc.replace(cfg, context_parallel=False)
        B = 8
        rng = jax.random.PRNGKey(0)
        u = jnp.arange(B, dtype=jnp.int32)
        i = jnp.arange(B, dtype=jnp.int32) % 12
        h = jnp.asarray(
            np.random.default_rng(0).integers(-1, 12, (B, 8)), jnp.int32
        )
        ref = TwoTower(ref_cfg)
        params = ref.init(rng, u, i, h)["params"]
        out_ref = ref.apply({"params": params}, u, i, h)
        sp = TwoTower(cfg, sp_mesh=mesh)
        out_sp = sp.apply({"params": params}, u, i, h)
        for a, b in zip(out_sp, out_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5
            )

    def test_context_parallel_trains(self):
        """Gradients flow through the ring collective (ppermute inside
        fori_loop): a short context-parallel train must reduce the loss."""
        from predictionio_tpu.models.twotower.model import (
            TwoTowerConfig,
            build_history_matrix,
            train_two_tower,
        )
        from predictionio_tpu.parallel.mesh import make_mesh

        rng = np.random.default_rng(5)
        n_users, n_items = 32, 16
        u = rng.integers(0, n_users, 600).astype(np.int32)
        i = ((u % 4) * 4 + rng.integers(0, 4, 600)).astype(np.int32)
        cfg = TwoTowerConfig(
            n_users=n_users, n_items=n_items, embed_dim=8, hidden=(16,),
            out_dim=8, batch_size=64, epochs=6, history_len=8, n_heads=2,
            context_parallel=True,
        )
        hist = build_history_matrix(u, i, None, n_users, cfg.history_len)
        res = train_two_tower(
            u, i, cfg, mesh=make_mesh("data=4,model=2"), history=hist
        )
        assert np.isfinite(res.losses).all()
        assert res.losses[-1] < res.losses[0]

    def test_context_parallel_requires_divisible_history(self):
        import jax
        import jax.numpy as jnp

        from predictionio_tpu.models.twotower.model import TwoTower, TwoTowerConfig
        from predictionio_tpu.parallel.mesh import make_mesh

        cfg = TwoTowerConfig(
            n_users=8, n_items=8, embed_dim=8, hidden=(8,), out_dim=4,
            history_len=6, n_heads=2, context_parallel=True,  # 6 % 4 != 0
        )
        model = TwoTower(cfg, sp_mesh=make_mesh("data=2,model=4"))
        u = jnp.zeros((4,), jnp.int32)
        h = jnp.zeros((4, 6), jnp.int32)
        with pytest.raises(ValueError, match="not divisible"):
            model.init(jax.random.PRNGKey(0), u, u, h)

    def seed(self, storage):
        app_id, levents = seed_app(storage)
        rng = np.random.default_rng(3)
        events = []
        for u in range(24):
            cluster = u % 2
            for _ in range(10):
                i = int(rng.integers(0, 6)) + cluster * 6
                events.append(
                    Event(
                        event="view",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                    )
                )
        levents.insert_batch(events, app_id)

    def test_train_and_retrieve(self, memory_storage):
        from predictionio_tpu.models.twotower import engine_factory
        from predictionio_tpu.models.twotower.engine import Query

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "twotower",
                        "params": {
                            "embedDim": 16,
                            "hidden": [32],
                            "outDim": 8,
                            "epochs": 30,
                            "batchSize": 64,
                            "mesh": "data=4,model=2",
                        },
                    }
                ],
            }
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        model = models[0]
        # loss decreased
        assert model.losses[-1] < model.losses[0]
        _, _, algos, _ = engine.make_components(ep)
        algo = algos[0]
        r = algo.predict(model, Query(user="u0", num=4))
        assert len(r.item_scores) == 4
        # in-cluster retrieval dominates
        in_cluster = sum(1 for s in r.item_scores if int(s.item[1:]) < 6)
        assert in_cluster >= 3
        # unknown user -> empty
        assert algo.predict(model, Query(user="ghost")).item_scores == ()

    def test_history_encoder_end_to_end(self, memory_storage):
        """historyLen > 0 turns on the sequence encoder (the consumer of
        ops/attention.fused_attention — pallas on TPU, jnp reference here):
        train through the template, predict with per-user histories, and
        round-trip the model blob with its history matrix."""
        import pickle

        from predictionio_tpu.models.twotower import engine_factory
        from predictionio_tpu.models.twotower.engine import Query

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "twotower",
                        "params": {
                            "embedDim": 16,
                            "hidden": [32],
                            "outDim": 8,
                            "epochs": 30,
                            "batchSize": 64,
                            "historyLen": 8,
                            "nHeads": 2,
                        },
                    }
                ],
            }
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        model = models[0]
        assert model.history is not None and model.history.shape[1] == 8
        # encoder params actually exist in the tree
        assert "hist_encoder" in model.params
        assert model.losses[-1] < model.losses[0]
        _, _, algos, _ = engine.make_components(ep)
        algo = algos[0]
        r = algo.predict(model, Query(user="u0", num=4))
        assert len(r.item_scores) == 4
        in_cluster = sum(1 for s in r.item_scores if int(s.item[1:]) < 6)
        assert in_cluster >= 3
        # serialization carries the history matrix (serving needs it)
        clone = pickle.loads(pickle.dumps(model))
        r2 = algo.predict(clone, Query(user="u0", num=4))
        assert [s.item for s in r2.item_scores] == [s.item for s in r.item_scores]

    def test_context_parallel_end_to_end(self, memory_storage):
        """contextParallel through engine.json: train with the history axis
        sharded over the mesh's model axis, then serve the model mesh-less
        (attention carries no params, so checkpoints are sharding-agnostic)."""
        import pickle

        from predictionio_tpu.models.twotower import engine_factory
        from predictionio_tpu.models.twotower.engine import Query

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "twotower",
                        "params": {
                            "embedDim": 16,
                            "hidden": [32],
                            "outDim": 8,
                            "epochs": 20,
                            "batchSize": 64,
                            "historyLen": 8,
                            "nHeads": 2,
                            "mesh": "data=4,model=2",
                            "contextParallel": True,
                        },
                    }
                ],
            }
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        model = models[0]
        assert model.config.context_parallel
        assert model.losses[-1] < model.losses[0]
        _, _, algos, _ = engine.make_components(ep)
        algo = algos[0]
        # serving reconstructs TwoTower WITHOUT a mesh — same params
        r = algo.predict(pickle.loads(pickle.dumps(model)), Query(user="u0", num=4))
        assert len(r.item_scores) == 4

    def _ckpt_problem(self):
        rng = np.random.default_rng(7)
        u = rng.integers(0, 20, 400).astype(np.int32)
        i = ((u % 4) * 3 + rng.integers(0, 3, 400)).astype(np.int32)
        return u, i

    def _ckpt_config(self, tmp_path, **over):
        from predictionio_tpu.models.twotower.model import TwoTowerConfig

        base = dict(
            n_users=20, n_items=12, embed_dim=8, hidden=(8,), out_dim=4,
            batch_size=64, epochs=4, checkpoint_dir=str(tmp_path / "ckpt"),
        )
        base.update(over)
        return TwoTowerConfig(**base)

    def test_completed_run_clears_its_checkpoint(self, tmp_path):
        """A finished run's checkpoint must not survive: resume=True would
        otherwise turn the next scheduled retrain into a silent no-op that
        returns the stale parameters (code-review r4, top finding)."""
        import os

        from predictionio_tpu.models.twotower.model import (
            _CKPT_NAME,
            train_two_tower,
        )

        u, i = self._ckpt_problem()
        cfg = self._ckpt_config(tmp_path)
        r1 = train_two_tower(u, i, cfg)
        assert len(r1.losses) == 4
        assert not os.path.exists(os.path.join(cfg.checkpoint_dir, _CKPT_NAME))
        # the second train actually trains (4 fresh epochs, not a resume)
        r2 = train_two_tower(u, i, cfg)
        assert len(r2.losses) == 4

    def test_resume_continues_from_checkpoint(self, tmp_path):
        """An interrupted run's checkpoint resumes: prior losses are kept
        and only the remaining epochs run."""
        import jax
        import jax.numpy as jnp
        import numpy as np_
        import optax

        from predictionio_tpu.models.twotower.model import (
            TwoTower,
            _train_signature,
            save_train_checkpoint,
            train_two_tower,
        )

        u, i = self._ckpt_problem()
        cfg = self._ckpt_config(tmp_path)
        # fabricate epoch-2 state exactly as an interrupted run leaves it
        model = TwoTower(cfg)
        z = jnp.zeros((8,), jnp.int32)
        params = model.init(jax.random.PRNGKey(cfg.seed), z, z)["params"]
        opt_state = optax.adam(cfg.learning_rate).init(params)
        host = jax.tree_util.tree_map(np_.asarray, (params, opt_state))
        save_train_checkpoint(
            cfg.checkpoint_dir, host[0], host[1], 2, [9.0, 8.5],
            signature=_train_signature(cfg, u, i),
        )
        res = train_two_tower(u, i, cfg)
        assert res.losses[:2] == [9.0, 8.5]  # carried over
        assert len(res.losses) == 4  # only epochs 3-4 ran fresh

    def test_stale_checkpoint_from_other_config_ignored(self, tmp_path):
        """A checkpoint whose signature doesn't match (different dataset or
        vocab sizes) must be ignored — restoring wrong-shape embedding
        tables would corrupt silently (XLA clamps OOB gathers)."""
        import jax
        import jax.numpy as jnp
        import numpy as np_
        import optax

        from predictionio_tpu.models.twotower.model import (
            TwoTower,
            save_train_checkpoint,
            train_two_tower,
        )

        u, i = self._ckpt_problem()
        cfg = self._ckpt_config(tmp_path)
        model = TwoTower(cfg)
        z = jnp.zeros((8,), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), z, z)["params"]
        opt_state = optax.adam(cfg.learning_rate).init(params)
        host = jax.tree_util.tree_map(np_.asarray, (params, opt_state))
        save_train_checkpoint(
            cfg.checkpoint_dir, host[0], host[1], 4, [1.0] * 4,
            signature="someone-elses-run",
        )
        res = train_two_tower(u, i, cfg)
        # trained from scratch: 4 fresh losses, fabricated ones discarded
        assert len(res.losses) == 4 and res.losses[:2] != [1.0, 1.0]

    def test_build_history_matrix_chronological_pad_end(self):
        from predictionio_tpu.models.twotower.model import build_history_matrix

        u = np.asarray([1, 0, 1, 1, 1], np.int32)
        i = np.asarray([5, 9, 3, 7, 2], np.int32)
        ts = np.asarray([3.0, 0.0, 1.0, 2.0, 4.0])
        hist = build_history_matrix(u, i, ts, n_users=3, history_len=3)
        # user 1: chronological (3, 7, 5, 2) -> last 3 = (7, 5, 2)
        assert hist[1].tolist() == [7, 5, 2]
        assert hist[0].tolist() == [9, -1, -1]  # pad at END
        assert hist[2].tolist() == [-1, -1, -1]

    def test_model_checkpoint_roundtrip(self, memory_storage):
        from predictionio_tpu.controller import model_to_host
        from predictionio_tpu.models.twotower import engine_factory
        from predictionio_tpu.models.twotower.engine import Query
        from predictionio_tpu.workflow import model_io

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "twotower",
                        "params": {"embedDim": 8, "hidden": [16], "outDim": 8,
                                   "epochs": 2, "batchSize": 32},
                    }
                ],
            }
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        blob = model_io.serialize_models(
            engine.make_serializable_models(c, ep, models)
        )
        (restored,) = model_io.deserialize_models(blob)
        _, _, algos, _ = engine.make_components(ep)
        r1 = algos[0].predict(models[0], Query(user="u1", num=3))
        r2 = algos[0].predict(restored, Query(user="u1", num=3))
        assert [s.item for s in r1.item_scores] == [s.item for s in r2.item_scores]


# ---------------------------------------------------------------------------
# recommendation variants (ref examples/scala-parallel-recommendation/*)
# ---------------------------------------------------------------------------


class TestRecommendationVariants:
    def seed(self, storage, like_dislike=False, views=False):
        app_id, levents = seed_app(storage)
        events = []
        rng = np.random.default_rng(0)
        for u in range(20):
            for i in range(15):
                if (u + i) % 4 == 0:
                    continue
                if like_dislike:
                    name = "like" if (u + i) % 3 == 0 else "dislike"
                    props = {}
                elif views:
                    name, props = "view", {}
                else:
                    name = "rate"
                    props = {"rating": 5.0 if (u + i) % 3 == 0 else 1.0}
                events.append(
                    Event(
                        event=name,
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties=DataMap(props),
                    )
                )
        levents.insert_batch(events, app_id)

    def make(self, storage, variant):
        from predictionio_tpu.models.recommendation.engine import engine_factory

        engine = engine_factory()
        ep = engine.engine_params_from_variant(variant)
        models = engine.train(ctx(storage), ep)
        _, _, algos, serving = engine.make_components(ep)
        return engine, algos, models, serving

    def base_variant(self, **extra):
        v = {
            "datasource": {"params": {"appName": APP}},
            "algorithms": [
                {
                    "name": "als",
                    "params": {"rank": 8, "numIterations": 8, "lambda": 0.05, "seed": 1},
                }
            ],
        }
        v.update(extra)
        return v

    def test_gather_dtype_param_reaches_solver(self, memory_storage):
        """gatherDtype in engine.json flows through to ALSConfig: bf16
        trains to usable factors, a bad value fails at param parse/train."""
        from predictionio_tpu.models.recommendation.engine import Query

        self.seed(memory_storage)
        v = self.base_variant()
        v["algorithms"][0]["params"]["gatherDtype"] = "bf16"
        engine, algos, models, serving = self.make(memory_storage, v)
        r = algos[0].predict(models[0], Query(user="u1", num=5))
        assert len(r.item_scores) == 5
        v["algorithms"][0]["params"]["gatherDtype"] = "f64"
        with pytest.raises(ValueError, match="gather_dtype"):
            self.make(memory_storage, v)

    def test_solver_param_reaches_solver(self, memory_storage):
        """solver in engine.json flows through to ALSConfig: cg_fused
        trains to usable factors; a bad value fails at train."""
        from predictionio_tpu.models.recommendation.engine import Query

        self.seed(memory_storage)
        v = self.base_variant()
        v["algorithms"][0]["params"]["solver"] = "cg_fused"
        engine, algos, models, serving = self.make(memory_storage, v)
        r = algos[0].predict(models[0], Query(user="u1", num=5))
        assert len(r.item_scores) == 5
        v["algorithms"][0]["params"]["solver"] = "lu"
        with pytest.raises(ValueError, match="solver"):
            self.make(memory_storage, v)

    def test_blacklist_items_excluded(self, memory_storage):
        from predictionio_tpu.models.recommendation.engine import Query

        self.seed(memory_storage)
        engine, algos, models, serving = self.make(memory_storage, self.base_variant())
        full = algos[0].predict(models[0], Query(user="u1", num=5))
        assert len(full.item_scores) == 5
        banned = frozenset(s.item for s in full.item_scores[:2])
        filtered = algos[0].predict(
            models[0], Query(user="u1", num=5, black_list=banned)
        )
        got = {s.item for s in filtered.item_scores}
        assert not (got & banned)
        assert len(filtered.item_scores) == 5  # backfilled from next-best

    def test_blacklist_items_variant_file(self, memory_storage):
        """The blacklist-items variant end-to-end: load the actual shipped
        variant json, train through it, decode a wire query carrying
        blackList, and assert exclusion through the full serve pipeline
        (ref examples/scala-parallel-recommendation/blacklist-items/)."""
        import json as _json
        import os

        from predictionio_tpu.models.recommendation.engine import Query

        self.seed(memory_storage)
        vpath = os.path.join(
            os.path.dirname(
                __import__(
                    "predictionio_tpu.models.recommendation", fromlist=["x"]
                ).__file__
            ),
            "variants",
            "blacklist-items.json",
        )
        with open(vpath) as fh:
            variant = _json.load(fh)
        variant["datasource"]["params"]["appName"] = APP
        variant["algorithms"][0]["params"].update({"rank": 8, "numIterations": 8})
        engine, algos, models, serving = self.make(memory_storage, variant)
        full = algos[0].predict(models[0], Query.from_json_dict({"user": "u1", "num": 5}))
        banned = [s.item for s in full.item_scores[:2]]
        q = Query.from_json_dict({"user": "u1", "num": 5, "blackList": banned})
        preds = [algo.predict(m, q) for algo, m in zip(algos, models)]
        out = serving.serve(q, preds)
        got = {s.item for s in out.item_scores}
        assert not (got & set(banned))
        assert len(out.item_scores) == 5  # backfilled from next-best

    def test_blacklist_query_decode(self):
        from predictionio_tpu.models.recommendation.engine import Query

        q = Query.from_json_dict({"user": "u1", "num": 3, "blackList": ["i1", "i2"]})
        assert q.black_list == frozenset({"i1", "i2"})

    def test_customize_serving_filters_disabled_file(self, memory_storage, tmp_path):
        from predictionio_tpu.models.recommendation.engine import Query

        self.seed(memory_storage)
        disabled = tmp_path / "disabled_items.txt"
        disabled.write_text("")  # nothing disabled yet
        variant = self.base_variant(
            serving={"name": "filter", "params": {"filepath": str(disabled)}}
        )
        engine, algos, models, serving = self.make(memory_storage, variant)
        q = Query(user="u2", num=4)
        preds = [algos[0].predict(models[0], q)]
        assert len(serving.serve(q, preds).item_scores) == 4
        # live edit: disable the top item, no retrain/redeploy
        top = preds[0].item_scores[0].item
        disabled.write_text(top + "\n")
        served = serving.serve(q, preds)
        assert top not in {s.item for s in served.item_scores}

    def test_customize_data_prep_excludes_items(self, memory_storage, tmp_path):
        self.seed(memory_storage)
        exclude = tmp_path / "no_train.txt"
        exclude.write_text("i3\ni4\n")
        variant = self.base_variant(
            preparator={"name": "custom", "params": {"filepath": str(exclude)}}
        )
        from predictionio_tpu.models.recommendation.engine import engine_factory

        engine = engine_factory()
        ep = engine.engine_params_from_variant(variant)
        ds, prep, _, _ = engine.make_components(ep)
        td = ds.read_training(ctx(memory_storage))
        pd = prep.prepare(ctx(memory_storage), td)
        # excluded items leave the vocab entirely (no zero-factor rows that
        # could still be served at score 0.0)
        assert "i3" not in pd.item_vocab and "i4" not in pd.item_vocab
        assert len(pd.ratings) < len(td.ratings)
        # remaining indices still map to the right ids
        kept = sorted(set(pd.item_idx.tolist()))
        assert all(0 <= i < len(pd.item_vocab) for i in kept)

    def test_reading_custom_events_rating_map(self, memory_storage):
        self.seed(memory_storage, like_dislike=True)
        variant = self.base_variant(
            datasource={
                "params": {
                    "appName": APP,
                    "eventNames": ["like", "dislike"],
                    "ratingMap": {"like": 4.0, "dislike": 1.0},
                }
            }
        )
        from predictionio_tpu.models.recommendation.engine import engine_factory

        engine = engine_factory()
        ep = engine.engine_params_from_variant(variant)
        ds, _, _, _ = engine.make_components(ep)
        td = ds.read_training(ctx(memory_storage))
        assert set(np.unique(td.ratings)) == {1.0, 4.0}

    def test_train_with_view_event_implicit(self, memory_storage):
        from predictionio_tpu.models.recommendation.engine import Query

        self.seed(memory_storage, views=True)
        variant = self.base_variant(
            datasource={
                "params": {
                    "appName": APP,
                    "eventNames": ["view"],
                    "ratingMap": {"view": 1.0},
                }
            },
            algorithms=[
                {
                    "name": "als",
                    "params": {
                        "rank": 8,
                        "numIterations": 8,
                        "lambda": 0.05,
                        "seed": 1,
                        "implicitPrefs": True,
                        "alpha": 1.0,
                    },
                }
            ],
        )
        engine, algos, models, serving = self.make(memory_storage, variant)
        res = algos[0].predict(models[0], Query(user="u0", num=5))
        assert len(res.item_scores) == 5

    def test_variant_files_parse(self):
        import json as _json
        import os

        from predictionio_tpu.models.recommendation.engine import engine_factory

        engine = engine_factory()
        vdir = os.path.join(
            os.path.dirname(
                __import__(
                    "predictionio_tpu.models.recommendation", fromlist=["x"]
                ).__file__
            ),
            "variants",
        )
        files = sorted(os.listdir(vdir))
        assert len(files) == 5
        for f in files:
            with open(os.path.join(vdir, f)) as fh:
                engine.engine_params_from_variant(_json.load(fh))


# ---------------------------------------------------------------------------
# similar-product variants (ref examples/scala-parallel-similarproduct/*)
# ---------------------------------------------------------------------------


class TestSimilarProductVariants:
    def seed(self, storage):
        app_id, levents = seed_app(storage)
        events = []
        # item properties: title/date + categories
        for i in range(10):
            events.append(
                Event(
                    event="$set",
                    entity_type="item",
                    entity_id=f"i{i}",
                    properties=DataMap(
                        {
                            "title": f"Movie {i}",
                            "date": f"199{i % 10}",
                            "imdbUrl": f"http://imdb/{i}",
                            "categories": ["c0" if i < 5 else "c1"],
                        }
                    ),
                )
            )
        rng = np.random.default_rng(0)
        for u in range(16):
            # two taste clusters over items, views + rates
            cluster = range(5) if u % 2 == 0 else range(5, 10)
            for i in cluster:
                events.append(
                    Event(
                        event="view",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                    )
                )
                # two rate events for the same pair: later one must win
                for rating, days in ((2.0, 0), (4.0, 1)):
                    events.append(
                        Event(
                            event="rate",
                            entity_type="user",
                            entity_id=f"u{u}",
                            target_entity_type="item",
                            target_entity_id=f"i{i}",
                            properties=DataMap({"rating": rating}),
                            event_time=__import__("datetime").datetime(
                                2024, 1, 1 + days,
                                tzinfo=__import__("datetime").timezone.utc,
                            ),
                        )
                    )
        levents.insert_batch(events, app_id)

    def make(self, storage, variant):
        from predictionio_tpu.models.similarproduct.engine import engine_factory

        engine = engine_factory()
        ep = engine.engine_params_from_variant(variant)
        models = engine.train(ctx(storage), ep)
        _, _, algos, _ = engine.make_components(ep)
        return algos, models

    def test_return_item_properties(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        self.seed(memory_storage)
        algos, models = self.make(
            memory_storage,
            {
                "datasource": {
                    "params": {
                        "appName": APP,
                        "itemPropertyNames": ["title", "date", "imdbUrl"],
                    }
                },
                "algorithms": [
                    {
                        "name": "als",
                        "params": {"rank": 6, "numIterations": 6, "lambda": 0.05},
                    }
                ],
            },
        )
        res = algos[0].predict(models[0], Query(items=("i0",), num=3))
        assert res.item_scores
        wire = res.to_json_dict()["itemScores"][0]
        # properties are flattened next to item/score like the reference
        assert set(wire) >= {"item", "score", "title", "date", "imdbUrl"}
        assert wire["title"].startswith("Movie ")

    def test_train_with_rate_event_latest_wins(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import engine_factory

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP, "rateEvent": "rate"}},
                "algorithms": [
                    {
                        "name": "rateals",
                        "params": {"rank": 6, "numIterations": 6, "lambda": 0.05},
                    }
                ],
            }
        )
        ds, _, algos, _ = engine.make_components(ep)
        td = ds.read_training(ctx(memory_storage))
        # dedup kept exactly one rating per (user, item), the later 4.0
        assert td.rate_values is not None
        assert np.all(td.rate_values == 4.0)
        pairs = set(zip(td.rate_user_idx.tolist(), td.rate_item_idx.tolist()))
        assert len(pairs) == len(td.rate_user_idx)
        # and the model trains + predicts same-cluster items
        from predictionio_tpu.models.similarproduct.engine import Query

        model = algos[0].train(ctx(memory_storage), td)
        res = algos[0].predict(model, Query(items=("i1",), num=3))
        assert len(res.item_scores) == 3

    def test_properties_survive_checkpoint(self, memory_storage):
        import pickle

        from predictionio_tpu.models.similarproduct.engine import Query

        self.seed(memory_storage)
        algos, models = self.make(
            memory_storage,
            {
                "datasource": {
                    "params": {"appName": APP, "itemPropertyNames": ["title"]}
                },
                "algorithms": [
                    {
                        "name": "als",
                        "params": {"rank": 6, "numIterations": 6, "lambda": 0.05},
                    }
                ],
            },
        )
        model = pickle.loads(pickle.dumps(models[0]))
        res = model and algos[0].predict(model, Query(items=("i0",), num=2))
        assert res.to_json_dict()["itemScores"][0].get("title")


# ---------------------------------------------------------------------------
# e-commerce adjust-score variant
# ---------------------------------------------------------------------------


class TestECommerceAdjustScore:
    def test_weighted_items_scale_scores(self, memory_storage):
        # reuse the e-commerce seed/train helper from TestECommerce
        helper = TestECommerce()
        c, algo, model, app_id = helper.make(
            memory_storage, adjustScore=True, cacheTtlS=0
        )
        from predictionio_tpu.models.ecommerce.engine import Query

        q = Query(user="u0", num=4)
        base = algo.predict_with_context(c, model, q)
        assert len(base.item_scores) >= 2
        # boost the currently-second item via the weightedItems constraint
        second = base.item_scores[1].item
        memory_storage.get_l_events().insert(
            Event(
                event="$set",
                entity_type="constraint",
                entity_id="weightedItems",
                properties=DataMap(
                    {"weights": [{"items": [second], "weight": 100.0}]}
                ),
            ),
            app_id,
        )
        boosted = algo.predict_with_context(c, model, q)
        assert boosted.item_scores[0].item == second


# ---------------------------------------------------------------------------
# recommended-user template
# ---------------------------------------------------------------------------


class TestRecommendedUser:
    def seed(self, storage):
        app_id, levents = seed_app(storage)
        events = []
        # two communities: followers of group A follow a0/a1/a2, B follow b0..b2
        for g, members in (("a", range(8)), ("b", range(8, 16))):
            for m in members:
                for t in range(3):
                    events.append(
                        Event(
                            event="follow",
                            entity_type="user",
                            entity_id=f"u{m}",
                            target_entity_type="user",
                            target_entity_id=f"{g}{t}",
                        )
                    )
        levents.insert_batch(events, app_id)

    def make(self, storage):
        from predictionio_tpu.models.recommendeduser.engine import engine_factory

        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "als",
                        "params": {"rank": 6, "numIterations": 8, "lambda": 0.05},
                    }
                ],
            }
        )
        models = engine.train(ctx(storage), ep)
        _, _, algos, _ = engine.make_components(ep)
        return algos, models

    def test_similar_users_same_community(self, memory_storage):
        from predictionio_tpu.models.recommendeduser.engine import Query

        self.seed(memory_storage)
        algos, models = self.make(memory_storage)
        res = algos[0].predict(models[0], Query(users=("a0",), num=2))
        got = [s.user for s in res.similar_user_scores]
        assert got and all(u.startswith("a") for u in got)
        assert "a0" not in got  # query users excluded

    def test_black_and_white_lists(self, memory_storage):
        from predictionio_tpu.models.recommendeduser.engine import Query

        self.seed(memory_storage)
        algos, models = self.make(memory_storage)
        res = algos[0].predict(
            models[0], Query(users=("a0",), num=3, black_list=frozenset({"a1"}))
        )
        assert "a1" not in {s.user for s in res.similar_user_scores}
        res = algos[0].predict(
            models[0], Query(users=("a0",), num=3, white_list=frozenset({"b0"}))
        )
        assert {s.user for s in res.similar_user_scores} <= {"b0"}

    def test_unknown_users_empty(self, memory_storage):
        from predictionio_tpu.models.recommendeduser.engine import Query

        self.seed(memory_storage)
        algos, models = self.make(memory_storage)
        assert algos[0].predict(models[0], Query(users=("zz",))).similar_user_scores == ()

    def test_wire_format(self, memory_storage):
        from predictionio_tpu.models.recommendeduser.engine import Query

        self.seed(memory_storage)
        algos, models = self.make(memory_storage)
        res = algos[0].predict(models[0], Query(users=("b0", "b1"), num=2))
        wire = res.to_json_dict()
        assert "similarUserScores" in wire
        assert set(wire["similarUserScores"][0]) == {"user", "score"}
