"""Template tests: classification, similar-product, e-commerce, two-tower
(ref per-template engine behaviors in examples/)."""

import numpy as np
import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.workflow.context import WorkflowContext

APP = "tplapp"


def seed_app(storage):
    app_id = storage.get_meta_data_apps().insert(App(0, APP))
    return app_id, storage.get_l_events()


def ctx(storage):
    return WorkflowContext(mode="training", _storage=storage, app_name=APP)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


class TestClassification:
    def seed(self, storage):
        app_id, levents = seed_app(storage)
        rng = np.random.default_rng(0)
        events = []
        for u in range(60):
            plan = float(u % 2)
            # attrs correlate with plan
            base = np.array([3.0, 0.0, 3.0]) if plan else np.array([0.0, 3.0, 0.0])
            attrs = rng.poisson(base + 0.3)
            events.append(
                Event(
                    event="$set",
                    entity_type="user",
                    entity_id=f"u{u}",
                    properties=DataMap(
                        {
                            "plan": plan,
                            "attr0": float(attrs[0]),
                            "attr1": float(attrs[1]),
                            "attr2": float(attrs[2]),
                        }
                    ),
                )
            )
        levents.insert_batch(events, app_id)

    def variant(self, algos):
        return {
            "datasource": {"params": {"appName": APP, "evalK": 3}},
            "algorithms": algos,
        }

    def test_train_and_predict_both_algos(self, memory_storage):
        from predictionio_tpu.models.classification import engine_factory
        from predictionio_tpu.models.classification.engine import Query

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            self.variant(
                [
                    {"name": "naive", "params": {"lambda": 1.0}},
                    {"name": "randomforest", "params": {"numTrees": 5}},
                ]
            )
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        assert len(models) == 2
        _, _, algos, _ = engine.make_components(ep)
        for algo, model in zip(algos, models):
            plan1 = algo.predict(model, Query(4.0, 0.0, 4.0))
            plan0 = algo.predict(model, Query(0.0, 4.0, 0.0))
            assert plan1.label == 1.0
            assert plan0.label == 0.0

    def test_eval_precision(self, memory_storage):
        from predictionio_tpu.eval import AverageMetric, MetricEvaluator
        from predictionio_tpu.models.classification import engine_factory

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            self.variant([{"name": "naive", "params": {}}])
        )

        class Accuracy(AverageMetric):
            def calculate_score(self, ei, q, p, a):
                return 1.0 if p.label == a.label else 0.0

        result = MetricEvaluator(Accuracy()).evaluate_base(
            ctx(memory_storage), engine, [ep]
        )
        assert result.best_score > 0.8  # separable synthetic data


# ---------------------------------------------------------------------------
# similar-product
# ---------------------------------------------------------------------------


class TestSimilarProduct:
    def seed(self, storage):
        app_id, levents = seed_app(storage)
        rng = np.random.default_rng(1)
        events = []
        # two item clusters: users view within one cluster
        for u in range(40):
            cluster = u % 2
            for _ in range(12):
                i = int(rng.integers(0, 10)) + cluster * 10
                events.append(
                    Event(
                        event="view",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                    )
                )
        # item category properties
        for i in range(20):
            events.append(
                Event(
                    event="$set",
                    entity_type="item",
                    entity_id=f"i{i}",
                    properties=DataMap(
                        {"categories": ["even" if i % 2 == 0 else "odd"]}
                    ),
                )
            )
        levents.insert_batch(events, app_id)

    def variant(self, name="als", params=None):
        return {
            "datasource": {"params": {"appName": APP}},
            "algorithms": [
                {"name": name, "params": params or {"rank": 8, "numIterations": 8}}
            ],
        }

    def make(self, memory_storage, name="als", params=None):
        from predictionio_tpu.models.similarproduct import engine_factory

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(self.variant(name, params))
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        _, _, algos, _ = engine.make_components(ep)
        return engine, algos[0], models[0]

    def test_als_similar_items_same_cluster(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        _, algo, model = self.make(memory_storage)
        result = algo.predict(model, Query(items=("i1",), num=5))
        assert len(result.item_scores) == 5
        items = [s.item for s in result.item_scores]
        assert "i1" not in items  # query item excluded
        same_cluster = sum(1 for it in items if int(it[1:]) < 10)
        assert same_cluster >= 4  # mostly same cluster

    def test_filters(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        _, algo, model = self.make(memory_storage)
        r = algo.predict(
            model, Query(items=("i1",), num=10, white_list=frozenset({"i2", "i3"}))
        )
        assert {s.item for s in r.item_scores} <= {"i2", "i3"}
        r = algo.predict(
            model, Query(items=("i1",), num=10, black_list=frozenset({"i2"}))
        )
        assert "i2" not in {s.item for s in r.item_scores}
        r = algo.predict(
            model, Query(items=("i1",), num=10, categories=frozenset({"even"}))
        )
        assert all(int(s.item[1:]) % 2 == 0 for s in r.item_scores)
        r = algo.predict(
            model,
            Query(items=("i1",), num=10, category_black_list=frozenset({"even"})),
        )
        assert all(int(s.item[1:]) % 2 == 1 for s in r.item_scores)

    def test_unknown_query_items(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        _, algo, model = self.make(memory_storage)
        assert algo.predict(model, Query(items=("ghost",), num=5)).item_scores == ()

    def test_cooccurrence_algorithm(self, memory_storage):
        from predictionio_tpu.models.similarproduct.engine import Query

        _, algo, model = self.make(memory_storage, name="cooccurrence", params={"n": 10})
        result = algo.predict(model, Query(items=("i1",), num=5))
        assert len(result.item_scores) > 0
        items = [int(s.item[1:]) for s in result.item_scores]
        assert all(i < 10 for i in items)  # cooccur within cluster
        # scores are integer counts summed
        assert all(s.score >= 1 for s in result.item_scores)


# ---------------------------------------------------------------------------
# e-commerce
# ---------------------------------------------------------------------------


class TestECommerce:
    def seed(self, storage):
        app_id, levents = seed_app(storage)
        rng = np.random.default_rng(2)
        events = []
        for u in range(30):
            cluster = u % 2
            for _ in range(10):
                i = int(rng.integers(0, 8)) + cluster * 8
                events.append(
                    Event(
                        event="rate",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties=DataMap({"rating": float(rng.integers(3, 6))}),
                    )
                )
        # buys make i0 the most popular
        for u in range(10):
            events.append(
                Event(
                    event="buy",
                    entity_type="user",
                    entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id="i0",
                )
            )
        levents.insert_batch(events, app_id)
        return app_id

    def make(self, memory_storage, **extra):
        from predictionio_tpu.models.ecommerce import engine_factory

        app_id = self.seed(memory_storage)
        engine = engine_factory()
        params = {
            "appName": APP,
            "unseenOnly": False,
            "seenEvents": ["buy", "view"],
            "similarEvents": ["view"],
            "rank": 8,
            "numIterations": 8,
            **extra,
        }
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP}},
                "algorithms": [{"name": "ecomm", "params": params}],
            }
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        _, _, algos, _ = engine.make_components(ep)
        return c, algos[0], models[0], app_id

    def test_known_user(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, _ = self.make(memory_storage)
        r = algo.predict_with_context(c, model, Query(user="u0", num=4))
        assert len(r.item_scores) == 4

    def test_cold_user_popularity_fallback(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, _ = self.make(memory_storage)
        r = algo.predict_with_context(c, model, Query(user="stranger", num=3))
        assert r.item_scores[0].item == "i0"  # most-bought item first

    def test_cold_user_recent_views(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, app_id = self.make(memory_storage)
        # new user views items in cluster 1
        memory_storage.get_l_events().insert_batch(
            [
                Event(
                    event="view",
                    entity_type="user",
                    entity_id="newbie",
                    target_entity_type="item",
                    target_entity_id=f"i{8 + j}",
                )
                for j in range(3)
            ],
            app_id,
        )
        r = algo.predict_with_context(c, model, Query(user="newbie", num=5))
        in_cluster = sum(1 for s in r.item_scores if int(s.item[1:]) >= 8)
        assert in_cluster >= 3

    def test_unseen_only_filters_seen(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, app_id = self.make(memory_storage, unseenOnly=True)
        # u0 bought i0 (seeded); with unseenOnly the result must omit i0
        r = algo.predict_with_context(c, model, Query(user="u0", num=16))
        assert "i0" not in {s.item for s in r.item_scores}

    def test_unavailable_items_constraint(self, memory_storage):
        from predictionio_tpu.models.ecommerce.engine import Query

        c, algo, model, app_id = self.make(memory_storage)
        memory_storage.get_l_events().insert(
            Event(
                event="$set",
                entity_type="constraint",
                entity_id="unavailableItems",
                properties=DataMap({"items": ["i1", "i2"]}),
            ),
            app_id,
        )
        r = algo.predict_with_context(c, model, Query(user="u0", num=16))
        assert {"i1", "i2"} & {s.item for s in r.item_scores} == set()


# ---------------------------------------------------------------------------
# two-tower
# ---------------------------------------------------------------------------


class TestTwoTower:
    def seed(self, storage):
        app_id, levents = seed_app(storage)
        rng = np.random.default_rng(3)
        events = []
        for u in range(24):
            cluster = u % 2
            for _ in range(10):
                i = int(rng.integers(0, 6)) + cluster * 6
                events.append(
                    Event(
                        event="view",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                    )
                )
        levents.insert_batch(events, app_id)

    def test_train_and_retrieve(self, memory_storage):
        from predictionio_tpu.models.twotower import engine_factory
        from predictionio_tpu.models.twotower.engine import Query

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "twotower",
                        "params": {
                            "embedDim": 16,
                            "hidden": [32],
                            "outDim": 8,
                            "epochs": 30,
                            "batchSize": 64,
                            "mesh": "data=4,model=2",
                        },
                    }
                ],
            }
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        model = models[0]
        # loss decreased
        assert model.losses[-1] < model.losses[0]
        _, _, algos, _ = engine.make_components(ep)
        algo = algos[0]
        r = algo.predict(model, Query(user="u0", num=4))
        assert len(r.item_scores) == 4
        # in-cluster retrieval dominates
        in_cluster = sum(1 for s in r.item_scores if int(s.item[1:]) < 6)
        assert in_cluster >= 3
        # unknown user -> empty
        assert algo.predict(model, Query(user="ghost")).item_scores == ()

    def test_model_checkpoint_roundtrip(self, memory_storage):
        from predictionio_tpu.controller import model_to_host
        from predictionio_tpu.models.twotower import engine_factory
        from predictionio_tpu.models.twotower.engine import Query
        from predictionio_tpu.workflow import model_io

        self.seed(memory_storage)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": APP}},
                "algorithms": [
                    {
                        "name": "twotower",
                        "params": {"embedDim": 8, "hidden": [16], "outDim": 8,
                                   "epochs": 2, "batchSize": 32},
                    }
                ],
            }
        )
        c = ctx(memory_storage)
        models = engine.train(c, ep)
        blob = model_io.serialize_models(
            engine.make_serializable_models(c, ep, models)
        )
        (restored,) = model_io.deserialize_models(blob)
        _, _, algos, _ = engine.make_components(ep)
        r1 = algos[0].predict(models[0], Query(user="u1", num=3))
        r2 = algos[0].predict(restored, Query(user="u1", num=3))
        assert [s.item for s in r1.item_scores] == [s.item for s in r2.item_scores]
