"""Fleet flight recorder tests (ISSUE 11, docs/observability.md).

Covers the three connected pieces at unit + integration tiers:

- the durable telemetry ring (``obs/tsring.py``): bounded rotation,
  crash-safe resume (torn trailing lines skipped, seq continues),
  window/tail queries — the on-disk history ``pio top --history`` and
  incident bundles read;
- the incident recorder (``obs/incidents.py``): content-addressed
  atomic bundles, per-kind rate limiting, failing sources recorded not
  fatal, GC, list/show/export plumbing;
- worker log capture (``fleet/worklog.py``): spawn with captured
  stderr/stdout, rotation at respawn, rotation-aware tails;
- the gateway's cross-tier tracing + telemetry loop: ``gateway.route``/
  ``gateway.proxy`` spans on the ingress trace id (retry + panic
  attribution), the fan-in merged ``/traces/recent`` (incl. the dead-
  replica span cache), ``/telemetry/window`` over a ring that SURVIVES
  a gateway restart, the fleet SLO engine, and the incident triggers
  (5xx escape, breaker trip, SLO alert transition);
- trace-id continuity end to end: client -> gateway retry on a second
  replica -> REAL QueryServer micro-batcher -> storage span, one trace
  id throughout, both tiers visible in the merged view, and a federated
  exemplar scrape resolving to that assembled waterfall.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig
from predictionio_tpu.fleet.launch import build_obs_plane, wire_incident_sources
from predictionio_tpu.fleet.supervisor import (
    Supervisor,
    SupervisorConfig,
    WorkerSpec,
)
from predictionio_tpu.fleet.worklog import WorkerLogBook, spawn_with_log
from predictionio_tpu.obs.incidents import (
    IncidentRecorder,
    export_bundle,
    list_bundles,
    load_bundle,
)
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tracing import TRACE_HEADER, mint_trace_id
from predictionio_tpu.obs.tsring import TelemetryRing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if "tests" not in sys.path:
    sys.path.insert(0, "tests")


# ---------------------------------------------------------------------------
# telemetry ring
# ---------------------------------------------------------------------------


class TestTelemetryRing:
    def test_append_read_roundtrip(self, tmp_path):
        ring = TelemetryRing(str(tmp_path), segment_records=4, segments=3)
        for i in range(3):
            ring.append({"gauges": {"queue_depth": float(i)}})
        recs = ring.records()
        assert [r["seq"] for r in recs] == [0, 1, 2]
        assert all("t" in r for r in recs)
        assert recs[2]["gauges"]["queue_depth"] == 2.0

    def test_ring_is_bounded_and_drops_oldest(self, tmp_path):
        ring = TelemetryRing(str(tmp_path), segment_records=4, segments=3)
        for i in range(50):
            ring.append({"i": i})
        recs = ring.records()
        # capacity is segments*segment_records minus the rotated-away
        # partials; the INVARIANTS are the bound and oldest-first loss
        assert len(recs) <= 12
        assert recs[-1]["seq"] == 49
        assert recs[0]["seq"] > 0
        files = [f for f in os.listdir(tmp_path) if f.startswith("seg-")]
        assert len(files) <= 3

    def test_resume_continues_sequence_after_reopen(self, tmp_path):
        ring = TelemetryRing(str(tmp_path), segment_records=4, segments=3)
        for i in range(6):
            ring.append({"i": i})
        ring.close()
        # "gateway restart": a NEW ring instance over the same directory
        ring2 = TelemetryRing(str(tmp_path), segment_records=4, segments=3)
        pre_restart = [r["seq"] for r in ring2.records()]
        assert 5 in pre_restart, "pre-restart history must survive"
        seq = ring2.append({"i": 6})
        assert seq == 6  # monotonic across the restart
        assert ring2.records()[-1]["i"] == 6

    def test_torn_trailing_line_is_skipped_not_fatal(self, tmp_path):
        ring = TelemetryRing(str(tmp_path), segment_records=8, segments=2)
        for i in range(3):
            ring.append({"i": i})
        ring.close()
        seg = sorted(
            f for f in os.listdir(tmp_path) if f.startswith("seg-")
        )[0]
        with open(tmp_path / seg, "a") as fh:
            fh.write('{"seq": 99, "torn')  # crashed-writer tail
        ring2 = TelemetryRing(str(tmp_path), segment_records=8, segments=2)
        assert [r["seq"] for r in ring2.records()] == [0, 1, 2]
        assert ring2.append({"i": 3}) == 3

    def test_window_filters_on_time(self, tmp_path):
        ring = TelemetryRing(str(tmp_path))
        ring.append({"t": 100.0, "i": 0})
        ring.append({"t": 200.0, "i": 1})
        ring.append({"t": 290.0, "i": 2})
        got = ring.window(seconds=120, now=300.0)
        assert [r["i"] for r in got] == [1, 2]
        assert ring.window(seconds=1000, now=300.0) == ring.records()

    def test_tail_and_approx_count(self, tmp_path):
        ring = TelemetryRing(str(tmp_path), segment_records=4, segments=2)
        for i in range(5):
            ring.append({"i": i})
        assert [r["i"] for r in ring.tail(2)] == [3, 4]
        assert ring.approx_count == 5
        for i in range(20):
            ring.append({"i": i})
        assert ring.approx_count == 8  # clamped to capacity


# ---------------------------------------------------------------------------
# incident recorder
# ---------------------------------------------------------------------------


class TestIncidentRecorder:
    def _recorder(self, tmp_path, **kw):
        self.clock = [0.0]
        kw.setdefault("clock", lambda: self.clock[0])
        return IncidentRecorder(
            str(tmp_path), metrics=MetricsRegistry(), **kw
        )

    def test_bundle_contains_manifest_parts_and_texts(self, tmp_path):
        rec = self._recorder(tmp_path)
        rec.add_source("fleet", lambda: {"replicas": 2})
        path = rec.trigger(
            "worker-crash",
            context={"replica": "w1", "rc": -9},
            texts={"stderr_tail": "Fatal: device lost\n"},
        )
        assert path is not None and os.path.isdir(path)
        manifest = json.load(open(os.path.join(path, "manifest.json")))
        assert manifest["trigger"] == "worker-crash"
        assert manifest["context"]["replica"] == "w1"
        assert manifest["parts"] == ["fleet"]
        assert json.load(open(os.path.join(path, "fleet.json"))) == {
            "replicas": 2
        }
        tail = open(os.path.join(path, "stderr_tail.txt")).read()
        assert "device lost" in tail
        # content-addressed: the manifest's sha prefix names the dir
        assert manifest["sha256"][:12] in os.path.basename(path)

    def test_failing_source_recorded_not_fatal(self, tmp_path):
        rec = self._recorder(tmp_path)
        rec.add_source("boom", lambda: 1 / 0)
        rec.add_source("ok", lambda: [1, 2])
        path = rec.trigger("slo-alert")
        bundle = load_bundle(str(tmp_path), os.path.basename(path))
        assert bundle["parts"]["ok"] == [1, 2]
        assert "ZeroDivisionError" in bundle["parts"]["boom"]["error"]

    def test_rate_limit_is_per_kind(self, tmp_path):
        rec = self._recorder(tmp_path, min_interval_s=10.0)
        assert rec.trigger("worker-crash") is not None
        assert rec.trigger("worker-crash") is None  # limited
        assert rec.trigger("breaker-trip") is not None  # different kind
        self.clock[0] = 11.0
        assert rec.trigger("worker-crash") is not None  # window passed

    def test_gc_keeps_newest(self, tmp_path):
        rec = self._recorder(tmp_path, min_interval_s=0.0, max_bundles=3)
        for i in range(6):
            self.clock[0] = float(i)
            rec.trigger("worker-crash", context={"n": i})
        refs = list_bundles(str(tmp_path))
        assert len(refs) == 3
        kept = [
            json.load(open(os.path.join(r.path, "manifest.json")))["context"]["n"]
            for r in refs
        ]
        assert kept == [3, 4, 5]

    def test_list_load_export_with_prefix(self, tmp_path):
        rec = self._recorder(tmp_path)
        path = rec.trigger("fleet-5xx", context={"status": 502})
        ref = list_bundles(str(tmp_path))[0]
        assert ref.trigger == "fleet-5xx"
        # unique sha prefix resolves like a git short hash
        sha_prefix = os.path.basename(path).rsplit("-", 1)[1][:8]
        bundle = load_bundle(str(tmp_path), ref.bundle_id)
        assert bundle["manifest"]["context"]["status"] == 502
        dest = tmp_path / "export"
        os.makedirs(dest)
        out = export_bundle(str(tmp_path), ref.bundle_id, str(dest))
        assert os.path.isfile(os.path.join(out, "manifest.json"))
        assert sha_prefix in out

    def test_trigger_never_raises(self, tmp_path):
        rec = self._recorder(tmp_path)
        blocker = tmp_path / "blocker"
        blocker.write_text("not a directory")
        rec.dir = str(blocker)  # capture will fail to mkdir under a file
        assert rec.trigger("worker-crash") is None  # swallowed, not raised


# ---------------------------------------------------------------------------
# worker log capture
# ---------------------------------------------------------------------------


class TestWorkerLogBook:
    def test_spawn_with_log_captures_stderr_and_stdout(self, tmp_path):
        book = WorkerLogBook(str(tmp_path))
        proc = spawn_with_log(
            [
                sys.executable,
                "-c",
                "import sys; print('out line'); print('err line', file=sys.stderr)",
            ],
            book,
            "w0",
        )
        proc.wait(timeout=30)
        tail = book.tail("w0")
        assert "out line" in tail and "err line" in tail

    def test_rotation_at_open_bounds_the_file(self, tmp_path):
        book = WorkerLogBook(str(tmp_path), max_bytes=64)
        with book.open_for("w0") as fh:
            fh.write(b"A" * 100 + b"\n")
        # respawn: the oversized generation shifts to .1, fresh file opens
        with book.open_for("w0") as fh:
            fh.write(b"B" * 10 + b"\n")
        assert os.path.getsize(book.path("w0")) < 64
        assert os.path.exists(book.rotated_path("w0"))
        tail = book.tail("w0", max_bytes=200)
        assert "B" * 10 in tail
        assert "A" in tail  # rotation-aware: reaches into .1 for the gap

    def test_tail_missing_worker_is_empty(self, tmp_path):
        book = WorkerLogBook(str(tmp_path))
        assert book.tail("ghost") == ""


# ---------------------------------------------------------------------------
# supervisor crash capture -> incident hook
# ---------------------------------------------------------------------------


class _FakeProc:
    def __init__(self):
        self.pid = 4242
        self.rc = None

    def poll(self):
        return self.rc

    def terminate(self):
        self.rc = -15

    def kill(self):
        self.rc = -9


class TestSupervisorCrashCapture:
    def _sup(self, tmp_path, on_crash, budget=5):
        self.clock = [0.0]
        self.procs = []

        def spawn(spec):
            p = _FakeProc()
            self.procs.append(p)
            return p

        book = WorkerLogBook(str(tmp_path / "logs"))
        sup = Supervisor(
            spawn,
            [WorkerSpec("w0", 9001)],
            SupervisorConfig(crash_loop_budget=budget, crash_loop_window_s=60.0),
            metrics=MetricsRegistry(),
            clock=lambda: self.clock[0],
            logbook=book,
            on_crash=on_crash,
        )
        return sup, book

    def test_crash_hands_stderr_tail_to_hook(self, tmp_path):
        crashes = []
        sup, book = self._sup(tmp_path, crashes.append)
        sup.start()
        with book.open_for("w0") as fh:
            fh.write(b"Traceback: boom\n")
        self.procs[-1].rc = 1
        sup.tick()
        assert len(crashes) == 1
        info = crashes[0]
        assert info["replica"] == "w0" and info["rc"] == 1
        assert not info["parked"]
        assert "boom" in info["stderrTail"]
        assert info["logPath"].endswith("w0.log")

    def test_park_reported_as_parked(self, tmp_path):
        crashes = []
        sup, _ = self._sup(tmp_path, crashes.append, budget=1)
        sup.start()
        for i in range(3):
            self.clock[0] += 0.1
            if self.procs and self.procs[-1].rc is None:
                self.procs[-1].rc = 1
            sup.tick()
            self.clock[0] += 10.0
            sup.tick()
        assert any(c["parked"] for c in crashes)
        assert sup.snapshot()[0]["parked"]

    def test_hook_failure_never_stalls_restarts(self, tmp_path):
        def bad_hook(info):
            raise RuntimeError("recorder down")

        sup, _ = self._sup(tmp_path, bad_hook)
        sup.start()
        self.procs[-1].rc = 1
        sup.tick()  # must not raise
        self.clock[0] += 60.0
        sup.tick()  # restart still happens
        assert len(self.procs) == 2

    def test_snapshot_and_metric_carry_log_path(self, tmp_path):
        sup, book = self._sup(tmp_path, None)
        sup.start()
        assert sup.snapshot()[0]["logPath"] == book.path("w0")
        text = sup.metrics.render_prometheus()
        assert "pio_fleet_worker_log_info" in text
        assert "w0.log" in text


# ---------------------------------------------------------------------------
# gateway: spans, merged traces, telemetry, incidents
# ---------------------------------------------------------------------------


class FakeObsReplica:
    """A replica with the observability surface the gateway fans into:
    /queries.json (optionally failing), /healthz, /metrics (fixed
    exposition incl. a queue-depth gauge and an exemplar-decorated
    histogram), /traces/recent (its own span list)."""

    def __init__(self, name: str):
        self.name = name
        self.fail_status: int | None = None
        self.queries = 0
        self.spans: list[dict] = []
        self.server: TestServer | None = None

    def make_app(self) -> web.Application:
        app = web.Application()

        async def queries(request: web.Request) -> web.Response:
            self.queries += 1
            tid = request.headers.get(TRACE_HEADER, "")
            self.spans.append(
                {
                    "traceId": tid,
                    "spanId": f"{self.name}-{self.queries}",
                    "name": "ingress",
                    "kind": "ingress",
                    "startTime": time.time(),
                    "durationMs": 1.0,
                    "status": "ok",
                    "tags": {},
                }
            )
            if self.fail_status:
                return web.json_response({"m": "injected"}, status=self.fail_status)
            return web.json_response({"replica": self.name})

        async def healthz(request):
            return web.json_response({"ready": True})

        async def metrics(request):
            exemplar = ""
            if request.query.get("exemplars"):
                exemplar = ' # {trace_id="exemplar-tid"} 0.004'
            return web.Response(
                text=(
                    "pio_queue_depth 3\n"
                    "# TYPE pio_request_seconds histogram\n"
                    'pio_request_seconds_bucket{endpoint="/queries.json",le="0.01"} 5'
                    + exemplar
                    + "\n"
                    'pio_request_seconds_bucket{endpoint="/queries.json",le="+Inf"} 8\n'
                    'pio_request_seconds_count{endpoint="/queries.json"} 8\n'
                )
            )

        async def traces(request):
            return web.json_response({"spans": self.spans})

        app.add_routes(
            [
                web.post("/queries.json", queries),
                web.get("/healthz", healthz),
                web.get("/metrics", metrics),
                web.get("/traces/recent", traces),
            ]
        )
        return app

    async def start(self) -> str:
        self.server = TestServer(self.make_app())
        await self.server.start_server()
        return f"http://127.0.0.1:{self.server.port}"

    async def stop(self) -> None:
        if self.server is not None:
            await self.server.close()
            self.server = None


def _gw_rig(tmp_path, n=2, **cfg_kw):
    replicas = [FakeObsReplica(f"r{i}") for i in range(n)]

    def run(body, telemetry=True, incidents_interval=0.0):
        async def outer():
            urls = [await r.start() for r in replicas]
            cfg_kw.setdefault("probe_interval_s", 0.05)
            cfg_kw.setdefault("telemetry_interval_s", 0.05)
            cfg_kw.setdefault("request_timeout_s", 5.0)
            metrics = MetricsRegistry()
            ring = (
                TelemetryRing(str(tmp_path / "telemetry"))
                if telemetry
                else None
            )
            recorder = IncidentRecorder(
                str(tmp_path / "incidents"),
                metrics=metrics,
                min_interval_s=incidents_interval,
            )
            gw = Gateway(
                GatewayConfig(replica_urls=tuple(urls), **cfg_kw),
                metrics=metrics,
                telemetry=ring,
                incidents=recorder,
            )
            client = TestClient(TestServer(gw.make_app()))
            await client.start_server()
            try:
                await asyncio.sleep(0.12)  # first probe + telemetry ticks
                await body(gw, client, recorder, ring)
            finally:
                await client.close()
                for r in replicas:
                    await r.stop()
                if ring is not None:
                    ring.close()

        asyncio.run(outer())

    return replicas, run


class TestGatewaySpans:
    def test_route_and_proxy_spans_share_ingress_trace_id(self, tmp_path):
        replicas, run = _gw_rig(tmp_path)
        tid = mint_trace_id()

        async def body(gw, client, recorder, ring):
            resp = await client.post(
                "/queries.json",
                json={"user": "u1"},
                headers={TRACE_HEADER: tid},
            )
            assert resp.status == 200
            assert resp.headers[TRACE_HEADER] == tid
            spans = gw.tracer.find(tid)
            names = [s["name"] for s in spans]
            assert "gateway.route" in names and "gateway.proxy" in names
            route = next(s for s in spans if s["name"] == "gateway.route")
            assert route["tags"]["status"] == 200
            assert route["tags"]["replica"]
            assert route["tags"]["healthy"] == 2
            proxy = next(s for s in spans if s["name"] == "gateway.proxy")
            assert proxy["tags"]["upstream_status"] == 200
            assert proxy["durationMs"] >= 0

        run(body)

    def test_retry_attribution_lands_in_route_span(self, tmp_path):
        replicas, run = _gw_rig(tmp_path)
        tid = mint_trace_id()

        async def body(gw, client, recorder, ring):
            replicas[0].fail_status = 500
            replicas[1].fail_status = None
            # hammer until a request lands on the failing replica first
            # (sticky hashing may pick either first)
            retried = None
            for i in range(16):
                t = f"{tid}{i:02d}"
                resp = await client.post(
                    "/queries.json",
                    json={"user": f"u{i}"},
                    headers={TRACE_HEADER: t},
                )
                assert resp.status == 200  # retry always rescues
                spans = gw.tracer.find(t)
                route = next(
                    s for s in spans if s["name"] == "gateway.route"
                )
                if route["tags"].get("retried"):
                    retried = route
                    # BOTH forward attempts recorded on the same trace
                    proxies = [
                        s for s in spans if s["name"] == "gateway.proxy"
                    ]
                    assert len(proxies) == 2
                    assert {p["tags"]["upstream_status"] for p in proxies} == {
                        500,
                        200,
                    }
                    break
            assert retried is not None, "no request ever hit the bad replica"
            assert retried["tags"]["retry_replica"]
            # SLO semantics: the retry RESCUED every client — the
            # per-attempt forwards recorded 5xx, but the client-visible
            # response counter (the fleet-availability input) must not
            responses = {
                dict(zip(gw._m_responses.labelnames, k))["status"]: v
                for k, v in gw._m_responses.collect()
            }
            attempts = {
                dict(zip(gw._m_requests.labelnames, k)).get("status"): v
                for k, v in gw._m_requests.collect()
            }
            assert attempts.get("5xx", 0) > 0  # the failures happened...
            assert responses.get("5xx", 0) == 0  # ...but no client saw one

        run(body)

    def test_merged_traces_and_dead_replica_cache(self, tmp_path):
        replicas, run = _gw_rig(tmp_path)
        tid = mint_trace_id()

        async def body(gw, client, recorder, ring):
            resp = await client.post(
                "/queries.json",
                json={"user": "u1"},
                headers={TRACE_HEADER: tid},
            )
            assert resp.status == 200
            # wait for a telemetry tick to cache the replica spans
            await asyncio.sleep(0.12)
            # the merged view holds both tiers for the trace id
            t = await client.get(f"/traces/recent?trace_id={tid}")
            spans = (await t.json())["spans"]
            sources = {s["source"] for s in spans}
            assert "gateway" in sources
            assert any(src != "gateway" for src in sources)
            # the waterfall is time-ordered oldest-first
            starts = [s["startTime"] for s in spans]
            assert starts == sorted(starts)
            # SIGKILL analog: stop the replica that served the query;
            # its spans must STILL be served (from the fan-in cache)
            for r in replicas:
                await r.stop()
            t = await client.get(f"/traces/recent?trace_id={tid}")
            spans = (await t.json())["spans"]
            assert any(
                s["kind"] == "ingress" and s["source"] != "gateway"
                for s in spans
            ), "dead replica's spans evaporated from the merged view"

        run(body)

    def test_federated_exemplar_resolves_cross_tier(self, tmp_path):
        """Acceptance: scrape the GATEWAY with exemplars negotiated; the
        federated exposition still carries the replica's exemplar
        clause, and the trace id it names assembles into a waterfall via
        the gateway's /traces/recent."""
        replicas, run = _gw_rig(tmp_path)

        async def body(gw, client, recorder, ring):
            # seed the replica span rings with the exemplar's trace id
            resp = await client.post(
                "/queries.json",
                json={"user": "u1"},
                headers={TRACE_HEADER: "exemplar-tid"},
            )
            assert resp.status == 200
            scrape = await client.get("/metrics?exemplars=1")
            text = await scrape.text()
            assert "openmetrics" in scrape.headers["Content-Type"]
            assert text.rstrip().endswith("# EOF")
            line = next(
                ln
                for ln in text.splitlines()
                if ln.startswith("pio_request_seconds_bucket") and " # " in ln
            )
            exemplar_tid = line.split('trace_id="')[1].split('"')[0]
            assert exemplar_tid == "exemplar-tid"
            # ... and the plain scrape stays strict v0.0.4
            plain = await (await client.get("/metrics")).text()
            assert " # " not in plain and "# EOF" not in plain
            # the exemplar resolves through the merged trace view
            t = await client.get(f"/traces/recent?trace_id={exemplar_tid}")
            spans = (await t.json())["spans"]
            assert any(s["name"] == "gateway.route" for s in spans)
            assert any(s["source"] != "gateway" for s in spans)

        run(body)

    def test_health_transitions_recorded_as_spans(self, tmp_path):
        replicas, run = _gw_rig(tmp_path)

        async def body(gw, client, recorder, ring):
            await replicas[0].stop()
            await asyncio.sleep(0.2)  # probe ejects
            events = [
                s
                for s in gw.tracer.recent(None)
                if s["name"] == "gateway.health"
            ]
            assert any(s["status"] == "eject" for s in events)

        run(body)


class TestGatewayTelemetry:
    def test_ring_snapshots_and_window_endpoint(self, tmp_path):
        replicas, run = _gw_rig(tmp_path)

        async def body(gw, client, recorder, ring):
            await client.post("/queries.json", json={"user": "u"})
            await asyncio.sleep(0.15)
            resp = await client.get("/telemetry/window?s=60")
            assert resp.status == 200
            records = (await resp.json())["records"]
            assert records, "telemetry loop appended nothing"
            last = records[-1]
            # federated gauge: 3 queue depth per replica
            assert last["gauges"]["queue_depth"] == 6.0
            assert set(last["replicas"]) == {r.name for r in gw.replicas}
            assert "fleet-availability" in last["slo"]
            assert last["counters"]["requests"] >= 1.0
            text = gw.metrics.render_prometheus()
            assert "pio_telemetry_snapshots_total" in text

        run(body)

    def test_ring_survives_gateway_restart(self, tmp_path):
        """Acceptance: the on-disk ring outlives the process — a NEW
        gateway over the same directory serves the pre-restart window,
        and `pio top --history` renders it."""
        replicas, run = _gw_rig(tmp_path)

        async def body(gw, client, recorder, ring):
            await asyncio.sleep(0.2)
            assert ring.approx_count > 0

        run(body)
        # "restart": fresh ring instance + fresh gateway over the same dir
        ring2 = TelemetryRing(str(tmp_path / "telemetry"))
        pre = ring2.records()
        assert pre, "history did not survive the restart"
        from predictionio_tpu.tools.top import render_history, run_history

        screen = render_history(ring2.window(3600), 3600)
        assert "queue" in screen and "burn" in screen
        out: list[str] = []
        rc = run_history(
            obs_dir=str(tmp_path), window_s=3600, out=out.append
        )
        assert rc == 0
        assert "snapshots" in out[0]
        ring2.close()

    def test_telemetry_window_bad_param_400s(self, tmp_path):
        replicas, run = _gw_rig(tmp_path)

        async def body(gw, client, recorder, ring):
            resp = await client.get("/telemetry/window?s=banana")
            assert resp.status == 400

        run(body)

    def test_fleet_slo_endpoint_reports_objectives(self, tmp_path):
        replicas, run = _gw_rig(tmp_path)

        async def body(gw, client, recorder, ring):
            resp = await client.get("/slo")
            names = {s["name"] for s in (await resp.json())["slos"]}
            assert names == {
                "fleet-availability",
                "fleet-latency",
                "fleet-shed",
            }

        run(body)


async def _await_bundle(inc_dir: str, trigger: str, deadline_s: float = 5.0):
    """Captures run on an executor thread (never on the event loop — the
    gateway must keep proxying mid-incident), so tests poll."""
    deadline = time.monotonic() + deadline_s
    while True:
        refs = [b for b in list_bundles(inc_dir) if b.trigger == trigger]
        if refs:
            return refs
        assert time.monotonic() < deadline, f"no {trigger} bundle appeared"
        await asyncio.sleep(0.05)


class TestGatewayIncidents:
    def test_escaped_5xx_triggers_bundle(self, tmp_path):
        replicas, run = _gw_rig(tmp_path)

        async def body(gw, client, recorder, ring):
            for r in replicas:
                r.fail_status = 500
            resp = await client.post("/queries.json", json={"user": "u"})
            assert resp.status == 500  # relayed, not masked
            await _await_bundle(str(tmp_path / "incidents"), "fleet-5xx")

        run(body)

    def test_breaker_trip_triggers_bundle(self, tmp_path):
        replicas, run = _gw_rig(tmp_path, breaker_threshold=2)

        async def body(gw, client, recorder, ring):
            replicas[0].fail_status = 503
            replicas[1].fail_status = 503
            for i in range(8):
                await client.post("/queries.json", json={"user": f"u{i}"})
            await _await_bundle(str(tmp_path / "incidents"), "breaker-trip")

        run(body)

    def test_slo_alert_transition_triggers_bundle(self, tmp_path):
        replicas, run = _gw_rig(tmp_path)

        async def body(gw, client, recorder, ring):
            # force an alert: every window of every objective breaching is
            # simulated by monkeying the engine's evaluate output
            orig = gw.slo.evaluate

            def alerting_evaluate(now=None):
                out = orig(now)
                for rpt in out:
                    rpt["alerting"] = True
                return out

            gw.slo.evaluate = alerting_evaluate
            await _await_bundle(str(tmp_path / "incidents"), "slo-alert")
            # let the SAME tick's captures (one per flipping objective)
            # settle on the executor before counting
            await asyncio.sleep(0.3)
            n = len(
                [
                    b
                    for b in list_bundles(str(tmp_path / "incidents"))
                    if b.trigger == "slo-alert"
                ]
            )
            # level-triggered refiring is suppressed: alert state latched,
            # several more still-alerting ticks must add no bundles
            await asyncio.sleep(0.3)
            refs = list_bundles(str(tmp_path / "incidents"))
            assert (
                len([b for b in refs if b.trigger == "slo-alert"]) == n
            ), "alert incident re-fired while still alerting"

        run(body)


# ---------------------------------------------------------------------------
# launch wiring: build_obs_plane + incident sources
# ---------------------------------------------------------------------------


class TestObsPlaneWiring:
    def test_disabled_when_no_dir(self):
        assert build_obs_plane("", MetricsRegistry()) == {}
        assert build_obs_plane(None, MetricsRegistry()) == {}

    def test_plane_pieces_and_crash_capture(self, tmp_path):
        metrics = MetricsRegistry()
        obs = build_obs_plane(str(tmp_path / "obs"), metrics)
        assert set(obs) == {
            "dir",
            "logbook",
            "telemetry",
            "incidents",
            "on_crash",
        }
        obs["on_crash"](
            {
                "replica": "w0",
                "rc": -9,
                "parked": False,
                "stderrTail": "dying words\n",
            }
        )
        refs = list_bundles(str(tmp_path / "obs" / "incidents"))
        assert refs and refs[0].trigger == "worker-crash"
        bundle = load_bundle(
            str(tmp_path / "obs" / "incidents"), refs[0].bundle_id
        )
        assert "dying words" in bundle["texts"]["stderr_tail"]
        # telemetry tail source captured (empty ring -> empty list)
        assert bundle["parts"]["telemetry"] == []

    def test_wire_incident_sources_captures_both_tiers(self, tmp_path):
        metrics = MetricsRegistry()
        obs = build_obs_plane(str(tmp_path / "obs"), metrics)
        gw = Gateway(
            GatewayConfig(replica_urls=("http://127.0.0.1:1",)),
            metrics=metrics,
            telemetry=obs["telemetry"],
            incidents=obs["incidents"],
        )
        sup = Supervisor(
            spawn=lambda spec: _FakeProc(),
            specs=[WorkerSpec("w0", 9001)],
            metrics=metrics,
            logbook=obs["logbook"],
            on_crash=obs["on_crash"],
        )
        wire_incident_sources(obs["incidents"], gw, sup)
        gw.tracer.record_span("gateway.route", "gateway", 0.01)
        path = obs["incidents"].trigger("breaker-trip", context={"b": "r0"})
        bundle = load_bundle(
            str(tmp_path / "obs" / "incidents"), os.path.basename(path)
        )
        assert {"traces", "fleet", "supervisor", "telemetry"} <= set(
            bundle["parts"]
        )
        assert any(
            s["name"] == "gateway.route" for s in bundle["parts"]["traces"]
        )
        assert bundle["parts"]["supervisor"][0]["name"] == "w0"


# ---------------------------------------------------------------------------
# trace continuity: client -> gateway retry -> REAL server -> storage span
# ---------------------------------------------------------------------------


class TestTraceContinuityE2E:
    def test_one_trace_id_client_to_storage_through_retry(
        self, memory_storage
    ):
        """Satellite acceptance: a query that fails on the first replica,
        retries onto a REAL QueryServer (micro-batcher + storage read),
        keeps ONE trace id end to end — and the gateway's merged
        /traces/recent shows the gateway AND replica tiers for it."""
        from predictionio_tpu.data.storage.traced import trace_dao
        from predictionio_tpu.obs.tracing import get_tracer
        from tests.sample_engine import Serving0
        from tests.test_resilience import _make_query_server

        traced_apps = trace_dao(memory_storage.get_meta_data_apps(), "apps")

        class StorageTouchingServing(Serving0):
            def supplement(self, query):
                traced_apps.get_all()
                return query

        tid = mint_trace_id()
        get_tracer().clear()

        async def outer():
            server = _make_query_server(request_timeout_s=5.0)
            server.engine.serving_classes = {"s": StorageTouchingServing}
            server._active = server._active._replace(
                serving=StorageTouchingServing()
            )
            bad = FakeObsReplica("bad")
            bad.fail_status = 502
            bad_url = await bad.start()
            real = TestServer(server.make_app())
            await real.start_server()
            real_url = f"http://127.0.0.1:{real.port}"
            gw = Gateway(
                GatewayConfig(
                    replica_urls=(bad_url, real_url),
                    probe_interval_s=0.05,
                    telemetry_interval_s=0.05,
                    request_timeout_s=5.0,
                )
            )
            client = TestClient(TestServer(gw.make_app()))
            await client.start_server()
            try:
                await asyncio.sleep(0.12)
                # hit until the BAD replica is picked first (forcing the
                # retry path onto the real server)
                hit_tid = None
                for i in range(24):
                    t = f"{tid}{i:02d}"
                    resp = await client.post(
                        "/queries.json",
                        json={"qid": 7, "user": f"u{i}"},
                        headers={TRACE_HEADER: t},
                    )
                    assert resp.status == 200
                    assert resp.headers[TRACE_HEADER] == t
                    route = next(
                        s
                        for s in gw.tracer.find(t)
                        if s["name"] == "gateway.route"
                    )
                    if route["tags"].get("retried"):
                        hit_tid = t
                        break
                assert hit_tid, "no query ever routed bad-first"
                # the REAL server saw the same trace id through its
                # micro-batcher down to the storage DAO span
                server_spans = get_tracer().find(hit_tid)
                kinds = {s["kind"] for s in server_spans}
                assert {"ingress", "batch", "storage"} <= kinds, server_spans
                # the merged view assembles BOTH tiers for that one id
                await asyncio.sleep(0.12)  # fan-in tick
                t = await client.get(f"/traces/recent?trace_id={hit_tid}")
                merged = (await t.json())["spans"]
                merged_names = {s["name"] for s in merged}
                assert "gateway.route" in merged_names
                assert "gateway.proxy" in merged_names
                merged_kinds = {
                    s["kind"] for s in merged if s["source"] != "gateway"
                }
                assert {"ingress", "batch", "storage"} <= merged_kinds
            finally:
                await client.close()
                await bad.stop()
                await real.close()
                await server.stop()

        asyncio.run(outer())


# ---------------------------------------------------------------------------
# pio top: the crash line + history rendering units
# ---------------------------------------------------------------------------


class TestTopCrashLine:
    def test_fleet_screen_shows_last_crash_excerpt_path(self):
        from predictionio_tpu.tools.top import parse_prometheus, render, summarize

        text = (
            "pio_fleet_replicas 2\n"
            'pio_fleet_replica_up{replica="w0"} 1\n'
            'pio_fleet_replica_up{replica="w1"} 0\n'
            'pio_fleet_worker_last_crash_unix{replica="w1"} 1700000000\n'
            'pio_fleet_worker_log_info{replica="w0",path="/obs/logs/w0.log"} 1\n'
            'pio_fleet_worker_log_info{replica="w1",path="/obs/logs/w1.log"} 1\n'
        )
        summary = summarize(parse_prometheus(text))
        screen = render(summary, "http://gw:8000")
        assert "crash" in screen
        assert "/obs/logs/w1.log" in screen
        # the healthy worker has a log but no crash: no crash line for it
        assert "/obs/logs/w0.log" not in screen

    def test_sparkline_shapes(self):
        from predictionio_tpu.tools.top import sparkline

        assert sparkline([]) == "-"
        assert len(sparkline([0.0, 1.0, 2.0])) == 3
        assert len(sparkline(list(range(500)), width=60)) == 60
        flat = sparkline([0.0, 0.0])
        assert flat == flat[0] * 2
