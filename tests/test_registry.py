"""Model registry + progressive rollout tests (tier-1, CPU-only, fast).

Covers the subsystem end to end: PIOTPU02 checksummed model framing,
content-addressed artifact store with lineage manifests and GC, the
rollout state machine, sticky canary hashing, the metric-gated promotion
controller, and the serving integration — including the acceptance rail:
train -> publish v2 -> canary with sticky hashing -> injected faults on
v2 trip the candidate breaker -> auto-rollback to v1 with zero 5xx on the
stable lane, all visible in /metrics and the registry state.
"""

import asyncio
import json
import os
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tracing import TRACE_HEADER, get_tracer
from predictionio_tpu.registry import (
    ArtifactIntegrityError,
    ArtifactStore,
    Lane,
    ModelManifest,
    PromotionCriteria,
    RolloutController,
    RolloutInstruments,
    params_hash_of,
    sticky_bucket,
)
from predictionio_tpu.registry.controller import (
    VERDICT_IDLE,
    VERDICT_PROMOTE,
    VERDICT_READY,
    VERDICT_ROLLBACK,
    VERDICT_WAIT,
)
from predictionio_tpu.registry.result_cache import ResultCache
from predictionio_tpu.registry.router import (
    LANE_CANDIDATE,
    LANE_STABLE,
    RolloutPlan,
    choose_lane,
    routing_key,
)
from predictionio_tpu.resilience import CLOSED, OPEN, FaultInjector
from predictionio_tpu.workflow import model_io


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


# ---------------------------------------------------------------------------
# model_io: PIOTPU02 checksummed framing
# ---------------------------------------------------------------------------


class TestModelIOIntegrity:
    def test_v02_roundtrip(self):
        blob = model_io.serialize_models([{"w": [1.0, 2.0]}, "second"])
        assert blob.startswith(model_io.MAGIC)
        assert model_io.deserialize_models(blob) == [{"w": [1.0, 2.0]}, "second"]

    def test_reads_legacy_v01(self):
        import pickle
        import zlib

        legacy = model_io.MAGIC_V1 + zlib.compress(pickle.dumps([1, 2, 3]))
        assert model_io.deserialize_models(legacy) == [1, 2, 3]

    def test_truncation_is_a_clear_integrity_error(self):
        blob = model_io.serialize_models([list(range(100))])
        for cut in (len(blob) - 1, len(blob) - 20, len(model_io.MAGIC) + 4):
            with pytest.raises(model_io.ModelIntegrityError):
                model_io.deserialize_models(blob[:cut])

    def test_bitflip_is_a_clear_integrity_error(self):
        blob = bytearray(model_io.serialize_models([list(range(100))]))
        blob[len(blob) // 2] ^= 0xFF
        with pytest.raises(model_io.ModelIntegrityError) as exc_info:
            model_io.deserialize_models(bytes(blob))
        assert "sha256" in str(exc_info.value)

    def test_corrupt_v01_wrapped_not_opaque(self):
        import pickle
        import zlib

        legacy = model_io.MAGIC_V1 + zlib.compress(pickle.dumps([1, 2, 3]))
        with pytest.raises(model_io.ModelIntegrityError):
            model_io.deserialize_models(legacy[:-4])

    def test_bad_magic(self):
        with pytest.raises(model_io.ModelIntegrityError):
            model_io.deserialize_models(b"NOTPIO00" + b"x" * 64)


# ---------------------------------------------------------------------------
# manifests
# ---------------------------------------------------------------------------


class TestManifest:
    def test_params_hash_is_order_independent(self):
        a = params_hash_of({"x": 1, "y": {"b": 2, "a": 3}})
        b = params_hash_of({"y": {"a": 3, "b": 2}, "x": 1})
        assert a == b
        assert a != params_hash_of({"x": 2, "y": {"b": 2, "a": 3}})

    def test_json_roundtrip_ignores_unknown_keys(self):
        m = ModelManifest(
            version="v000001",
            engine_id="e",
            engine_version="1",
            engine_variant="engine.json",
            metrics={"ndcg": 0.41},
        )
        data = m.to_json_dict()
        data["future_field"] = "ignored"
        clone = ModelManifest.from_json_dict(data)
        assert clone.version == "v000001"
        assert clone.metrics == {"ndcg": 0.41}


# ---------------------------------------------------------------------------
# artifact store
# ---------------------------------------------------------------------------


def _manifest(engine_id="store-test", **kw):
    defaults = dict(
        version="",
        engine_id=engine_id,
        engine_version="1",
        engine_variant="engine.json",
        engine_factory="pkg.mod.engine",
    )
    defaults.update(kw)
    return ModelManifest(**defaults)


class TestArtifactStore:
    def test_publish_assigns_versions_and_auto_stable(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        m1 = store.publish(_manifest(instance_id="i1"), b"blob-one")
        m2 = store.publish(_manifest(instance_id="i2"), b"blob-two")
        assert m1.version == "v000001"
        assert m2.version == "v000002"
        assert m2.parent_version == "v000001"  # stable at publish time
        state = store.get_state("store-test")
        assert state.stable == "v000001"  # first publish auto-stabilizes
        assert [m.version for m in store.list_versions("store-test")] == [
            "v000001",
            "v000002",
        ]
        assert [h["action"] for h in state.history][:2] == ["publish", "auto-stable"]

    def test_load_blob_verifies_sha256(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        m = store.publish(_manifest(), b"precious bytes")
        assert store.load_blob("store-test", m.version) == b"precious bytes"
        blob_path = store._blob_path("store-test", m.blob_sha256)
        with open(blob_path, "wb") as fh:
            fh.write(b"precious bytez")  # flipped one byte, same length
        with pytest.raises(ArtifactIntegrityError) as exc_info:
            store.load_blob("store-test", m.version)
        assert "checksum" in str(exc_info.value)
        with open(blob_path, "wb") as fh:
            fh.write(b"short")
        with pytest.raises(ArtifactIntegrityError) as exc_info:
            store.load_blob("store-test", m.version)
        assert "length" in str(exc_info.value)

    def test_load_blob_unknown_version(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        with pytest.raises(ArtifactIntegrityError):
            store.load_blob("store-test", "v999999")

    def test_identical_bytes_are_deduplicated(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        m1 = store.publish(_manifest(), b"same bytes")
        m2 = store.publish(_manifest(), b"same bytes")
        assert m1.blob_sha256 == m2.blob_sha256
        blobs_dir = os.path.dirname(store._blob_path("store-test", m1.blob_sha256))
        assert len(os.listdir(blobs_dir)) == 1

    def test_gc_keeps_last_n_and_pinned(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        for i in range(5):
            store.publish(_manifest(instance_id=f"i{i}"), f"blob{i}".encode())
        # stable pin is v000001 (auto-stable); keep_last=2 drops the oldest
        # unpinned versions
        removed = store.gc("store-test", keep_last=2)
        left = [m.version for m in store.list_versions("store-test")]
        assert "v000001" in left  # pinned by stable
        assert "v000005" in left  # newest survives
        assert len(left) <= 3
        for version in removed:
            with pytest.raises(ArtifactIntegrityError):
                store.load_blob("store-test", version)

    def test_gc_pins_never_eat_the_newest_budget(self, tmp_path):
        """With pinned count >= keep_last, publish must still keep the
        version it just wrote (pins are additive to the newest-N set,
        not counted against it)."""
        store = ArtifactStore(str(tmp_path))
        store.publish(_manifest(), b"one")  # auto-stable -> pinned
        m2 = store.publish(_manifest(), b"two", keep_last=1)
        left = [m.version for m in store.list_versions("store-test")]
        assert m2.version in left  # the just-published version survives
        assert "v000001" in left  # the stable pin survives

    def test_state_machine_stage_promote_rollback(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        store.publish(_manifest(), b"one")
        store.publish(_manifest(), b"two")
        with pytest.raises(ValueError):
            store.stage_candidate("store-test", "v000404")  # unknown
        with pytest.raises(ValueError):
            store.stage_candidate("store-test", "v000001")  # already stable
        with pytest.raises(ValueError):
            store.promote("store-test")  # nothing staged
        state = store.stage_candidate(
            "store-test", "v000002", mode="canary", fraction=0.25
        )
        assert (state.candidate, state.mode, state.fraction) == (
            "v000002",
            "canary",
            0.25,
        )
        state = store.promote("store-test")
        assert state.stable == "v000002"
        assert state.previous_stable == "v000001"
        assert state.candidate == "" and state.mode == "off"
        # post-promote regret: rollback reverts to previous stable
        state = store.rollback("store-test", reason="regret")
        assert state.stable == "v000001"
        with pytest.raises(ValueError):
            store.rollback("store-test")  # nothing left to roll back
        actions = [h["action"] for h in store.get_state("store-test").history]
        assert actions.count("rollback") == 1
        assert "stage" in actions and "promote" in actions

    def test_promote_past_staged_candidate_unstages_it(self, tmp_path):
        """Promoting an explicit version different from the staged
        candidate obsoletes that rollout: an orphaned candidate would
        report a canary no server is baking and pin the version against
        GC forever."""
        store = ArtifactStore(str(tmp_path))
        store.publish(_manifest(), b"one")
        store.publish(_manifest(), b"two")
        store.publish(_manifest(), b"three")
        store.stage_candidate("store-test", "v000002", mode="canary")
        state = store.promote("store-test", "v000003")
        assert state.stable == "v000003"
        assert state.candidate == "" and state.mode == "off"
        assert any(
            h["action"] == "unstage" and h["version"] == "v000002"
            for h in state.history
        )

    def test_no_tmp_litter(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        store.publish(_manifest(), b"x" * 1000)
        litter = [
            name
            for _, _, names in os.walk(tmp_path)
            for name in names
            if name.startswith(".tmp-")
        ]
        assert litter == []


# ---------------------------------------------------------------------------
# sticky routing
# ---------------------------------------------------------------------------


class TestStickyRouting:
    def test_deterministic_and_uniform(self):
        buckets = [sticky_bucket(f"user{i}", "salt") for i in range(2000)]
        assert buckets == [sticky_bucket(f"user{i}", "salt") for i in range(2000)]
        assert all(0.0 <= b < 1.0 for b in buckets)
        share = sum(1 for b in buckets if b < 0.1) / len(buckets)
        assert 0.05 < share < 0.15  # ~10% +- sampling noise

    def test_salt_resamples_population(self):
        a = {i for i in range(500) if sticky_bucket(f"u{i}", "v1") < 0.2}
        b = {i for i in range(500) if sticky_bucket(f"u{i}", "v2") < 0.2}
        assert a != b  # a later rollout canaries a different user set

    def test_choose_lane(self):
        canary = RolloutPlan("canary", 1.0, "s")
        assert choose_lane(canary, "anyone") == LANE_CANDIDATE
        assert choose_lane(RolloutPlan("canary", 0.0, "s"), "u") == LANE_STABLE
        assert choose_lane(RolloutPlan("shadow", 1.0, "s"), "u") == LANE_STABLE
        assert choose_lane(RolloutPlan("off", 1.0, "s"), "u") == LANE_STABLE

    def test_routing_key_field_and_fallback(self):
        assert routing_key({"user": "u7", "num": 3}, "user") == "u7"
        # missing field: still deterministic per payload
        k1 = routing_key({"num": 3, "q": "x"}, "user")
        k2 = routing_key({"q": "x", "num": 3}, "user")
        assert k1 == k2


# ---------------------------------------------------------------------------
# rollout controller (pure decision logic on fake clock + fresh registry)
# ---------------------------------------------------------------------------


def _controller(mode="canary", **criteria_kw):
    defaults = dict(bake_window_s=10.0, min_requests=10, auto_promote=True)
    defaults.update(criteria_kw)
    inst = RolloutInstruments(MetricsRegistry())
    clock = FakeClock()
    ctrl = RolloutController(inst, PromotionCriteria(**defaults), clock=clock)
    ctrl.begin("v1", "v2", mode)
    return ctrl, inst, clock


class TestRolloutController:
    def test_idle_without_active_rollout(self):
        inst = RolloutInstruments(MetricsRegistry())
        ctrl = RolloutController(inst, PromotionCriteria())
        assert ctrl.evaluate()[0] == VERDICT_IDLE

    def test_waits_for_window_and_sample(self):
        ctrl, inst, clock = _controller()
        inst.requests.inc(50, version="v2", lane=LANE_CANDIDATE)
        assert ctrl.evaluate()[0] == VERDICT_WAIT  # window not elapsed
        clock.advance(11)
        assert ctrl.evaluate()[0] == VERDICT_PROMOTE
        ctrl2, inst2, clock2 = _controller()
        clock2.advance(11)
        inst2.requests.inc(3, version="v2", lane=LANE_CANDIDATE)
        assert ctrl2.evaluate()[0] == VERDICT_WAIT  # sample too small

    def test_promotes_clean_candidate(self):
        ctrl, inst, clock = _controller()
        inst.requests.inc(100, version="v1", lane=LANE_STABLE)
        inst.errors.inc(2, version="v1", lane=LANE_STABLE)
        inst.requests.inc(30, version="v2", lane=LANE_CANDIDATE)
        clock.advance(11)
        verdict, reason = ctrl.evaluate()
        assert verdict == VERDICT_PROMOTE
        assert "gates passed" in reason

    def test_error_rate_gate_rolls_back(self):
        ctrl, inst, clock = _controller()
        inst.requests.inc(100, version="v1", lane=LANE_STABLE)
        inst.requests.inc(30, version="v2", lane=LANE_CANDIDATE)
        inst.errors.inc(10, version="v2", lane=LANE_CANDIDATE)
        clock.advance(11)
        verdict, reason = ctrl.evaluate()
        assert verdict == VERDICT_ROLLBACK
        assert reason.startswith("error-rate")

    def test_error_rate_compares_deltas_not_totals(self):
        # candidate counters carry history from an earlier bake: only
        # post-begin deltas may count
        inst = RolloutInstruments(MetricsRegistry())
        inst.errors.inc(50, version="v2", lane=LANE_CANDIDATE)  # pre-bake
        inst.requests.inc(50, version="v2", lane=LANE_CANDIDATE)
        clock = FakeClock()
        ctrl = RolloutController(
            inst,
            PromotionCriteria(bake_window_s=10.0, min_requests=10),
            clock=clock,
        )
        ctrl.begin("v1", "v2", "canary")
        inst.requests.inc(30, version="v2", lane=LANE_CANDIDATE)  # clean bake
        inst.requests.inc(30, version="v1", lane=LANE_STABLE)
        clock.advance(11)
        assert ctrl.evaluate()[0] == VERDICT_PROMOTE

    def test_latency_gate_rolls_back(self):
        ctrl, inst, clock = _controller(max_p95_ratio=1.5)
        inst.requests.inc(30, version="v2", lane=LANE_CANDIDATE)
        inst.requests.inc(30, version="v1", lane=LANE_STABLE)
        for _ in range(50):
            inst.predict_seconds.observe(0.010, version="v1")
            inst.predict_seconds.observe(0.200, version="v2")
        clock.advance(11)
        verdict, reason = ctrl.evaluate()
        assert verdict == VERDICT_ROLLBACK
        assert reason.startswith("latency")

    def test_latency_gate_is_windowed_not_lifetime(self):
        """A re-staged candidate is judged on THIS bake's samples: slow
        predicts from a previous (rolled-back) bake must not keep
        re-tripping the gate after the slowness is fixed."""
        inst = RolloutInstruments(MetricsRegistry())
        clock = FakeClock()
        for _ in range(50):  # previous bake: candidate was slow
            inst.predict_seconds.observe(0.010, version="v1")
            inst.predict_seconds.observe(0.500, version="v2")
        ctrl = RolloutController(
            inst,
            PromotionCriteria(
                bake_window_s=10.0, min_requests=10, max_p95_ratio=1.5
            ),
            clock=clock,
        )
        ctrl.begin("v1", "v2", "canary")  # re-stage after the fix
        inst.requests.inc(30, version="v2", lane=LANE_CANDIDATE)
        inst.requests.inc(30, version="v1", lane=LANE_STABLE)
        for _ in range(50):  # this bake: same speed as stable
            inst.predict_seconds.observe(0.010, version="v1")
            inst.predict_seconds.observe(0.010, version="v2")
        clock.advance(11)
        assert ctrl.evaluate()[0] == VERDICT_PROMOTE

    def test_shadow_divergence_gate(self):
        ctrl, inst, clock = _controller(mode="shadow", max_divergence_rate=0.25)
        inst.shadow_scored.inc(40, version="v2")
        inst.divergence.inc(20, version="v2")
        clock.advance(11)
        verdict, reason = ctrl.evaluate()
        assert verdict == VERDICT_ROLLBACK
        assert reason.startswith("divergence")
        ctrl2, inst2, clock2 = _controller(mode="shadow")
        inst2.shadow_scored.inc(40, version="v2")
        inst2.divergence.inc(2, version="v2")
        clock2.advance(11)
        assert ctrl2.evaluate()[0] == VERDICT_PROMOTE

    def test_ready_when_auto_promote_disabled(self):
        ctrl, inst, clock = _controller(auto_promote=False)
        inst.requests.inc(30, version="v2", lane=LANE_CANDIDATE)
        clock.advance(11)
        assert ctrl.evaluate()[0] == VERDICT_READY


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------


class _JsonQuery:
    """sample_engine Query with the /queries.json codec contract."""

    def __init__(self, qid: int):
        self.qid = qid

    @classmethod
    def from_json_dict(cls, d):
        return cls(qid=int(d["qid"]))


def _memory_storage():
    from predictionio_tpu.data.storage.registry import Storage

    return Storage(
        env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )


def _mk_engine():
    from predictionio_tpu.controller import Engine
    from tests.sample_engine import Algo0, DataSource0, Preparator0, Serving0

    return Engine(
        {"ds": DataSource0},
        {"prep": Preparator0},
        {"a": Algo0},
        {"s": Serving0},
        query_class=_JsonQuery,
    )


def _engine_manifest():
    from predictionio_tpu.workflow.engine_loader import EngineManifest

    return EngineManifest(
        engine_id="regtest",
        version="1",
        variant="engine.json",
        engine_factory="tests.test_engine.make_engine",
    )


def _train_version(storage, registry_dir, algo_id):
    """One real train -> metadata instance + registry publish."""
    from predictionio_tpu.workflow.core_workflow import run_train
    from tests.test_engine import params

    return run_train(
        _mk_engine(),
        _engine_manifest(),
        params(algos=((algo_id,),)),
        storage=storage,
        registry_dir=registry_dir,
    )


def _registry_server(tmp_path, **cfg_kw):
    """train v1 (algo id 3) + v2 (algo id 5), deploy the registry stable."""
    from predictionio_tpu.workflow.create_server import (
        ServerConfig,
        _query_server_from_registry,
    )

    storage = _memory_storage()
    registry_dir = str(tmp_path / "registry")
    id1 = _train_version(storage, registry_dir, algo_id=3)
    id2 = _train_version(storage, registry_dir, algo_id=5)
    store = ArtifactStore(registry_dir)
    cfg_kw.setdefault("bake_check_interval_s", 30.0)  # loop idle unless asked
    cfg_kw.setdefault("request_timeout_s", 5.0)
    config = ServerConfig(**cfg_kw)
    server = _query_server_from_registry(
        _mk_engine(), _engine_manifest(), store, "v000001", storage, config
    )
    return server, store, (id1, id2)


def _run_server(body_fn, server):
    async def outer():
        client = TestClient(TestServer(server.make_app()))
        await client.start_server()
        try:
            await body_fn(client)
        finally:
            await client.close()
        leftover = [
            t
            for t in asyncio.all_tasks()
            if t is not asyncio.current_task() and not t.done()
        ]
        assert leftover == [], f"hung tasks after shutdown: {leftover}"

    asyncio.run(outer())


class TestEndToEndRollout:
    def test_train_publishes_lineage(self, tmp_path):
        storage = _memory_storage()
        registry_dir = str(tmp_path / "registry")
        id1 = _train_version(storage, registry_dir, algo_id=3)
        store = ArtifactStore(registry_dir)
        versions = store.list_versions("regtest")
        assert [m.version for m in versions] == ["v000001"]
        m = versions[0]
        assert m.instance_id == id1
        assert m.params_hash  # canonical hash of the engine params json
        assert m.blob_sha256 and m.blob_size > 0
        assert "trainWallClockSec" in m.data_span
        # every batch publish carries its training evidence: the xray
        # step-profiler JSON (phases tiling the wall clock) rides the
        # manifest so `pio models show` answers "how was this trained"
        assert m.train_profile, "run_train must attach a train_profile"
        assert m.train_profile["wallClockS"] > 0
        assert "host_etl" in m.train_profile["phases"]
        assert "solve" in m.train_profile["phases"]
        assert (
            m.train_profile["attributedS"] <= m.train_profile["wallClockS"] * 1.001
        )
        # the registry blob IS the deployable artifact
        blob = store.load_blob("regtest", "v000001")
        assert model_io.deserialize_models(blob)
        assert store.get_state("regtest").stable == "v000001"

    def test_canary_sticky_fault_injection_auto_rollback(self, tmp_path):
        """The acceptance rail: canary at 50% with sticky hashing; injected
        faults on v2 trip the candidate breaker; the router auto-rolls
        back to v1 with ZERO 5xx on any lane; /metrics shows per-version
        counters and the rollback."""
        server, store, _ = _registry_server(tmp_path)
        assert server.model_version == "v000001"

        async def body(client):
            # baseline: stable serves algo id 3
            resp = await client.post("/queries.json", json={"qid": 1, "user": "u1"})
            assert resp.status == 200
            assert (await resp.json())["algo_id"] == 3
            # stage v2 as a 50% canary (sticky per user)
            resp = await client.post(
                "/models/candidate",
                json={"version": "v000002", "mode": "canary", "fraction": 0.5},
            )
            assert resp.status == 200, await resp.text()
            # sticky hashing: a user sees ONE model across repeats, and the
            # assignment is exactly the sticky_bucket contract
            seen: dict[int, int] = {}
            for _round in range(3):
                for u in range(20):
                    resp = await client.post(
                        "/queries.json", json={"qid": u, "user": f"user{u}"}
                    )
                    assert resp.status == 200
                    algo_id = (await resp.json())["algo_id"]
                    assert seen.setdefault(u, algo_id) == algo_id
            expected = {
                u: (5 if sticky_bucket(f"user{u}", "v000002") < 0.5 else 3)
                for u in range(20)
            }
            assert seen == expected
            assert {3, 5} <= set(seen.values())  # both lanes actually served
            # per-version request counters with version labels on /metrics
            text = await (await client.get("/metrics")).text()
            assert 'pio_model_requests_total{version="v000001",lane="stable"}' in text
            assert (
                'pio_model_requests_total{version="v000002",lane="candidate"}'
                in text
            )
            # inject faults into the candidate lane's algorithm: every
            # candidate predict now raises
            cand = server._candidate
            broken = FaultInjector(cand.algorithms[0])
            broken.inject(fail_count=10_000)
            server._candidate = cand._replace(algorithms=[broken])
            # hammer both lanes: candidate queries fall back to stable
            # (zero 5xx), the breaker trips, the rollout auto-rolls back
            for _round in range(3):
                for u in range(20):
                    resp = await client.post(
                        "/queries.json", json={"qid": u, "user": f"user{u}"}
                    )
                    assert resp.status == 200, await resp.text()
                    assert (await resp.json())["algo_id"] == 3  # stable answer
            assert server._candidate is None  # breaker-trip rollback fired
            assert server.model_version == "v000001"  # stable untouched
            assert server.candidate_breaker.snapshot()["trips"] >= 1
            # registry state records the rollback + reason
            state = store.get_state("regtest")
            assert state.candidate == "" and state.stable == "v000001"
            assert any(
                h["action"] == "rollback" and "breaker-trip" in h.get("reason", "")
                for h in state.history
            )
            # visible on /metrics and /models
            text = await (await client.get("/metrics")).text()
            assert 'pio_rollbacks_total{reason="breaker-trip"} 1' in text
            data = await (await client.get("/models")).json()
            assert data["candidate"] is None
            assert data["stable"]["version"] == "v000001"
            assert data["registry"]["state"]["stable"] == "v000001"
            # post-rollback: the same traffic still answers healthily
            resp = await client.post("/queries.json", json={"qid": 9, "user": "u9"})
            assert resp.status == 200
            assert (await resp.json())["algo_id"] == 3

        _run_server(body, server)

    def test_bake_gates_auto_promote(self, tmp_path):
        """A clean candidate is auto-promoted once the bake window and
        sample-size gates pass; the registry pin moves with it."""
        server, store, (id1, id2) = _registry_server(
            tmp_path,
            bake_window_s=0.05,
            bake_min_requests=5,
            bake_check_interval_s=0.02,
            max_p95_ratio=1000.0,  # same algo both lanes; don't flake on noise
        )

        async def body(client):
            resp = await client.post(
                "/models/candidate",
                json={"version": "v000002", "mode": "canary", "fraction": 1.0},
            )
            assert resp.status == 200, await resp.text()
            for i in range(8):
                resp = await client.post(
                    "/queries.json", json={"qid": i, "user": f"user{i}"}
                )
                assert resp.status == 200
                assert (await resp.json())["algo_id"] == 5  # fraction 1.0
            deadline = time.monotonic() + 5.0
            while server.model_version != "v000002":
                assert time.monotonic() < deadline, "auto-promote never fired"
                await asyncio.sleep(0.02)
            assert server._candidate is None
            assert server.instance_id == id2
            # the registry write lands just after the in-memory lane swap
            while store.get_state("regtest").stable != "v000002":
                assert time.monotonic() < deadline, "registry pin never moved"
                await asyncio.sleep(0.02)
            state = store.get_state("regtest")
            assert state.previous_stable == "v000001"
            text = await (await client.get("/metrics")).text()
            assert "pio_promotions_total 1" in text

        _run_server(body, server)

    def test_manual_promote_and_rollback_endpoints(self, tmp_path):
        server, store, (id1, id2) = _registry_server(tmp_path, auto_promote=False)

        async def body(client):
            resp = await client.post("/models/promote")
            assert resp.status == 404  # nothing staged
            resp = await client.post("/models/rollback")
            assert resp.status == 404
            resp = await client.post(
                "/models/candidate", json={"version": "v000002", "fraction": 0.1}
            )
            assert resp.status == 200
            # an explicit version that is NOT the staged candidate is a
            # guard violation, not a selector: 409, nothing promoted
            resp = await client.post(
                "/models/promote", json={"version": "v000404"}
            )
            assert resp.status == 409
            assert server._candidate is not None
            assert server.model_version == "v000001"
            resp = await client.post("/models/promote")
            assert resp.status == 200
            data = await resp.json()
            assert data["version"] == "v000002"
            assert data["instanceId"] == id2
            assert server.model_version == "v000002"
            assert store.get_state("regtest").stable == "v000002"
            # unknown version -> 400, nothing changes
            resp = await client.post(
                "/models/candidate", json={"version": "v000404"}
            )
            assert resp.status == 400
            assert server._candidate is None
            # staging the serving stable against itself -> 400, and the
            # server/registry states stay in sync (no phantom rollout)
            resp = await client.post(
                "/models/candidate", json={"version": "v000002"}
            )
            assert resp.status == 400
            assert "already the stable" in (await resp.json())["message"]
            assert server._candidate is None
            assert store.get_state("regtest").candidate == ""

        _run_server(body, server)

    def test_registry_is_deploy_source_of_truth(self, tmp_path):
        """create_query_server prefers the registry's pinned stable over
        the newest COMPLETED instance: a newer (possibly bad) train does
        not change what serves until promoted."""
        from predictionio_tpu.workflow.create_server import (
            _query_server_from_registry,
            ServerConfig,
        )

        server, store, (id1, id2) = _registry_server(tmp_path)
        # v2 is the newer instance, but the registry pin says v000001
        assert server.model_version == "v000001"
        assert server.instance_id == id1
        # promote in the registry, redeploy -> v2 serves
        store.promote("regtest", "v000002")
        server2 = _query_server_from_registry(
            _mk_engine(),
            _engine_manifest(),
            store,
            store.get_state("regtest").stable,
            server.storage,
            ServerConfig(),
        )
        assert server2.model_version == "v000002"
        assert server2.instance_id == id2


# ---------------------------------------------------------------------------
# shadow mode
# ---------------------------------------------------------------------------


class _TagModel:
    def __init__(self, tag):
        self.tag = tag


class _TagAlgo:
    """Minimal lane algorithm: echoes its model's tag; tunable latency to
    widen race windows in the swap-consistency test."""

    def __init__(self, delay_s: float = 0.0, fail: bool = False):
        self.delay_s = delay_s
        self.fail = fail

    def predict_batch_dispatch(self, model, queries):
        def fin():
            if self.delay_s:
                time.sleep(self.delay_s)
            if self.fail:
                raise RuntimeError("injected lane failure")
            return [model.tag for _ in queries]

        return fin

    def predict_batch(self, model, queries):
        if self.fail:
            raise RuntimeError("injected lane failure")
        return [model.tag for _ in queries]

    def predict(self, model, query):
        if self.fail:
            raise RuntimeError("injected lane failure")
        return model.tag

    def warmup_serving(self, model, max_batch):
        pass


class _TagServing:
    def __init__(self, tag, shift: int = 0):
        self.tag = tag
        self.shift = shift

    def supplement(self, query):
        return query

    def serve(self, query, predictions):
        return {
            "model": predictions[0],
            "serving": self.tag,
            "qid": query.qid + self.shift,
        }


def _tag_lane(tag, **algo_kw):
    return Lane(
        [_TagAlgo(**algo_kw)], _TagServing(tag), [_TagModel(tag)], tag, f"inst-{tag}"
    )


def _tag_server(**cfg_kw):
    from predictionio_tpu.workflow.create_server import QueryServer, ServerConfig
    from predictionio_tpu.workflow.engine_loader import EngineManifest
    from tests.test_engine import params

    cfg_kw.setdefault("bake_check_interval_s", 30.0)
    server = QueryServer(
        engine=_mk_engine(),
        engine_params=params(),
        models=[_TagModel("v1")],
        manifest=EngineManifest(
            engine_id="tagtest",
            version="1",
            variant="engine.json",
            engine_factory="tests.test_engine.make_engine",
        ),
        instance_id="inst-v1",
        config=ServerConfig(**cfg_kw),
    )
    server._active = _tag_lane("v1")
    return server


class TestShadowMode:
    def test_shadow_scores_async_and_records_divergence(self, tmp_path):
        server = _tag_server()
        # candidate serves a DIFFERENT qid (shift) -> every comparison diverges
        server.stage_candidate_lane(
            _tag_lane("v2")._replace(serving=_TagServing("v2", shift=1000)),
            mode="shadow",
            persist=False,
        )

        async def body(client):
            for i in range(6):
                resp = await client.post(
                    "/queries.json", json={"qid": i, "user": f"u{i}"}
                )
                assert resp.status == 200
                data = await resp.json()
                # responses come from stable; candidate answers discarded
                assert data["model"] == "v1" and data["qid"] == i
            inst = server._rollout_instruments
            deadline = time.monotonic() + 5.0
            while inst.shadow_scored.value(version="v2") < 6:
                assert time.monotonic() < deadline, "shadow scoring never ran"
                await asyncio.sleep(0.01)
            assert inst.divergence.value(version="v2") == inst.shadow_scored.value(
                version="v2"
            )
            # shadow scoring feeds the latency gate too: without candidate
            # predict samples a slow candidate would sail through on
            # error/divergence alone
            assert inst.predict_seconds.summary(version="v2")["count"] >= 6
            assert server.candidate_breaker.snapshot()["state"] == CLOSED

        _run_server(body, server)

    def test_shadow_failures_feed_candidate_breaker(self):
        server = _tag_server(candidate_breaker_threshold=3)
        server.stage_candidate_lane(
            _tag_lane("v2", fail=True), mode="shadow", persist=False
        )

        async def body(client):
            for i in range(6):
                resp = await client.post(
                    "/queries.json", json={"qid": i, "user": f"u{i}"}
                )
                assert resp.status == 200  # shadow failures never hit users
            deadline = time.monotonic() + 5.0
            while server._candidate is not None:
                assert (
                    time.monotonic() < deadline
                ), "shadow breaker trip never rolled back"
                await asyncio.sleep(0.01)
            assert server.model_version == "v1"

        _run_server(body, server)


class TestRolloutGeneration:
    def test_stale_generation_work_cannot_touch_the_next_rollout(self):
        """Shadow/canary work queued for a rollout that has since ended
        must not feed the breaker or counters of the current one — a
        slow crashing candidate's backlog could otherwise roll back a
        healthy successor."""
        server = _tag_server(candidate_breaker_threshold=1)
        server.stage_candidate_lane(
            _tag_lane("v2", fail=True), mode="shadow", persist=False
        )
        stale_gen = server._rollout_gen
        server._rollback_candidate("manual")
        inst = server._rollout_instruments
        # stale canary-path failure: dropped entirely
        server._record_candidate_failure("v2", stale_gen)
        assert server.candidate_breaker.snapshot()["state"] == CLOSED
        assert inst.errors.value(version="v2", lane=LANE_CANDIDATE) == 0
        # stale shadow batch: skipped wholesale, backlog slot released
        with server._shadow_lock:
            server._shadow_pending += 1  # as _submit_shadow would have
        server._shadow_score(
            _tag_lane("v2", fail=True),
            [(_JsonQuery(1), {"qid": 1})],
            stale_gen,
        )
        assert inst.shadow_scored.value(version="v2") == 0
        assert server.candidate_breaker.snapshot()["state"] == CLOSED
        assert server._shadow_pending == 0

    def test_serving_rollback_never_reverts_registry_stable(self, tmp_path):
        """When the registry never recorded the stage (write swallowed), a
        breaker-trip rollback must be a registry no-op — not a previous-
        stable revert that would point new deploys at an older model than
        the one actually serving."""
        store = ArtifactStore(str(tmp_path / "registry"))
        store.publish(_manifest(engine_id="gentest"), b"one")
        store.publish(_manifest(engine_id="gentest"), b"two")
        store.promote("gentest", "v000002")  # previous_stable = v000001
        server = _tag_server()
        server.registry_store = store
        server.manifest.engine_id = "gentest"
        server.stage_candidate_lane(_tag_lane("v3"), persist=False)
        assert server._rollback_candidate("breaker-trip") == "v3"
        state = store.get_state("gentest")
        assert state.stable == "v000002"  # NOT flipped back to v000001
        assert state.previous_stable == "v000001"

    def test_shadow_backlog_is_bounded(self):
        server = _tag_server(shadow_max_backlog=2)
        server.stage_candidate_lane(_tag_lane("v2"), mode="shadow", persist=False)
        cand = server._candidate
        with server._shadow_lock:
            server._shadow_pending = 2  # backlog full
        server._submit_shadow(cand, [(_JsonQuery(1), {"qid": 1})] * 3, server._rollout_gen)
        inst = server._rollout_instruments
        assert inst.shadow_dropped.value(version="v2") == 3  # counted, not queued
        with server._shadow_lock:
            server._shadow_pending = 0


# ---------------------------------------------------------------------------
# swap consistency under concurrent traffic (reload/promote contract)
# ---------------------------------------------------------------------------


class TestSwapConsistencyUnderTraffic:
    def test_concurrent_promotes_never_mix_lanes(self):
        """Queries in flight during version swaps must each see ONE
        consistent (algorithms, serving, models, version) quadruple, and
        their trace spans must carry the model version that answered."""
        server = _tag_server()
        lanes = {"v1": _tag_lane("v1", delay_s=0.002), "v2": _tag_lane("v2", delay_s=0.002)}
        server._active = lanes["v1"]
        tracer = get_tracer()

        async def churn():
            for _ in range(25):
                nxt = "v2" if server.model_version == "v1" else "v1"
                server.stage_candidate_lane(
                    lanes[nxt], fraction=0.0, persist=False
                )
                assert server._promote_candidate() == nxt
                await asyncio.sleep(0.001)

        async def one_query(client, i):
            trace_id = f"swaptrace{i:04d}"
            resp = await client.post(
                "/queries.json",
                json={"qid": i},
                headers={TRACE_HEADER: trace_id},
            )
            assert resp.status == 200
            data = await resp.json()
            # the quadruple consistency contract: model, serving (and the
            # qid echoed through that serving) all come from ONE lane
            assert data["model"] == data["serving"], data
            assert data["qid"] == i
            return trace_id, data["model"]

        async def body(client):
            results, _ = await asyncio.gather(
                asyncio.gather(*[one_query(client, i) for i in range(80)]),
                churn(),
            )
            versions = {v for _, v in results}
            assert versions <= {"v1", "v2"}
            # every query's batch span carries the version that answered it
            checked = 0
            for trace_id, version in results:
                for span in tracer.find(trace_id):
                    if span["name"] == "query.batch":
                        assert span["tags"]["version"] == version
                        checked += 1
            assert checked >= 40  # ring keeps the recent ones at minimum

        _run_server(body, server)


# ---------------------------------------------------------------------------
# version-keyed result cache (registry/result_cache.py + serving wiring)
# ---------------------------------------------------------------------------


class TestResultCacheUnit:
    def test_lru_eviction_and_counters(self):
        clock = FakeClock()
        cache = ResultCache(max_entries=2, ttl_s=0.0, clock=clock)
        cache.put("v1", b"a", {"n": 1})
        cache.put("v1", b"b", {"n": 2})
        assert cache.get("v1", b"a").body == {"n": 1}  # refreshes a's recency
        cache.put("v1", b"c", {"n": 3})  # evicts b (LRU)
        assert cache.get("v1", b"b") is None
        assert cache.get("v1", b"a") is not None
        assert cache.evictions == 1
        assert cache.hits == 2 and cache.misses == 1

    def test_ttl_expiry_counts_as_eviction(self):
        clock = FakeClock()
        cache = ResultCache(max_entries=8, ttl_s=5.0, clock=clock)
        cache.put("v1", b"a", {"n": 1})
        clock.advance(4.9)
        assert cache.get("v1", b"a") is not None
        clock.advance(0.2)
        assert cache.get("v1", b"a") is None
        assert cache.evictions == 1 and cache.misses == 1

    def test_version_key_isolates_lanes_and_flush_is_scoped(self):
        cache = ResultCache(max_entries=8, ttl_s=0.0)
        cache.put("v1", b"q", {"from": "v1"})
        cache.put("v2", b"q", {"from": "v2"})
        assert cache.get("v1", b"q").body == {"from": "v1"}
        assert cache.get("v2", b"q").body == {"from": "v2"}
        assert cache.flush_version("v1") == 1  # exactly v1's entries
        assert cache.get("v1", b"q") is None
        assert cache.get("v2", b"q") is not None
        assert cache.invalidations == 1

    def test_disabled_cache_is_inert(self):
        cache = ResultCache(max_entries=0)
        cache.put("v1", b"a", {})
        assert cache.get("v1", b"a") is None
        assert len(cache) == 0 and cache.misses == 0


class TestResultCacheServing:
    def test_hit_answers_without_entering_batch_queue(self):
        """The acceptance rail: a repeat query answers from the cache
        BEFORE micro-batch admission — the batcher never sees it."""
        server = _tag_server()

        async def body(client):
            r1 = await client.post("/queries.json", json={"qid": 7})
            assert r1.status == 200
            body1 = await r1.json()
            dispatched = server._batcher.queries_dispatched
            # same canonical payload, different key order: one cache entry
            r2 = await client.post(
                "/queries.json",
                data=json.dumps({"qid": 7}),
                headers={"Content-Type": "application/json"},
            )
            assert r2.status == 200
            assert await r2.json() == body1
            assert server._batcher.queries_dispatched == dispatched
            assert server._result_cache.hits == 1
            text = await (await client.get("/metrics")).text()
            assert "pio_cache_hits_total 1" in text
            assert 'pio_phase_seconds_count{phase="cache"}' in text

        _run_server(body, server)

    def test_active_rollout_bypasses_cache_entirely(self):
        """Canary users must exercise the candidate for the bake gates to
        mean anything: while a rollout is staged, lookups AND stores are
        bypassed — a canary answer can never be cached, so it can never
        be served from a stale lane."""
        server = _tag_server()
        server.stage_candidate_lane(_tag_lane("v2"), fraction=0.5, persist=False)

        async def body(client):
            for _ in range(2):
                resp = await client.post("/queries.json", json={"qid": 1, "user": "u1"})
                assert resp.status == 200
            cache = server._result_cache
            assert len(cache) == 0  # nothing stored
            assert cache.hits == 0 and cache.misses == 0  # nothing looked up

        _run_server(body, server)

    def test_promote_swap_serves_no_stale_answer(self):
        """The registry swap test: an answer cached under the old stable
        must not survive a promote — the next query is answered by the
        new version, and the retired lane's entries are flushed."""
        server = _tag_server()

        async def body(client):
            r1 = await client.post("/queries.json", json={"qid": 3})
            assert (await r1.json())["model"] == "v1"
            assert len(server._result_cache) == 1
            server.stage_candidate_lane(
                _tag_lane("v2"), fraction=0.0, persist=False
            )
            assert server._promote_candidate() == "v2"
            # the version boundary: same payload, NEW answer
            r2 = await client.post("/queries.json", json={"qid": 3})
            assert (await r2.json())["model"] == "v2"
            cache = server._result_cache
            assert cache.invalidations >= 1  # retired v1 lane flushed
            assert all(k[0] != "v1" for k in cache._entries)

        _run_server(body, server)

    def test_rollback_flushes_exactly_the_candidate_lane(self):
        server = _tag_server()
        cache = server._result_cache
        cache.put("v1", b"q1", {"from": "v1"})
        server.stage_candidate_lane(_tag_lane("v2"), persist=False)
        # belt-and-braces seeding: no real path caches candidate answers
        cache.put("v2", b"q2", {"from": "v2"})
        assert server._rollback_candidate("manual") == "v2"
        assert cache.get("v2", b"q2") is None
        # stable never changed: its entries stay valid and addressable
        assert cache.get("v1", b"q1").body == {"from": "v1"}

    def test_breaker_trip_auto_rollback_flushes_candidate_lane(self):
        """The chaos-stage contract: a breaker-trip INSTANT rollback runs
        the same flush as a manual one — zero stale candidate entries."""
        server = _tag_server(candidate_breaker_threshold=1)
        cache = server._result_cache
        server.stage_candidate_lane(
            _tag_lane("v2", fail=True), fraction=1.0, persist=False
        )
        cache.put("v2", b"q", {"from": "v2"})

        async def body(client):
            resp = await client.post("/queries.json", json={"qid": 5, "user": "u5"})
            assert resp.status == 200  # re-answered on stable, zero 5xx
            assert (await resp.json())["model"] == "v1"
            deadline = time.monotonic() + 5.0
            while server._candidate is not None:
                assert time.monotonic() < deadline, "auto-rollback never fired"
                await asyncio.sleep(0.01)
            assert cache.get("v2", b"q") is None

        _run_server(body, server)

    def test_restaged_candidate_lane_starts_empty(self):
        """A RE-staged candidate must not inherit entries from an earlier
        life of its version (prior bake + rollback)."""
        server = _tag_server()
        cache = server._result_cache
        server.stage_candidate_lane(_tag_lane("v2"), persist=False)
        cache.put("v2", b"old-bake", {"from": "v2-old"})
        server._rollback_candidate("manual")
        server.stage_candidate_lane(_tag_lane("v2"), persist=False)
        assert cache.get("v2", b"old-bake") is None

    def test_store_guard_orphans_write_across_swap(self):
        """A swap between dispatch and store must orphan the write: the
        batcher hands _cache_store the version that ANSWERED, and the
        guard drops it when that is no longer the current stable."""
        server = _tag_server()
        server._cache_store("v1", b"q", {"from": "v1"})
        assert len(server._result_cache) == 1
        server._result_cache.clear()
        server._active = _tag_lane("v2")  # swapped while batch in flight
        server._cache_store("v1", b"q", {"from": "v1"})
        assert len(server._result_cache) == 0

    def test_cache_disabled_by_config(self):
        server = _tag_server(result_cache_size=0)

        async def body(client):
            for _ in range(2):
                resp = await client.post("/queries.json", json={"qid": 1})
                assert resp.status == 200
            assert server._result_cache is None
            text = await (await client.get("/metrics")).text()
            assert "pio_cache_hits_total 0" in text  # registered, inert

        _run_server(body, server)


# ---------------------------------------------------------------------------
# /reload deprecation + instance id contract
# ---------------------------------------------------------------------------


class TestReloadContract:
    def _reload_server(self, monkeypatch):
        import datetime as dt

        from predictionio_tpu.data.storage.base import (
            EngineInstance,
            EngineInstanceStatus,
        )
        from predictionio_tpu.workflow import create_server as cs
        from predictionio_tpu.workflow.create_server import (
            QueryServer,
            ServerConfig,
        )
        from tests.test_engine import params

        storage = _memory_storage()
        now = dt.datetime.now(tz=dt.timezone.utc)
        latest_id = storage.get_meta_data_engine_instances().insert(
            EngineInstance(
                id="",
                status=EngineInstanceStatus.COMPLETED,
                start_time=now,
                end_time=now,
                engine_id="reloadtest",
                engine_version="1",
                engine_variant="engine.json",
                engine_factory="tests.test_engine.make_engine",
                algorithms_params='[{"name": "a", "params": {"id": 3}}]',
            )
        )
        monkeypatch.setattr(
            cs, "load_models_for_instance", lambda *a, **kw: [object()]
        )
        from predictionio_tpu.workflow.engine_loader import EngineManifest

        server = QueryServer(
            engine=_mk_engine(),
            engine_params=params(),
            models=[object()],
            manifest=EngineManifest(
                engine_id="reloadtest",
                version="1",
                variant="engine.json",
                engine_factory="tests.test_engine.make_engine",
            ),
            instance_id="old-instance",
            storage=storage,
            config=ServerConfig(),
        )
        return server, latest_id

    def test_post_is_canonical_get_warns_both_return_instance(
        self, monkeypatch, caplog
    ):
        import logging

        server, latest_id = self._reload_server(monkeypatch)

        async def body(client):
            with caplog.at_level(
                logging.WARNING, logger="predictionio_tpu.workflow.create_server"
            ):
                resp = await client.post("/reload")
                assert resp.status == 200
                assert (await resp.json())["instanceId"] == latest_id
            assert not any("deprecated" in r.message for r in caplog.records)
            with caplog.at_level(
                logging.WARNING, logger="predictionio_tpu.workflow.create_server"
            ):
                resp = await client.get("/reload")
                assert resp.status == 200
                # the GET spelling still works and returns the swapped-in
                # instance id, but logs the deprecation
                assert (await resp.json())["instanceId"] == latest_id
            assert any("deprecated" in r.message for r in caplog.records)

        _run_server(body, server)


# ---------------------------------------------------------------------------
# pio models CLI
# ---------------------------------------------------------------------------


class TestModelsCli:
    def _seed(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        store.publish(
            _manifest(engine_id="cliengine", instance_id="i1"), b"blob-one"
        )
        store.publish(
            _manifest(
                engine_id="cliengine",
                instance_id="i2",
                metrics={"rmse": 0.5},
            ),
            b"blob-two",
        )
        return store

    def _run(self, tmp_path, *argv):
        from predictionio_tpu.tools.cli import main

        return main(
            [
                "models",
                argv[0],
                "--engine-id",
                "cliengine",
                "--registry-dir",
                str(tmp_path),
                *argv[1:],
            ]
        )

    def test_list_show_promote_rollback_diff(self, tmp_path, capsys):
        store = self._seed(tmp_path)
        assert self._run(tmp_path, "list") == 0
        out = capsys.readouterr().out
        assert "v000001" in out and "stable" in out and "v000002" in out

        assert self._run(tmp_path, "show", "v000002") == 0
        data = json.loads(capsys.readouterr().out)
        assert data["manifest"]["version"] == "v000002"
        assert data["manifest"]["metrics"] == {"rmse": 0.5}
        assert data["rollout"]["stable"] == "v000001"

        assert self._run(tmp_path, "promote", "v000002") == 0
        assert "Promoted v000002" in capsys.readouterr().out
        assert store.get_state("cliengine").stable == "v000002"

        assert self._run(tmp_path, "rollback") == 0
        capsys.readouterr()
        assert store.get_state("cliengine").stable == "v000001"

        assert self._run(tmp_path, "diff", "v000001", "v000002") == 0
        out = capsys.readouterr().out
        assert "instance_id" in out and "blob_sha256" in out
        assert "same engine params" in out

    def test_errors_exit_nonzero(self, tmp_path, capsys):
        self._seed(tmp_path)
        assert self._run(tmp_path, "show", "v000404") != 0
        capsys.readouterr()
        assert self._run(tmp_path, "promote", "v000001") != 0  # already stable
        capsys.readouterr()


# ---------------------------------------------------------------------------
# admin API registry inventory
# ---------------------------------------------------------------------------


class TestAdminModels:
    def test_inventory_endpoints(self, tmp_path):
        from predictionio_tpu.tools.admin_api import AdminServer

        store = ArtifactStore(str(tmp_path))
        store.publish(_manifest(engine_id="adminengine"), b"blob")
        server = AdminServer(
            storage=_memory_storage(), registry_dir=str(tmp_path)
        )

        async def body():
            client = TestClient(TestServer(server.make_app()))
            await client.start_server()
            try:
                data = await (await client.get("/cmd/models")).json()
                assert len(data["engines"]) == 1
                row = data["engines"][0]
                assert row["engineId"] == "adminengine"
                assert row["stable"] == "v000001"
                detail = await (
                    await client.get(f"/cmd/models/{row['engineKey']}")
                ).json()
                assert [v["version"] for v in detail["versions"]] == ["v000001"]
                missing = await client.get("/cmd/models/nope")
                assert missing.status == 404
            finally:
                await client.close()

        asyncio.run(body())


# ---------------------------------------------------------------------------
# pio top rollout line
# ---------------------------------------------------------------------------


class TestTopRolloutLine:
    def test_summarize_and_render_model_versions(self):
        from predictionio_tpu.tools.top import parse_prometheus, render, summarize

        text = "\n".join(
            [
                'pio_model_requests_total{version="v000001",lane="stable"} 90',
                'pio_model_requests_total{version="v000002",lane="candidate"} 10',
                'pio_model_errors_total{version="v000002",lane="candidate"} 2',
                "pio_rollout_mode 1",
                "pio_rollout_fraction 0.1",
                'pio_rollbacks_total{reason="breaker-trip"} 1',
                "pio_requests_total 100",
            ]
        )
        summary = summarize(parse_prometheus(text))
        assert summary["model_versions"]["v000001"]["requests"] == 90
        assert summary["model_versions"]["v000002"]["errors"] == 2
        assert summary["rollout_mode"] == "canary"
        assert summary["rollbacks_total"] == 1
        screen = render(summary, "http://x")
        assert "v000001[stable]" in screen
        assert "v000002[candidate]" in screen
        assert "mode canary@0.10" in screen
        assert "rollbacks 1" in screen
