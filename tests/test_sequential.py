"""Sequential (session / next-item) engine tests — ISSUE 20.

The load-bearing contracts: ordered per-user session reads ride the PR-5
``find_after`` total order on EVERY backend (same-creation-time ties
resolved by event id, paging never skips or double-reads), and the
transition scorer is EXACTLY the ``e2.markov_chain`` math (parity unit
holds the template's matrix equal to a direct ``train_markov_chain`` call
on the same events). Plus: eval folds through ``EventStoreSplitter``,
both scorers' serving behavior, and the streaming fold-in trainer.
"""

from __future__ import annotations

import dataclasses
import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App, event_seq_key
from predictionio_tpu.data.storage.jsonl import JSONLStorageClient
from predictionio_tpu.data.storage.memory import MemoryStorageClient
from predictionio_tpu.data.storage.sqlite import SQLiteStorageClient
from predictionio_tpu.e2.markov_chain import train_markov_chain
from predictionio_tpu.models.sequential import engine_factory
from predictionio_tpu.models.sequential.engine import (
    AttentionAlgorithm,
    AttentionAlgorithmParams,
    DataSourceParams,
    EvalParams,
    MarkovAlgorithm,
    MarkovAlgorithmParams,
    Query,
    SequentialModel,
    TrainingData,
    _iter_ordered,
    build_markov,
    sequences_from_events,
    transition_coordinates,
)
from predictionio_tpu.workflow.context import WorkflowContext

UTC = dt.timezone.utc
APP = 5


def t(n: int) -> dt.datetime:
    return dt.datetime(2024, 6, 1, 0, 0, n, tzinfo=UTC)


def view(user: str, item: str, n: int, *, eid: str | None = None,
         ct: dt.datetime | None = None, name: str = "view") -> Event:
    return Event(
        event=name,
        entity_type="user",
        entity_id=user,
        target_entity_type="item",
        target_entity_id=item,
        properties=DataMap({}),
        event_time=t(n),
        creation_time=ct or t(n),
        event_id=eid,
    )


# ---------------------------------------------------------------------------
# ordered reads: per-backend find_after paging feeds the sequential reader
# ---------------------------------------------------------------------------


@pytest.fixture(params=["memory", "sqlite", "jsonl"])
def levents(request, tmp_path):
    if request.param == "memory":
        client = MemoryStorageClient()
    elif request.param == "sqlite":
        client = SQLiteStorageClient({"PATH": str(tmp_path / "t.db")})
    else:
        client = JSONLStorageClient({"PATH": str(tmp_path / "events")})
    l = client.l_events()
    l.init(APP)
    return l


class TestOrderedReads:
    def test_equal_creation_time_tiebreak_feeds_sessions_in_id_order(
        self, levents
    ):
        """All five session events land in the SAME creation second,
        inserted shuffled: the id tiebreak must reconstruct the session in
        id (= ingest) order on every backend, even paging one at a time."""
        tie = t(30)
        for eid, item in (("e3", "i3"), ("e1", "i1"), ("e4", "i4"),
                          ("e2", "i2"), ("e0", "i0")):
            levents.insert(view("u1", item, 1, eid=eid, ct=tie), APP)
        for page in (1, 2, 50):
            per_user, vocab = sequences_from_events(
                _iter_ordered(levents, APP, None, page, 10_000),
                event_names=("view",),
                entity_type="user",
                target_entity_type="item",
            )
            assert [vocab[i] for i in per_user["u1"]] == [
                "i0", "i1", "i2", "i3", "i4",
            ]

    def test_resumed_page_never_skips_or_dupes_within_a_tie(self, levents):
        tie = t(7)
        for eid in ("ca", "cb", "cc", "cd", "ce"):
            levents.insert(view("u1", f"item-{eid}", 1, eid=eid, ct=tie), APP)
        seen = [e.event_id for e in _iter_ordered(levents, APP, None, 2, 10_000)]
        assert seen == ["ca", "cb", "cc", "cd", "ce"]

    def test_head_bound_excludes_live_ingest(self, levents):
        """The reader snapshots seq_head at entry: an event landing while
        the scan is in flight must not extend the read (a live stream
        would otherwise hold the training read open forever)."""
        for n, eid in ((1, "aa"), (2, "ab")):
            levents.insert(view("u1", f"i{n}", n, eid=eid), APP)
        it = _iter_ordered(levents, APP, None, 1, 10_000)
        first = next(it)
        assert first.event_id == "aa"
        levents.insert(view("u1", "i9", 9, eid="zz"), APP)  # after the head
        assert [e.event_id for e in it] == ["ab"]

    def test_max_events_bounds_the_scan(self, levents):
        for n in range(10):
            levents.insert(view("u1", f"i{n}", n, eid=f"e{n}"), APP)
        assert len(list(_iter_ordered(levents, APP, None, 3, 4))) == 4

    def test_reader_filters_names_and_entity_types(self):
        events = [
            view("u1", "i0", 0, eid="a"),
            view("u1", "i1", 1, eid="b", name="buy"),  # wrong event name
            dataclasses.replace(view("u1", "i2", 2, eid="c"),
                                entity_type="session"),  # wrong entity type
            dataclasses.replace(view("u1", "i3", 3, eid="d"),
                                target_entity_type="cat"),  # wrong target
            view("u1", "i4", 4, eid="e"),
        ]
        per_user, vocab = sequences_from_events(
            iter(events), event_names=("view",), entity_type="user",
            target_entity_type="item",
        )
        assert [vocab[i] for i in per_user["u1"]] == ["i0", "i4"]


# ---------------------------------------------------------------------------
# e2 MarkovChain parity
# ---------------------------------------------------------------------------


class TestMarkovParity:
    def test_template_matrix_matches_e2_train_markov_chain(self):
        """The template's trainer and a DIRECT e2 call on the same events
        must produce the identical transition model — probabilities, order,
        truncation, everything."""
        rng = np.random.default_rng(42)
        sequences = [
            np.asarray(rng.integers(0, 12, size=rng.integers(2, 9)), np.int32)
            for _ in range(40)
        ]
        template, counts = build_markov(sequences, 12, top_n=3)
        direct = train_markov_chain(
            transition_coordinates(sequences), 12, top_n=3
        )
        assert template.transitions == direct.transitions
        assert template.n_states == direct.n_states == 12
        # the raw pair counts kept for the stream merge sum to the number
        # of consecutive pairs (train_markov_chain alone is top-N lossy)
        assert sum(counts.values()) == sum(len(s) - 1 for s in sequences)

    def test_hand_computed_probabilities_and_tiebreak(self):
        # from state 0: ->1 twice, ->2 once, ->3 once => 0.5, 0.25, 0.25;
        # the 0.25 tie ranks by destination index (e2's (-p, j) sort key)
        seqs = [np.asarray(s, np.int32)
                for s in ([0, 1], [0, 1], [0, 3], [0, 2])]
        model, _ = build_markov(seqs, 4, top_n=10)
        assert model.transition_probs(0) == [(1, 0.5), (2, 0.25), (3, 0.25)]

    def test_top_n_truncates_probabilities_not_counts(self):
        seqs = [np.asarray([0, 1, 0, 2, 0, 3], np.int32)]
        model, counts = build_markov(seqs, 4, top_n=2)
        assert len(model.transition_probs(0)) == 2
        # counts keep the full fan-out for the streaming merge
        assert {(0, 1), (0, 2), (0, 3)} <= set(counts)


# ---------------------------------------------------------------------------
# DataSource: training + eval-grid folds from the event store
# ---------------------------------------------------------------------------


def _seed_sessions(storage, app_name: str, sessions: dict[str, list[str]]):
    storage.get_meta_data_apps().insert(App(0, app_name))
    app_id = storage.get_meta_data_apps().get_by_name(app_name).id
    levents = storage.get_l_events()
    n = 0
    for user in sorted(sessions):
        for item in sessions[user]:
            n += 1
            levents.insert(view(user, item, n), app_id)
    return app_id


class TestDataSource:
    def test_read_training_reconstructs_sessions_in_ingest_order(
        self, memory_storage
    ):
        sessions = {
            "u1": ["a", "b", "c"],
            "u2": ["b", "a"],
            "u3": ["c"],
        }
        _seed_sessions(memory_storage, "seqapp", sessions)
        engine = engine_factory()
        ep = engine.engine_params_from_variant(
            {
                "datasource": {"params": {"appName": "seqapp", "page": 2}},
                "algorithms": [{"name": "markov", "params": {}}],
            }
        )
        ds, prep, _, _ = engine.make_components(ep)
        ctx = WorkflowContext(mode="training", _storage=memory_storage)
        td = prep.prepare(ctx, ds.read_training(ctx))
        assert td.users == ["u1", "u2", "u3"]
        got = {
            u: [td.item_vocab[i] for i in seq]
            for u, seq in zip(td.users, td.sequences)
        }
        assert got == sessions

    def test_read_eval_folds_partition_users_with_held_out_tails(
        self, memory_storage
    ):
        sessions = {f"u{i}": ["a", "b", "c", "d"] for i in range(8)}
        _seed_sessions(memory_storage, "sevalapp", sessions)
        ds = type(engine_factory().data_source_classes[""])  # sanity: class
        from predictionio_tpu.models.sequential.engine import DataSource

        src = DataSource(
            DataSourceParams(
                app_name="sevalapp",
                eval_params=EvalParams(k_fold=2, query_num=3, holdout_tail=2),
            )
        )
        ctx = WorkflowContext(mode="evaluation", _storage=memory_storage)
        folds = src.read_eval(ctx)
        assert len(folds) == 2
        all_users = set(sessions)
        for td, _, qa in folds:
            held = {q.user for q, _ in qa}
            # training users and held-out users partition the population
            assert set(td.users) | held == all_users
            assert set(td.users) & held == set()
            for q, actual in qa:
                # prefix becomes the query session, tail the continuation
                assert list(q.recent_items) == ["a", "b"]
                assert list(actual.items) == ["c", "d"]
        # the sticky bucket assigns every user to exactly one fold
        held_by_fold = [{q.user for q, _ in qa} for _, _, qa in folds]
        assert held_by_fold[0] | held_by_fold[1] == all_users
        assert held_by_fold[0] & held_by_fold[1] == set()

    def test_read_eval_without_eval_params_raises(self, memory_storage):
        from predictionio_tpu.models.sequential.engine import DataSource

        _seed_sessions(memory_storage, "noeval", {"u": ["a", "b"]})
        src = DataSource(DataSourceParams(app_name="noeval"))
        with pytest.raises(ValueError, match="evalParams"):
            src.read_eval(WorkflowContext(_storage=memory_storage))


# ---------------------------------------------------------------------------
# scorers
# ---------------------------------------------------------------------------


def _td(sessions: list[list[str]]) -> TrainingData:
    vocab: list[str] = []
    index: dict[str, int] = {}
    seqs = []
    for s in sessions:
        row = []
        for item in s:
            if item not in index:
                index[item] = len(vocab)
                vocab.append(item)
            row.append(index[item])
        seqs.append(np.asarray(row, np.int32))
    return TrainingData([f"u{i}" for i in range(len(seqs))], seqs, vocab)


class TestScorers:
    def test_markov_predict_masks_session_items(self):
        td = _td([["a", "b"], ["a", "b"], ["a", "c"]])
        alg = MarkovAlgorithm(MarkovAlgorithmParams(top_n=5))
        model = alg.train(WorkflowContext(), td)
        # scoring is from the session's LAST item ("a"); without masking
        # "b" would win, but "b" is already in the session -> "c" answers
        r = alg.predict(model, Query(recent_items=("b", "a"), num=2))
        assert [s.item for s in r.item_scores] == ["c"]

    def test_markov_falls_back_to_stored_last_item_for_bare_user(self):
        td = _td([["a", "b"], ["a", "b"]])
        alg = MarkovAlgorithm(MarkovAlgorithmParams())
        model = alg.train(WorkflowContext(), td)
        r = alg.predict(model, Query(user="u0", num=1))  # u0 ended on "b"
        # last item is "b"; no outgoing transition from "b" -> empty result
        assert r.item_scores == ()
        r = alg.predict(model, Query(user="missing", num=1))
        assert r.item_scores == ()

    def test_attention_serves_through_packed_topk_and_bans_session(self):
        td = _td([["a", "b", "c"], ["a", "b", "c"], ["b", "c", "d"]])
        alg = AttentionAlgorithm(
            AttentionAlgorithmParams(rank=4, num_iterations=3, context=4)
        )
        model = alg.train(WorkflowContext(), td)
        assert model.item_in is not None and model.item_out is not None
        out = alg.predict_batch(
            model,
            [Query(recent_items=("a", "b"), num=3),
             Query(recent_items=("c",), num=2)],
        )
        assert len(out) == 2
        for r, banned in zip(out, ({"a", "b"}, {"c"})):
            items = [s.item for s in r.item_scores]
            assert not set(items) & banned
            scores = [s.score for s in r.item_scores]
            assert scores == sorted(scores, reverse=True)

    def test_markov_only_model_on_attention_lane_uses_host_scorer(self):
        td = _td([["a", "b"], ["a", "b"], ["a", "c"]])
        markov_model = MarkovAlgorithm(MarkovAlgorithmParams()).train(
            WorkflowContext(), td
        )
        assert markov_model.item_in is None
        alg = AttentionAlgorithm(AttentionAlgorithmParams())
        got = alg.predict(markov_model, Query(recent_items=("a",), num=1))
        want = MarkovAlgorithm(MarkovAlgorithmParams()).predict(
            markov_model, Query(recent_items=("a",), num=1)
        )
        assert got == want

    def test_explicit_recent_items_override_stored_last(self):
        td = _td([["a", "b"], ["c", "d"]])
        model = MarkovAlgorithm(MarkovAlgorithmParams()).train(
            WorkflowContext(), td
        )
        # u0's stored last is "b", but the explicit session says "c"
        assert model.session_indices(
            Query(user="u0", recent_items=("c",))
        ) == [model.item_index()["c"]]


# ---------------------------------------------------------------------------
# streaming fold-in
# ---------------------------------------------------------------------------


class TestStreamFoldIn:
    def _seed(self):
        td = _td([["a", "b"], ["a", "b"], ["b", "c"]])
        return MarkovAlgorithm(MarkovAlgorithmParams(top_n=5)).train(
            WorkflowContext(), td
        )

    def test_snapshot_merges_stream_counts_through_exact_e2_math(self):
        from predictionio_tpu.stream.trainers import SequentialStreamTrainer

        seed = self._seed()
        trainer = SequentialStreamTrainer(seed, holdout_every=10_000)
        # u9 is a stream-only user viewing a stream-only item: a->b->e
        absorbed = trainer.absorb(
            [view("u9", "a", 1), view("u9", "b", 2), view("u9", "e", 3)]
        )
        assert absorbed == 2  # two transitions; the first event opens the session
        (model,) = trainer.snapshot()
        assert isinstance(model, SequentialModel)
        assert "e" in model.item_vocab  # vocab grew
        idx = model.item_index()
        # merged counts: seed had a->b twice; the stream added one more
        assert model.pair_counts[(idx["a"], idx["b"])] == 3.0
        assert model.pair_counts[(idx["b"], idx["e"])] == 1.0
        # and the published matrix is the exact e2 rebuild of those counts
        from predictionio_tpu.models.sequential.engine import (
            markov_from_counts,
        )

        want = markov_from_counts(
            model.pair_counts, len(model.item_vocab), model.top_n
        )
        assert model.markov.transitions == want.transitions
        # session cursor advanced for serving's bare-user fallback
        assert model.item_vocab[model.user_last["u9"]] == "e"

    def test_attention_tables_ride_through_fold_in_unchanged(self):
        from predictionio_tpu.stream.trainers import SequentialStreamTrainer

        td = _td([["a", "b", "c"], ["a", "b", "c"]])
        seed = AttentionAlgorithm(
            AttentionAlgorithmParams(rank=4, num_iterations=2)
        ).train(WorkflowContext(), td)
        trainer = SequentialStreamTrainer(seed, holdout_every=10_000)
        trainer.absorb([view("u9", "a", 1), view("u9", "c", 2)])
        (model,) = trainer.snapshot()
        assert model.item_in is seed.item_in
        assert model.item_out is seed.item_out

    def test_trainer_for_models_selects_sequential(self):
        from predictionio_tpu.stream.pipeline import trainer_for_models
        from predictionio_tpu.stream.trainers import SequentialStreamTrainer

        trainer = trainer_for_models([self._seed()], holdout_every=10_000)
        assert isinstance(trainer, SequentialStreamTrainer)

    def test_drift_guard_needs_samples_then_tracks_hit_rate(self):
        from predictionio_tpu.stream.trainers import SequentialStreamTrainer

        trainer = SequentialStreamTrainer(
            self._seed(), holdout_every=2, drift_min_samples=4,
            drift_hit_drop=0.5,
        )
        report = trainer.drift()
        assert report.ok and "insufficient" in report.reason
        n = 0
        for _ in range(40):  # repetitive a->b traffic: holdout fills, hits
            n += 1
            trainer.absorb([view(f"s{n}", "a", n), view(f"s{n}", "b", n + 1)])
        assert trainer.drift().ok
