"""Fleet e2e worker entry: one REAL QueryServer process for test_fleet.

Serves the shared registry's pinned stable version of the sample engine,
with registry sync (fleet coordination) and the SIGTERM drain path
enabled — this is the process the kill-mid-rollout chaos stage SIGKILLs
and the supervisor restarts.

argv: REGISTRY_DIR PORT STORAGE_BASEDIR
env:  FLEET_BAKE_WINDOW / FLEET_BAKE_MIN tune the bake gate cadence.
"""

from __future__ import annotations

import asyncio
import os
import signal
import sys


def main() -> int:
    registry_dir, port, basedir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)

    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.registry.store import ArtifactStore
    from predictionio_tpu.workflow.create_server import (
        ServerConfig,
        _query_server_from_registry,
    )
    from tests.test_registry import _engine_manifest, _mk_engine

    # the same zero-config sqlite-under-basedir store the publisher used,
    # so the lineage manifest's engine instance (and its params) resolve
    storage = Storage(env={"PIO_FS_BASEDIR": basedir})
    store = ArtifactStore(registry_dir)
    state = store.get_state("regtest")
    if not state.stable:
        print("no stable version pinned in the registry", file=sys.stderr)
        return 2
    config = ServerConfig(
        ip="127.0.0.1",
        port=port,
        request_timeout_s=5.0,
        # fleet coordination: adopt registry transitions fast so the test
        # can assert propagation without long sleeps
        registry_sync_interval_s=0.1,
        bake_check_interval_s=0.1,
        bake_window_s=float(os.environ.get("FLEET_BAKE_WINDOW", "1.0")),
        bake_min_requests=int(os.environ.get("FLEET_BAKE_MIN", "5")),
        auto_promote=True,
        drain_grace_s=5.0,
    )
    server = _query_server_from_registry(
        _mk_engine(), _engine_manifest(), store, state.stable, storage, config
    )
    # operational stderr breadcrumb: when the supervisor's logbook
    # captures this worker's output, a SIGKILLed process still leaves a
    # tail for the incident bundle (the chaos e2e asserts it)
    print(
        f"fleet worker serving on 127.0.0.1:{port} "
        f"(stable {state.stable})",
        file=sys.stderr,
        flush=True,
    )

    async def run() -> None:
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGTERM, server.begin_drain)
        except (NotImplementedError, RuntimeError):
            pass
        await server.run_until_stopped()

    asyncio.run(run())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
