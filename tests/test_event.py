"""Event model + validation spec (ref Event.scala:112-166,
EventJson4sSupport wire contract)."""

import datetime as dt

import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event, EventValidation, format_event_time

UTC = dt.timezone.utc


def ev(**kw):
    defaults = dict(event="rate", entity_type="user", entity_id="u1")
    defaults.update(kw)
    return Event(**defaults)


def test_valid_plain_event():
    EventValidation.validate(ev())


def test_valid_event_with_target():
    EventValidation.validate(
        ev(target_entity_type="item", target_entity_id="i1")
    )


@pytest.mark.parametrize(
    "kw",
    [
        dict(event=""),
        dict(entity_type=""),
        dict(entity_id=""),
        dict(target_entity_type="item"),  # target type without id
        dict(target_entity_id="i1"),  # target id without type
        dict(target_entity_type="", target_entity_id="i1"),
        dict(event="$custom"),  # reserved prefix, not special
        dict(event="pio_thing"),
        dict(event="$set", target_entity_type="item", target_entity_id="i1"),
        dict(entity_type="pio_users"),  # reserved non-builtin entity type
        dict(target_entity_type="pio_x", target_entity_id="i1"),
    ],
)
def test_invalid_events(kw):
    with pytest.raises(ValueError):
        EventValidation.validate(ev(**kw))


def test_unset_requires_properties():
    with pytest.raises(ValueError):
        EventValidation.validate(ev(event="$unset"))
    EventValidation.validate(ev(event="$unset", properties=DataMap({"a": 1})))


def test_special_events_allowed():
    for name in ("$set", "$unset", "$delete"):
        props = DataMap({"a": 1}) if name != "$delete" else DataMap()
        EventValidation.validate(ev(event=name, properties=props))


def test_builtin_entity_type_allowed():
    EventValidation.validate(ev(entity_type="pio_pr"))


def test_reserved_property_rejected():
    with pytest.raises(ValueError):
        EventValidation.validate(ev(properties=DataMap({"pio_x": 1})))
    with pytest.raises(ValueError):
        EventValidation.validate(ev(properties=DataMap({"$weird": 1})))


def test_wire_roundtrip():
    e = Event(
        event="buy",
        entity_type="user",
        entity_id="u1",
        target_entity_type="item",
        target_entity_id="i3",
        properties=DataMap({"price": 9.99}),
        event_time=dt.datetime(2024, 1, 2, 3, 4, 5, 600000, tzinfo=UTC),
        pr_id="abc",
    )
    d = e.to_json_dict()
    assert d["eventTime"] == "2024-01-02T03:04:05.600Z"
    e2 = Event.from_json_dict(d)
    assert e2.event == e.event
    assert e2.entity_id == e.entity_id
    assert e2.target_entity_id == e.target_entity_id
    assert e2.properties == e.properties
    assert e2.event_time == e.event_time
    assert e2.pr_id == "abc"


def test_wire_requires_fields():
    with pytest.raises(ValueError):
        Event.from_json_dict({"event": "x", "entityType": "user"})


def test_wire_default_event_time_is_utc_now():
    e = Event.from_json_dict({"event": "x", "entityType": "u", "entityId": "1"})
    assert e.event_time.tzinfo is not None
    assert abs((dt.datetime.now(tz=UTC) - e.event_time).total_seconds()) < 5


def test_wire_rejects_naive_event_time():
    with pytest.raises(ValueError):
        Event.from_json_dict(
            {
                "event": "x",
                "entityType": "u",
                "entityId": "1",
                "eventTime": "2024-01-02T03:04:05",
            }
        )


def test_non_utc_offset_formats():
    t = dt.datetime(2024, 1, 2, 12, 0, 0, tzinfo=dt.timezone(dt.timedelta(hours=8)))
    assert format_event_time(t) == "2024-01-02T12:00:00.000+08:00"
