"""Webhook connector golden tests (ref ConnectorTestUtil.scala + per-connector
specs): JSON-in / event-JSON-out."""

import pytest

from predictionio_tpu.data.webhooks import (
    ConnectorException,
    connector_to_event,
)
from predictionio_tpu.data.webhooks.examples import (
    ExampleFormConnector,
    ExampleJsonConnector,
)
from predictionio_tpu.data.webhooks.mailchimp import MailChimpConnector
from predictionio_tpu.data.webhooks.segmentio import SegmentIOConnector


class TestSegmentIO:
    C = SegmentIOConnector()

    def test_identify(self):
        e = connector_to_event(
            self.C,
            {
                "version": "2",
                "type": "identify",
                "userId": "u1",
                "traits": {"email": "a@b.c"},
                "timestamp": "2024-05-01T10:00:00.000Z",
            },
        )
        assert e.event == "identify"
        assert e.entity_type == "user" and e.entity_id == "u1"
        assert e.properties.get("traits") == {"email": "a@b.c"}
        assert e.event_time.year == 2024

    def test_anonymous_id_fallback(self):
        e = connector_to_event(
            self.C, {"version": "2", "type": "page", "anonymousId": "anon-7"}
        )
        assert e.entity_id == "anon-7"

    def test_alias_group_screen(self):
        alias = self.C.to_event_json(
            {"version": "2", "type": "alias", "userId": "u", "previousId": "old"}
        )
        assert alias["properties"]["previous_id"] == "old"
        group = self.C.to_event_json(
            {"version": "2", "type": "group", "userId": "u", "groupId": "g1"}
        )
        assert group["properties"]["group_id"] == "g1"
        screen = self.C.to_event_json(
            {"version": "2", "type": "screen", "userId": "u", "name": "Home"}
        )
        assert screen["properties"]["name"] == "Home"

    def test_context_merged(self):
        out = self.C.to_event_json(
            {
                "version": "2",
                "type": "track",
                "userId": "u",
                "event": "X",
                "context": {"ip": "1.2.3.4"},
            }
        )
        assert out["properties"]["context"] == {"ip": "1.2.3.4"}

    def test_missing_version(self):
        with pytest.raises(ConnectorException):
            self.C.to_event_json({"type": "track", "userId": "u"})

    def test_missing_user(self):
        with pytest.raises(ConnectorException):
            self.C.to_event_json({"version": "2", "type": "track"})

    def test_unknown_type(self):
        with pytest.raises(ConnectorException):
            self.C.to_event_json({"version": "2", "type": "nope", "userId": "u"})


class TestMailChimp:
    C = MailChimpConnector()

    def test_unsubscribe(self):
        e = connector_to_event(
            self.C,
            {
                "type": "unsubscribe",
                "fired_at": "2009-03-26 21:40:57",
                "data[action]": "unsub",
                "data[reason]": "manual",
                "data[id]": "8a25ff1d98",
                "data[list_id]": "a6b5da1054",
                "data[email]": "api+unsub@mailchimp.com",
                "data[email_type]": "html",
                "data[merges][EMAIL]": "api+unsub@mailchimp.com",
                "data[merges][FNAME]": "MailChimp",
                "data[merges][LNAME]": "API",
                "data[campaign_id]": "cb398d21d2",
                "data[ip_opt]": "10.20.10.30",
            },
        )
        assert e.event == "unsubscribe"
        assert e.target_entity_id == "a6b5da1054"
        assert e.properties.get("action") == "unsub"

    def test_upemail_cleaned_campaign(self):
        up = self.C.to_event_json(
            {
                "type": "upemail",
                "fired_at": "2009-03-26 22:15:09",
                "data[list_id]": "a6b5da1054",
                "data[new_id]": "51da8c3259",
                "data[new_email]": "new@x.com",
                "data[old_email]": "old@x.com",
            }
        )
        assert up["event"] == "upemail" and up["entityType"] == "list"
        cleaned = self.C.to_event_json(
            {
                "type": "cleaned",
                "fired_at": "2009-03-26 22:01:00",
                "data[list_id]": "a6b5da1054",
                "data[campaign_id]": "4fjk2ma9xd",
                "data[reason]": "hard",
                "data[email]": "api+cleaned@mailchimp.com",
            }
        )
        assert cleaned["event"] == "cleaned"
        campaign = self.C.to_event_json(
            {
                "type": "campaign",
                "fired_at": "2009-03-26 21:31:21",
                "data[id]": "5aa2102003",
                "data[subject]": "Test Campaign Subject",
                "data[status]": "sent",
                "data[reason]": "",
                "data[list_id]": "a6b5da1054",
            }
        )
        assert campaign["entityType"] == "campaign"

    def test_unknown_type(self):
        with pytest.raises(ConnectorException):
            self.C.to_event_json({"type": "bogus", "fired_at": "2009-03-26 21:31:21"})

    def test_missing_type(self):
        with pytest.raises(ConnectorException):
            self.C.to_event_json({})


class TestExamples:
    def test_json_user_action(self):
        e = connector_to_event(
            ExampleJsonConnector(),
            {"type": "userAction", "userId": "u1", "properties": {"x": 1}},
        )
        assert e.event == "userAction" and e.properties.get("x") == 1

    def test_json_user_action_item(self):
        e = connector_to_event(
            ExampleJsonConnector(),
            {"type": "userActionItem", "action": "view", "userId": "u1", "itemId": "i1"},
        )
        assert e.event == "view" and e.target_entity_id == "i1"

    def test_form(self):
        e = connector_to_event(
            ExampleFormConnector(),
            {"type": "userAction", "userId": "u1", "price": "9.99"},
        )
        assert e.properties.get("price") == "9.99"
