"""Batch-view API tests (ref data/view/: DataView, LBatchView EventSeq).

Ref test models: the view code has no dedicated spec in the reference (it is
deprecated), so these pin the semantics SURVEY.md documents: strict-after
start-time predicate, ordered per-entity folds, DataView's hash-keyed cache.
"""

from __future__ import annotations

import datetime as dt
import warnings

import numpy as np
import pytest

from predictionio_tpu.data import view
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App

UTC = dt.timezone.utc
T0 = dt.datetime(2024, 1, 1, tzinfo=UTC)


def _ev(name, eid, day, props=None, target=None):
    return Event(
        event=name,
        entity_type="user",
        entity_id=eid,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=props or {},
        event_time=T0 + dt.timedelta(days=day),
    )


def _seq(events):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        return view.EventSeq(events)


class TestEventSeq:
    def test_deprecation_warned(self):
        with pytest.warns(DeprecationWarning):
            view.EventSeq([])

    def test_filter_event_and_entity_type(self):
        es = _seq([_ev("view", "u1", 0), _ev("buy", "u1", 1), _ev("view", "u2", 2)])
        assert len(es.filter(event="view")) == 2
        assert len(es.filter(event="buy", entity_type="user")) == 1

    def test_start_time_strictly_after(self):
        # ref ViewPredicates.getStartTimePredicate: excludes equality
        es = _seq([_ev("view", "u1", 0), _ev("view", "u1", 1)])
        assert len(es.filter(start_time=T0)) == 1
        assert len(es.filter(until_time=T0 + dt.timedelta(days=1))) == 1

    def test_aggregate_by_entity_ordered(self):
        # out-of-order input must fold in event-time order
        es = _seq(
            [
                _ev("buy", "u1", 2, props={"n": 3}),
                _ev("buy", "u1", 0, props={"n": 1}),
                _ev("buy", "u2", 1, props={"n": 5}),
            ]
        )
        folds = es.aggregate_by_entity_ordered(
            init=[], op=lambda acc, e: acc + [e.properties.get("n")]
        )
        assert folds == {"u1": [1, 3], "u2": [5]}

    def test_datamap_aggregator_set_unset_delete(self):
        agg = view.datamap_aggregator()
        acc = None
        acc = agg(acc, _ev("$set", "u1", 0, props={"a": 1, "b": 2}))
        acc = agg(acc, _ev("$set", "u1", 1, props={"b": 3}))
        acc = agg(acc, _ev("$unset", "u1", 2, props={"a": 0}))
        assert isinstance(acc, DataMap) and acc.fields == {"b": 3}
        assert agg(acc, _ev("$delete", "u1", 3)) is None
        # non-special events are ignored
        assert agg(acc, _ev("view", "u1", 4)).fields == {"b": 3}


@pytest.fixture
def app_with_events(memory_storage):
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "viewapp"))
    lev = memory_storage.get_l_events()
    for i in range(6):
        lev.insert(
            _ev("rate", f"u{i % 2}", i, props={"rating": float(i)}, target=f"i{i}"),
            app_id,
        )
    return memory_storage


class TestDataView:
    def test_create_and_cache(self, app_with_events, tmp_path):
        calls = []

        def convert(e: Event):
            calls.append(1)
            if e.properties.get("rating", 0) < 1:
                return None  # dropped rows
            return {
                "user": e.entity_id,
                "item": e.target_entity_id,
                "rating": e.properties["rating"],
            }

        until = T0 + dt.timedelta(days=30)
        cols = view.create(
            "viewapp",
            convert,
            until_time=until,
            name="ratings",
            version="v1",
            base_dir=str(tmp_path),
        )
        assert set(cols) == {"user", "item", "rating"}
        assert len(cols["rating"]) == 5  # one dropped by conversion
        assert cols["rating"].dtype == np.float64
        n_calls = len(calls)

        # second call: served from cache, conversion not re-run
        cols2 = view.create(
            "viewapp",
            convert,
            until_time=until,
            name="ratings",
            version="v1",
            base_dir=str(tmp_path),
        )
        assert len(calls) == n_calls
        np.testing.assert_array_equal(cols2["rating"], cols["rating"])

        # version bump invalidates
        view.create(
            "viewapp",
            convert,
            until_time=until,
            name="ratings",
            version="v2",
            base_dir=str(tmp_path),
        )
        assert len(calls) > n_calls

    def test_tuple_and_dataclass_records(self, app_with_events, tmp_path):
        cols = view.create(
            "viewapp",
            lambda e: (e.entity_id, e.properties.get("rating", 0.0)),
            until_time=T0 + dt.timedelta(days=30),
            name="tup",
            base_dir=str(tmp_path),
        )
        assert set(cols) == {"c0", "c1"}

    def test_channel_has_own_cache_key(self, app_with_events, tmp_path):
        # regression: channel_name must be part of the cache key or two
        # channels of the same app silently share one cached view
        from predictionio_tpu.data.storage.base import Channel

        st = app_with_events
        app = st.get_meta_data_apps().get_by_name("viewapp")
        st.get_meta_data_channels().insert(Channel(0, "mobile", app.id))
        until = T0 + dt.timedelta(days=30)
        default_cols = view.create(
            "viewapp",
            lambda e: {"u": e.entity_id},
            until_time=until,
            name="chan",
            base_dir=str(tmp_path),
        )
        assert len(default_cols["u"]) == 6
        mobile_cols = view.create(
            "viewapp",
            lambda e: {"u": e.entity_id},
            channel_name="mobile",
            until_time=until,
            name="chan",
            base_dir=str(tmp_path),
        )
        assert mobile_cols == {}  # empty channel, not the default's cache

    def test_no_until_time_caches_on_version_stamp(self, app_with_events, tmp_path):
        """until_time=None must key on the store's version stamp, not
        wall-clock 'now' (which can never hit and leaves an npz per call):
        unchanged store -> cache hit; new event -> fresh scan; the view dir
        stays bounded (code-review r4)."""
        import os

        calls = []

        def convert(e: Event):
            calls.append(1)
            return {"u": e.entity_id}

        kw = dict(name="nowless", base_dir=str(tmp_path))
        cols = view.create("viewapp", convert, **kw)
        assert len(cols["u"]) == 6
        n1 = len(calls)
        cols2 = view.create("viewapp", convert, **kw)  # unchanged -> HIT
        assert len(calls) == n1
        assert len(cols2["u"]) == 6
        # a new event changes the stamp -> fresh scan sees 7 rows
        st = app_with_events
        app = st.get_meta_data_apps().get_by_name("viewapp")
        st.get_l_events().insert(_ev("rate", "u9", 9, target="i9"), app.id)
        cols3 = view.create("viewapp", convert, **kw)
        assert len(cols3["u"]) == 7 and len(calls) > n1
        # the directory is bounded, not one file per call
        files = [f for f in os.listdir(tmp_path / "view") if f.startswith("nowless-")]
        assert len(files) <= 4

    def test_none_stamp_bypasses_cache(self, app_with_events, tmp_path, monkeypatch):
        """A backend that cannot stamp cheaply (version_stamp() -> None,
        the documented base-class default) must BYPASS the cache, not key
        on the constant 'stamp:None' — which served the first npz forever
        while new events accumulated (advisor r4)."""
        import os

        calls = []

        def convert(e: Event):
            calls.append(1)
            return {"u": e.entity_id}

        st = app_with_events
        p_events = st.get_p_events()
        monkeypatch.setattr(
            type(p_events), "version_stamp", lambda self, a, c=None: None
        )
        kw = dict(name="nostamp", base_dir=str(tmp_path))
        cols = view.create("viewapp", convert, **kw)
        assert len(cols["u"]) == 6
        n1 = len(calls)
        # second call must RESCAN (no false cache hit) and see new events
        app = st.get_meta_data_apps().get_by_name("viewapp")
        st.get_l_events().insert(_ev("rate", "u9", 9, target="i9"), app.id)
        cols2 = view.create("viewapp", convert, **kw)
        assert len(cols2["u"]) == 7 and len(calls) > n1
        # nothing was written for the uncacheable view
        view_dir = tmp_path / "view"
        if view_dir.exists():
            assert not [f for f in os.listdir(view_dir) if f.startswith("nostamp-")]

    def test_prune_spares_explicit_until_time_views(self, app_with_events, tmp_path):
        """Explicit-until_time views are immutable and valid forever; a
        workload alternating among >4 fixed windows must keep hitting the
        cache (advisor r4: the prune kept only the 4 newest npz per
        prefix, including immutable window views)."""
        calls = []

        def convert(e: Event):
            calls.append(1)
            return {"u": e.entity_id}

        windows = [T0 + dt.timedelta(days=d) for d in range(1, 8)]
        kw = dict(name="win", base_dir=str(tmp_path))
        for w in windows:
            view.create("viewapp", convert, until_time=w, **kw)
        n1 = len(calls)
        # every one of the 7 windows is still cached: zero re-scans
        for w in windows:
            view.create("viewapp", convert, until_time=w, **kw)
        assert len(calls) == n1
        # stamp-keyed entries are still bounded (prune applies to them)
        for _ in range(6):
            st = app_with_events
            app = st.get_meta_data_apps().get_by_name("viewapp")
            st.get_l_events().insert(_ev("rate", "u8", 8, target="i8"), app.id)
            view.create("viewapp", convert, **kw)
        import os

        stamped = [
            f for f in os.listdir(tmp_path / "view") if f.startswith("win-viewapp-stamp-")
        ]
        assert 0 < len(stamped) <= 4

    def test_legacy_unmarked_entries_swept_not_orphaned(
        self, app_with_events, tmp_path
    ):
        """Pre-marker npz files (written before the stamp-/t- naming) can
        never be cache-hit again; the prune must delete them instead of
        letting them accumulate forever (code-review r5)."""
        import os

        view_dir = tmp_path / "view"
        view_dir.mkdir()
        legacy = view_dir / ("leg-viewapp-" + "ab" * 8 + ".npz")
        legacy.write_bytes(b"legacy")
        view.create(
            "viewapp", lambda e: {"u": e.entity_id}, name="leg",
            base_dir=str(tmp_path),
        )
        names = os.listdir(view_dir)
        assert legacy.name not in names  # swept
        assert any(n.startswith("leg-viewapp-stamp-") for n in names)

    def test_prefix_collision_files_untouched(self, app_with_events, tmp_path):
        """One view's prune must never delete files of a DIFFERENT view
        whose name merely extends this one's prefix ('als-prod-' is a
        string prefix of 'als-prod-eu-...'): only tails that are exactly
        <marker><16-hex>.npz belong to this view (code-review r5)."""
        import os

        view_dir = tmp_path / "view"
        view_dir.mkdir()
        # files of the colliding view "leg" for app "viewapp-eu": both an
        # immutable window entry and a stamp entry, plus its legacy form
        other = [
            view_dir / ("leg-viewapp-eu-t-" + "cd" * 8 + ".npz"),
            view_dir / ("leg-viewapp-eu-stamp-" + "ef" * 8 + ".npz"),
            view_dir / ("leg-viewapp-eu-" + "0a" * 8 + ".npz"),
        ]
        for p in other:
            p.write_bytes(b"other-view")
        view.create(
            "viewapp", lambda e: {"u": e.entity_id}, name="leg",
            base_dir=str(tmp_path),
        )
        names = os.listdir(view_dir)
        for p in other:
            assert p.name in names, f"{p.name} was wrongly deleted"

    def test_empty_result(self, app_with_events, tmp_path):
        cols = view.create(
            "viewapp",
            lambda e: None,
            until_time=T0 + dt.timedelta(days=30),
            name="none",
            base_dir=str(tmp_path),
        )
        assert cols == {}
