"""Serving fleet tests (docs/fleet.md): gateway, supervisor, coordination.

Covers the subsystem at three tiers:

- units — metrics federation math, routing/ejection/readmission with
  fake replicas, retry semantics (once, different replica, never on 4xx,
  never for non-idempotent admin posts, budget-bounded), supervisor
  restart backoff and the crash-loop budget, worker argv derivation;
- in-process integration — registry state-generation propagation between
  two QueryServers sharing one registry (stage/promote/rollback adopted
  cross-process), the graceful drain path answering in-flight queries;
- e2e (slow, run by scripts/run_chaos.sh) — the kill-mid-rollout chaos
  stage: real worker processes behind a real gateway under load, one
  SIGKILLed mid-bake, asserting ZERO 5xx, ejection within the probe
  interval, supervisor restart + readmission, and bake-gate convergence.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from predictionio_tpu.fleet import (
    Gateway,
    GatewayConfig,
    Supervisor,
    SupervisorConfig,
    WorkerSpec,
    federate_metrics,
)
from predictionio_tpu.fleet.launch import worker_argv
from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.registry import ArtifactStore, ModelManifest
from predictionio_tpu.resilience import CLOSED, OPEN

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# metrics federation
# ---------------------------------------------------------------------------


class TestFederation:
    def test_counters_sum_across_replicas(self):
        a = 'pio_requests_total{endpoint="/q",status="200"} 3\n'
        b = (
            'pio_requests_total{endpoint="/q",status="200"} 4\n'
            'pio_requests_total{endpoint="/q",status="503"} 1\n'
        )
        merged = federate_metrics([a, b])
        from predictionio_tpu.tools.top import parse_prometheus

        samples = dict(
            (labels["status"], v)
            for labels, v in parse_prometheus(merged)["pio_requests_total"]
        )
        assert samples == {"200": 7.0, "503": 1.0}

    def test_histograms_merge_bucketwise(self):
        """Two replicas' histograms merge by adding cumulative bucket
        counts — the federated quantile is the fleet-wide quantile."""
        regs = [MetricsRegistry(), MetricsRegistry()]
        for i, reg in enumerate(regs):
            h = reg.histogram("pio_gw_seconds", "t")
            for _ in range(10):
                h.observe(0.002 if i == 0 else 0.2)
        merged = federate_metrics([r.render_prometheus() for r in regs])
        from predictionio_tpu.tools.top import (
            _histogram_quantile,
            parse_prometheus,
        )

        metrics = parse_prometheus(merged)
        count = metrics["pio_gw_seconds_count"][0][1]
        assert count == 20.0
        # 10 fast + 10 slow: the median sits between the two modes and the
        # p95 lands in the slow mode — only true if buckets really merged
        assert _histogram_quantile(metrics, "pio_gw_seconds", 0.95) > 0.1
        assert _histogram_quantile(metrics, "pio_gw_seconds", 0.25) < 0.01
        # TYPE declared exactly once
        assert merged.count("# TYPE pio_gw_seconds histogram") == 1

    def test_disjoint_series_pass_through(self):
        merged = federate_metrics(["only_a 1\n", "only_b 2\n"])
        assert "only_a 1" in merged and "only_b 2" in merged

    def test_openmetrics_exemplars_and_eof_never_corrupt_the_sum(self):
        """A replica scraped with ?exemplars=1 decorates bucket lines
        with ` # {trace_id=...} v` and ends with `# EOF` — the merge must
        strip both from the VALUE math (the same " # " split `pio top`
        uses), or the series-wise sum silently corrupts."""
        a = (
            "# TYPE pio_phase_seconds histogram\n"
            'pio_phase_seconds_bucket{le="0.01",phase="fetch"} 5'
            ' # {trace_id="aaa"} 0.003\n'
            'pio_phase_seconds_bucket{le="+Inf",phase="fetch"} 7'
            ' # {trace_id="bbb"} 0.2\n'
            'pio_phase_seconds_sum{phase="fetch"} 0.5\n'
            'pio_phase_seconds_count{phase="fetch"} 7\n'
            "# EOF\n"
        )
        b = a.replace(" 5 ", " 3 ").replace('"aaa"', '"ccc"')
        merged = federate_metrics([a, b])
        assert (
            'pio_phase_seconds_bucket{le="0.01",phase="fetch"} 8' in merged
        )
        assert 'pio_phase_seconds_bucket{le="+Inf",phase="fetch"} 14' in merged
        assert 'pio_phase_seconds_sum{phase="fetch"} 1' in merged
        # plain merge stays strict v0.0.4: no clauses, no EOF
        assert " # " not in merged and "# EOF" not in merged

    def test_exemplar_clauses_carried_when_negotiated(self):
        """With exemplars=True the clauses survive the merge (last input
        wins per series) and the output is OpenMetrics-terminated — a
        federated p99 exemplar still names a concrete trace id."""
        a = (
            'pio_phase_seconds_bucket{le="0.01",phase="fetch"} 5'
            ' # {trace_id="aaa"} 0.003\n'
        )
        b = a.replace(" 5 ", " 3 ").replace('"aaa"', '"ccc"')
        merged = federate_metrics([a, b], exemplars=True)
        assert (
            'pio_phase_seconds_bucket{le="0.01",phase="fetch"} 8'
            ' # {trace_id="ccc"} 0.003' in merged
        )
        assert merged.rstrip().endswith("# EOF")


# ---------------------------------------------------------------------------
# gateway: fake replicas over real sockets
# ---------------------------------------------------------------------------


class FakeReplica:
    """A stand-in QueryServer: answers /queries.json with its own name,
    exposes /healthz (toggleable), /metrics (its query count), and the
    rollout admin posts (counted, optionally failing)."""

    def __init__(self, name: str):
        self.name = name
        self.queries = 0
        self.ready = True
        self.fail_status: int | None = None
        self.delay_s = 0.0
        self.admin_hits = 0
        self.server: TestServer | None = None

    def make_app(self) -> web.Application:
        app = web.Application()

        async def queries(request: web.Request) -> web.Response:
            self.queries += 1
            if self.delay_s:
                await asyncio.sleep(self.delay_s)
            if self.fail_status:
                return web.json_response(
                    {"message": "injected"}, status=self.fail_status
                )
            body = await request.json()
            return web.json_response({"replica": self.name, "echo": body})

        async def healthz(request: web.Request) -> web.Response:
            return web.json_response(
                {"ready": self.ready}, status=200 if self.ready else 503
            )

        async def metrics(request: web.Request) -> web.Response:
            return web.Response(
                text=(
                    "pio_requests_total"
                    f'{{endpoint="/queries.json",status="200"}} {self.queries}\n'
                )
            )

        async def admin(request: web.Request) -> web.Response:
            self.admin_hits += 1
            if self.fail_status:
                return web.json_response(
                    {"message": "injected"}, status=self.fail_status
                )
            return web.json_response({"message": "ok", "replica": self.name})

        app.add_routes(
            [
                web.post("/queries.json", queries),
                web.get("/healthz", healthz),
                web.get("/metrics", metrics),
                web.get("/models", admin),
                web.post("/models/{action}", admin),
            ]
        )
        return app

    async def start(self) -> str:
        self.server = TestServer(self.make_app())
        await self.server.start_server()
        return f"http://127.0.0.1:{self.server.port}"

    async def stop(self) -> None:
        if self.server is not None:
            await self.server.close()


def _gateway_rig(n_replicas: int = 2, **cfg_kw):
    """(replicas, start coroutine factory) — the start coroutine yields
    (gateway, client) with everything running and probed once."""
    replicas = [FakeReplica(f"r{i}") for i in range(n_replicas)]

    async def start(body):
        urls = [await r.start() for r in replicas]
        cfg_kw.setdefault("probe_interval_s", 0.05)
        cfg_kw.setdefault("probe_timeout_s", 1.0)
        cfg_kw.setdefault("request_timeout_s", 5.0)
        gw = Gateway(
            GatewayConfig(replica_urls=tuple(urls), **cfg_kw)
        )
        client = TestClient(TestServer(gw.make_app()))
        await client.start_server()
        try:
            await asyncio.sleep(0.1)  # first probe pass
            await body(gw, client)
        finally:
            await client.close()
            for r in replicas:
                await r.stop()

    def run(body):
        asyncio.run(start(body))

    return replicas, run


class TestGatewayRouting:
    def test_queries_spread_and_answer(self):
        replicas, run = _gateway_rig(2)

        async def body(gw, client):
            for i in range(12):
                resp = await client.post(
                    "/queries.json", json={"user": f"u{i}", "num": 3}
                )
                assert resp.status == 200
                data = await resp.json()
                assert data["echo"]["user"] == f"u{i}"
            assert replicas[0].queries + replicas[1].queries == 12
            # the consistent hash spreads distinct users over both
            assert replicas[0].queries >= 1 and replicas[1].queries >= 1

        run(body)

    def test_same_user_sticks_while_loads_equal(self):
        replicas, run = _gateway_rig(2)

        async def body(gw, client):
            for _ in range(6):
                resp = await client.post(
                    "/queries.json", json={"user": "sticky-user"}
                )
                assert resp.status == 200
            counts = sorted((replicas[0].queries, replicas[1].queries))
            assert counts == [0, 6]  # one replica took every request

        run(body)

    def test_least_loaded_beats_hash_under_load(self):
        replicas, run = _gateway_rig(2)

        async def body(gw, client):
            for r in replicas:
                r.delay_s = 0.25
            t1 = asyncio.ensure_future(
                client.post("/queries.json", json={"user": "same"})
            )
            await asyncio.sleep(0.1)  # t1 is in flight on its replica
            t2 = asyncio.ensure_future(
                client.post("/queries.json", json={"user": "same"})
            )
            r1, r2 = await asyncio.gather(t1, t2)
            assert r1.status == 200 and r2.status == 200
            # same sticky key, but the occupied replica was skipped
            assert (replicas[0].queries, replicas[1].queries) == (1, 1)

        run(body)

    def test_ejection_and_readmission_via_probes(self):
        replicas, run = _gateway_rig(2)

        async def body(gw, client):
            replicas[0].ready = False
            await _poll(
                lambda: not gw.replicas[0].healthy, "ejection never happened"
            )
            before = replicas[0].queries
            for i in range(6):
                resp = await client.post(
                    "/queries.json", json={"user": f"u{i}"}
                )
                assert resp.status == 200
            assert replicas[0].queries == before  # no traffic while ejected
            health = await (await client.get("/healthz")).json()
            assert health["replicasHealthy"] == 1
            replicas[0].ready = True
            await _poll(
                lambda: gw.replicas[0].healthy, "readmission never happened"
            )
            assert gw._m_ejections.value(replica=gw.replicas[0].name) == 1
            assert gw._m_readmissions.value(replica=gw.replicas[0].name) == 1

        run(body)

    def test_probe_blackout_routes_in_panic_mode(self):
        """A replica that fails its probe but still answers (probe
        timeout under load, not death) keeps serving: with EVERY replica
        ejected, routing ignores health rather than shedding."""
        replicas, run = _gateway_rig(1)

        async def body(gw, client):
            replicas[0].ready = False
            await _poll(lambda: not gw.replicas[0].healthy, "no ejection")
            resp = await client.post("/queries.json", json={"user": "u"})
            assert resp.status == 200  # panic pick, not a 503 shed
            assert gw._m_panic.value() >= 1
            # /healthz still reports the fleet unready — panic routing
            # serves traffic, it does not mask the outage signal
            health = await client.get("/healthz")
            assert health.status == 503

        run(body)

    def test_all_replicas_down_sheds_with_retry_after(self):
        replicas, run = _gateway_rig(1)

        async def body(gw, client):
            replicas[0].ready = False
            await _poll(lambda: not gw.replicas[0].healthy, "no ejection")
            # breaker open too: the replica is truly gone, panic routing
            # has nowhere left to try and the query is shed
            for _ in range(gw.config.breaker_threshold):
                gw.replicas[0].breaker.record_failure()
            resp = await client.post("/queries.json", json={"user": "u"})
            assert resp.status == 503
            assert "Retry-After" in resp.headers
            health = await client.get("/healthz")
            assert health.status == 503
            assert gw._m_no_replica.value() >= 1

        run(body)


class TestGatewayRetry:
    def test_5xx_retries_once_on_a_different_replica(self):
        replicas, run = _gateway_rig(2, breaker_threshold=3)

        async def body(gw, client):
            replicas[0].fail_status = 500
            for i in range(10):
                resp = await client.post(
                    "/queries.json", json={"user": f"u{i}"}
                )
                assert resp.status == 200  # failures masked by failover
                assert (await resp.json())["replica"] == "r1"
            assert gw._m_retries.value() >= 1
            # three consecutive 500s opened r0's breaker: traffic stopped
            # reaching it long before the 10th request
            assert gw.replicas[0].breaker.snapshot()["state"] == OPEN
            assert replicas[0].queries <= 4

        run(body)

    def test_connection_error_retries_then_503_when_alone(self):
        """Transport failure on the only replica: no second replica to
        retry on -> clean 503, not a hang or a raw exception."""
        replicas, run = _gateway_rig(1)

        async def body(gw, client):
            import aiohttp as _aiohttp

            async def dead_forward(replica, method, path, body_b, headers):
                raise _aiohttp.ClientConnectionError("replica vanished")

            gw._forward = dead_forward
            resp = await client.post("/queries.json", json={"user": "u"})
            assert resp.status == 503

        run(body)

    def test_4xx_passes_through_untouched(self):
        replicas, run = _gateway_rig(1)

        async def body(gw, client):
            replicas[0].fail_status = 400
            for _ in range(5):
                resp = await client.post("/queries.json", json={"user": "u"})
                assert resp.status == 400  # the client's error, not ours
            assert gw._m_retries.value() == 0
            # a 4xx is a healthy replica doing its job: breaker untouched
            assert gw.replicas[0].breaker.snapshot()["state"] == CLOSED
            assert replicas[0].queries == 5

        run(body)

    def test_admin_posts_never_double_dispatch(self):
        """Non-idempotent surface: a failing promote is relayed, not
        retried — exactly ONE replica saw the request."""
        replicas, run = _gateway_rig(2)

        async def body(gw, client):
            for r in replicas:
                r.fail_status = 500
            resp = await client.post("/models/promote", json={})
            assert resp.status == 500  # the replica's own answer, relayed
            assert replicas[0].admin_hits + replicas[1].admin_hits == 1
            assert gw._m_retries.value() == 0

        run(body)

    def test_retry_budget_bounds_failover(self):
        """With the budget drained, a forward failure surfaces instead of
        doubling load on the survivors."""
        replicas, run = _gateway_rig(2, breaker_threshold=100)

        async def body(gw, client):
            replicas[0].fail_status = 503
            gw.retry_budget._tokens = 0.0  # drained (ratio tops it up slowly)
            gw.retry_budget.ratio = 0.0
            statuses = set()
            for i in range(12):
                resp = await client.post(
                    "/queries.json", json={"user": f"u{i}"}
                )
                statuses.add(resp.status)
            assert gw._m_retries.value() == 0
            assert 503 in statuses  # r0's failures surfaced un-retried

        run(body)


class TestGatewayFederationAndDrain:
    def test_metrics_federates_replicas_plus_gateway(self):
        replicas, run = _gateway_rig(2)

        async def body(gw, client):
            for i in range(4):
                await client.post("/queries.json", json={"user": f"u{i}"})
            text = await (await client.get("/metrics")).text()
            from predictionio_tpu.tools.top import parse_prometheus, _total

            metrics = parse_prometheus(text)
            # replicas' own request counters summed to the fleet total
            assert (
                _total(metrics, "pio_requests_total", endpoint="/queries.json")
                == 4.0
            )
            # gateway-side instruments ride the same exposition
            assert _total(metrics, "pio_fleet_replicas") == 2.0
            up = {
                labels["replica"]: v
                for labels, v in metrics["pio_fleet_replica_up"]
            }
            assert len(up) == 2 and all(v == 1.0 for v in up.values())
            assert "pio_gateway_request_seconds_bucket" in metrics

        run(body)

    def test_top_fleet_line_renders_from_federated_scrape(self):
        replicas, run = _gateway_rig(2)

        async def body(gw, client):
            await client.post("/queries.json", json={"user": "u"})
            text = await (await client.get("/metrics")).text()
            from predictionio_tpu.tools.top import (
                parse_prometheus,
                render,
                summarize,
            )

            summary = summarize(parse_prometheus(text))
            assert summary["fleet"] is not None
            assert summary["fleet"]["replicas_total"] == 2.0
            assert summary["fleet"]["replicas_up"] == 2.0
            screen = render(summary, "http://gw")
            assert "fleet" in screen and "2/2 up" in screen

        run(body)

    def test_drain_answers_keepalive_and_inflight_5xx_free(self):
        replicas, run = _gateway_rig(1, drain_grace_s=5.0)

        async def body(gw, client):
            replicas[0].delay_s = 0.3
            inflight = asyncio.ensure_future(
                client.post("/queries.json", json={"user": "u"})
            )
            await asyncio.sleep(0.1)
            drain = asyncio.ensure_future(gw.drain())
            await asyncio.sleep(0.05)
            # a request arriving on an established keep-alive connection
            # mid-drain is ANSWERED (the 5xx-free contract) with
            # Connection: close so the client migrates
            straggler = await client.post("/queries.json", json={"user": "v"})
            assert straggler.status == 200
            assert straggler.headers.get("Connection") == "close"
            resp = await inflight
            assert resp.status == 200  # ... and in-flight answered
            await drain
            assert gw._inflight_requests == 0
            # /healthz signals not-ready the whole time, so load
            # balancers route around the draining gateway
            hz = await client.get("/healthz")
            assert hz.status == 503

        run(body)


async def _poll(cond, message: str, deadline_s: float = 5.0) -> None:
    deadline = time.monotonic() + deadline_s
    while not cond():
        assert time.monotonic() < deadline, message
        await asyncio.sleep(0.02)


class TestTopMultiEndpoint:
    """Satellite: `pio top --json` over several --metrics-url endpoints
    emits ONE object per endpoint per refresh, with per-endpoint rate
    state and per-endpoint error isolation."""

    def _fetch(self, texts: dict[str, str]):
        def fetch(url: str) -> str:
            result = texts[url]
            if isinstance(result, Exception):
                raise result
            return result

        return fetch

    def test_one_json_object_per_endpoint_per_refresh(self):
        from predictionio_tpu.tools.top import run_top

        texts = {
            "http://a": "pio_requests_total 5\n",
            "http://b": "pio_requests_total 9\n",
        }
        out: list[str] = []
        rc = run_top(
            "http://ignored",
            urls=["http://a", "http://b"],
            iterations=2,
            interval_s=0.0,
            fetch=self._fetch(texts),
            out=out.append,
            sleep=lambda s: None,
            json_mode=True,
        )
        assert rc == 0
        objs = [json.loads(line) for line in out]
        assert len(objs) == 4  # 2 endpoints x 2 refreshes
        assert [o["url"] for o in objs] == [
            "http://a",
            "http://b",
            "http://a",
            "http://b",
        ]
        assert all(o["requests_total"] in (5.0, 9.0) for o in objs)
        # second refresh has per-endpoint rate state (qps computed)
        assert objs[2]["qps"] is not None and objs[3]["qps"] is not None

    def test_unreachable_endpoint_degrades_only_its_own_line(self):
        from predictionio_tpu.tools.top import run_top

        texts = {
            "http://a": "pio_requests_total 5\n",
            "http://b": OSError("connection refused"),
        }
        out: list[str] = []
        run_top(
            "http://ignored",
            urls=["http://a", "http://b"],
            iterations=1,
            fetch=self._fetch(texts),
            out=out.append,
            sleep=lambda s: None,
            json_mode=True,
        )
        objs = [json.loads(line) for line in out]
        assert len(objs) == 2
        assert objs[0]["url"] == "http://a" and "requests_total" in objs[0]
        assert objs[1]["url"] == "http://b" and "error" in objs[1]

    def test_single_url_screen_mode_unchanged(self):
        from predictionio_tpu.tools.top import run_top

        out: list[str] = []
        run_top(
            "http://a",
            iterations=1,
            fetch=self._fetch({"http://a": "pio_requests_total 5\n"}),
            out=out.append,
            sleep=lambda s: None,
            clear_screen=False,
        )
        assert len(out) == 1 and "pio top — http://a" in out[0]


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


class FakeProc:
    _pid = 1000

    def __init__(self, ignore_term: bool = False):
        self.rc: int | None = None
        FakeProc._pid += 1
        self.pid = FakeProc._pid
        self.terminated = False
        self.killed = False
        self.ignore_term = ignore_term

    def poll(self):
        return self.rc

    def exit(self, rc: int = 1):
        self.rc = rc

    def terminate(self):
        self.terminated = True
        if not self.ignore_term:
            self.rc = -15

    def kill(self):
        self.killed = True
        self.rc = -9


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def _supervisor(cfg: SupervisorConfig, n: int = 1, ignore_term: bool = False):
    clock = FakeClock()
    spawned: list[FakeProc] = []

    def spawn(spec):
        p = FakeProc(ignore_term=ignore_term)
        spawned.append(p)
        return p

    sup = Supervisor(
        spawn,
        [WorkerSpec(f"w{i}", 9000 + i) for i in range(n)],
        cfg,
        clock=clock,
    )
    return sup, spawned, clock


class TestSupervisor:
    def test_restart_backoff_grows_exponentially(self):
        sup, spawned, clock = _supervisor(
            SupervisorConfig(
                backoff_base_s=1.0,
                backoff_multiplier=2.0,
                backoff_max_s=8.0,
                crash_loop_window_s=1e9,
                crash_loop_budget=99,
                healthy_reset_s=30.0,
            )
        )
        sup.start()
        assert len(spawned) == 1
        spawned[-1].exit(1)
        sup.tick()  # reap: schedules restart at +1.0
        sup.tick()
        assert len(spawned) == 1  # backoff not elapsed
        clock.advance(1.0)
        sup.tick()
        assert len(spawned) == 2
        spawned[-1].exit(1)
        sup.tick()  # second consecutive crash: backoff now 2.0
        clock.advance(1.0)
        sup.tick()
        assert len(spawned) == 2  # 1.0 < 2.0: still waiting
        clock.advance(1.0)
        sup.tick()
        assert len(spawned) == 3
        assert sup._m_restarts.value(replica="w0") == 2

    def test_healthy_uptime_resets_the_backoff_ladder(self):
        sup, spawned, clock = _supervisor(
            SupervisorConfig(
                backoff_base_s=1.0,
                backoff_multiplier=2.0,
                backoff_max_s=64.0,
                crash_loop_window_s=1e9,
                crash_loop_budget=99,
                healthy_reset_s=10.0,
            )
        )
        sup.start()
        spawned[-1].exit(1)
        sup.tick()
        clock.advance(1.0)
        sup.tick()  # restart #1 (next crash would back off 2.0)
        clock.advance(10.0)
        sup.tick()  # healthy long enough: ladder resets
        spawned[-1].exit(1)
        sup.tick()
        clock.advance(1.0)  # base backoff again, NOT 2.0
        sup.tick()
        assert len(spawned) == 3

    def test_crash_loop_budget_parks_the_worker(self):
        sup, spawned, clock = _supervisor(
            SupervisorConfig(
                backoff_base_s=0.0,
                crash_loop_window_s=100.0,
                crash_loop_budget=2,
                healthy_reset_s=1e9,
            )
        )
        sup.start()
        for _ in range(2):
            spawned[-1].exit(1)
            sup.tick()  # reap
            sup.tick()  # respawn (zero backoff)
        spawned[-1].exit(1)
        sup.tick()  # third exit in the window: over budget
        snap = sup.snapshot()[0]
        assert snap["parked"] is True
        clock.advance(1000.0)
        sup.tick()
        assert len(spawned) == 3  # parked: never respawned
        assert sup._m_crash_loops.value(replica="w0") == 1

    def test_stop_escalates_term_to_kill(self):
        sup, spawned, clock = _supervisor(
            SupervisorConfig(term_grace_s=0.0), ignore_term=True
        )
        sup.start()
        sup.stop()
        assert spawned[0].terminated and spawned[0].killed

    def test_stop_graceful_when_term_honored(self):
        sup, spawned, clock = _supervisor(SupervisorConfig(term_grace_s=5.0))
        sup.start()
        sup.stop()
        assert spawned[0].terminated and not spawned[0].killed


class TestWorkerArgv:
    def test_strips_fleet_topology_flags_and_appends_worker_port(self):
        argv = [
            "deploy",
            "--engine-dir",
            "eng",
            "--fleet",
            "3",
            "--ip",
            "0.0.0.0",
            "--port",
            "8000",
            "--registry-dir",
            "reg",
        ]
        out = worker_argv(argv, 8001, 0.5)
        assert out[:4] == [
            sys.executable,
            "-m",
            "predictionio_tpu.tools.cli",
            "deploy",
        ]
        assert "--fleet" not in out
        assert "--registry-dir" in out and "reg" in out
        assert out[out.index("--port") + 1] == "8001"
        assert out[out.index("--ip") + 1] == "127.0.0.1"
        assert out[out.index("--registry-sync-interval") + 1] == "0.5"

    def test_handles_equals_spelling(self):
        out = worker_argv(
            ["deploy", "--fleet=3", "--port=8000", "--accesskey=k"], 9001, 1.0
        )
        assert not any(a.startswith("--fleet") for a in out)
        assert "--accesskey=k" in out
        assert out[out.index("--port") + 1] == "9001"


# ---------------------------------------------------------------------------
# registry state generation + cross-process coordination
# ---------------------------------------------------------------------------


class TestStateGeneration:
    def _publish(self, store: ArtifactStore, engine_id: str = "e") -> str:
        m = store.publish(
            ModelManifest(
                version="",
                engine_id=engine_id,
                engine_version="1",
                engine_variant="v",
            ),
            b"blob-%d" % store.state_generation(engine_id),
        )
        return m.version

    def test_bumps_on_every_state_transition(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        assert store.state_generation("e") == 0
        v1 = self._publish(store)  # publish + auto-stable
        g1 = store.state_generation("e")
        assert g1 >= 1
        v2 = self._publish(store)
        g2 = store.state_generation("e")
        assert g2 > g1
        store.stage_candidate("e", v2, mode="canary", fraction=0.2)
        g3 = store.state_generation("e")
        assert g3 > g2
        store.promote("e")
        g4 = store.state_generation("e")
        assert g4 > g3
        store.rollback("e")  # previous-stable revert
        assert store.state_generation("e") > g4
        assert store.get_state("e").stable == v1

    def test_transitions_serialized_across_store_instances(self, tmp_path):
        """Fleet workers are concurrent registry writers; the flock-backed
        state mutex must serialize a transition from one store instance
        (= process: flock is per-open-file-description) against another's."""
        import threading

        a = ArtifactStore(str(tmp_path))
        b = ArtifactStore(str(tmp_path))
        v1 = self._publish(a)
        v2 = self._publish(a)
        entered = threading.Event()
        release = threading.Event()
        done = threading.Event()

        def hold_lock():
            with a._state_mutex("e"):
                entered.set()
                release.wait(5.0)
            done.set()

        t = threading.Thread(target=hold_lock)
        t.start()
        assert entered.wait(5.0)
        # b's transition must BLOCK while a (another "process") holds the
        # transition lock
        result: dict = {}

        def transition():
            result["state"] = b.stage_candidate("e", v2, fraction=0.2)

        t2 = threading.Thread(target=transition)
        t2.start()
        t2.join(0.3)
        assert t2.is_alive(), "stage did not wait for the cross-process lock"
        release.set()
        t2.join(5.0)
        t.join(5.0)
        assert not t2.is_alive() and done.is_set()
        assert result["state"].candidate == v2
        assert a.get_state("e").candidate == v2 and a.get_state("e").stable == v1

    def test_concurrent_writers_never_collide_on_a_generation(self, tmp_path):
        """Read-modify-write hammer from two store instances: every
        persisted save must land its own generation number (a lost update
        shows up as final generation < number of saves)."""
        import threading

        a = ArtifactStore(str(tmp_path))
        b = ArtifactStore(str(tmp_path))
        v2 = (self._publish(a), self._publish(a))[1]
        base_gen = a.state_generation("e")
        saves = []
        for store in (a, b):
            orig = store._save_state

            def counted(engine_id, state, _orig=orig):
                _orig(engine_id, state)
                saves.append(state.generation)

            store._save_state = counted

        def hammer(store):
            for _ in range(25):
                store.stage_candidate("e", v2, fraction=0.1)
                store.unstage("e", reason="test")

        threads = [threading.Thread(target=hammer, args=(s,)) for s in (a, b)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert a.state_generation("e") == base_gen + len(saves)
        # and every save got a DISTINCT generation — no collisions
        assert len(set(saves)) == len(saves)

    def test_generation_survives_reload_from_disk(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        self._publish(store)
        gen = store.state_generation("e")
        assert ArtifactStore(str(tmp_path)).state_generation("e") == gen


def _synced_pair(tmp_path, **cfg_kw):
    """Two QueryServers sharing one registry (the fleet topology, in one
    process): v000001 pinned stable, v000002 published and stageable."""
    from predictionio_tpu.workflow.create_server import (
        ServerConfig,
        _query_server_from_registry,
    )
    from tests.test_registry import (
        _engine_manifest,
        _memory_storage,
        _mk_engine,
        _train_version,
    )

    storage = _memory_storage()
    registry_dir = str(tmp_path / "registry")
    _train_version(storage, registry_dir, algo_id=3)  # v000001, auto-stable
    _train_version(storage, registry_dir, algo_id=5)  # v000002
    store = ArtifactStore(registry_dir)
    cfg_kw.setdefault("bake_check_interval_s", 30.0)
    cfg_kw.setdefault("request_timeout_s", 5.0)

    def mk():
        return _query_server_from_registry(
            _mk_engine(),
            _engine_manifest(),
            store,
            "v000001",
            storage,
            ServerConfig(**cfg_kw),
        )

    return mk(), mk(), store


class TestRegistrySync:
    def test_stage_on_one_replica_propagates(self, tmp_path):
        a, b, store = _synced_pair(tmp_path)

        async def body():
            lane = a._load_lane_from_registry("v000002")
            a.stage_candidate_lane(lane, mode="canary", fraction=0.25)
            await b._registry_sync_tick()
            assert b._candidate is not None
            assert b._candidate.version == "v000002"
            assert b._plan.mode == "canary"
            assert abs(b._plan.fraction - 0.25) < 1e-9
            # controller is baking on B too
            assert b.rollout_controller.snapshot()["active"] is True

        asyncio.run(body())

    def test_promote_on_one_replica_propagates(self, tmp_path):
        a, b, store = _synced_pair(tmp_path)

        async def body():
            lane = a._load_lane_from_registry("v000002")
            a.stage_candidate_lane(lane, mode="canary", fraction=0.5)
            await b._registry_sync_tick()  # B bakes the same candidate
            a._promote_candidate()
            await b._registry_sync_tick()
            assert b.model_version == "v000002"
            assert b._candidate is None
            assert a.model_version == "v000002"

        asyncio.run(body())

    def test_promote_propagates_even_without_prior_stage_sync(self, tmp_path):
        """A replica that never saw the stage (e.g. it just restarted)
        still converges: the stable pin moved, so it loads the new
        stable from the registry wholesale."""
        a, b, store = _synced_pair(tmp_path)

        async def body():
            lane = a._load_lane_from_registry("v000002")
            a.stage_candidate_lane(lane, mode="canary", fraction=0.5)
            a._promote_candidate()
            await b._registry_sync_tick()
            assert b.model_version == "v000002"
            assert b._candidate is None

        asyncio.run(body())

    def test_rollback_on_one_replica_propagates(self, tmp_path):
        a, b, store = _synced_pair(tmp_path)

        async def body():
            lane = a._load_lane_from_registry("v000002")
            a.stage_candidate_lane(lane, mode="shadow")
            await b._registry_sync_tick()
            assert b._candidate is not None
            a._rollback_candidate("manual")
            await b._registry_sync_tick()
            assert b._candidate is None
            assert b.model_version == "v000001"
            # adopted WITHOUT re-persisting: no double history entry
            rollbacks = [
                h
                for h in store.get_state("regtest").history
                if h["action"] == "rollback"
            ]
            assert len(rollbacks) == 1

        asyncio.run(body())

    def test_sync_flushes_the_result_cache_on_stable_swap(self, tmp_path):
        a, b, store = _synced_pair(tmp_path, result_cache_size=64)

        async def body():
            cache = b._result_cache
            cache.put("v000001", b"somekey", {"x": 1})
            assert cache.stats()["entries"] == 1
            lane = a._load_lane_from_registry("v000002")
            a.stage_candidate_lane(lane, mode="canary", fraction=0.5)
            a._promote_candidate()
            await b._registry_sync_tick()
            assert b.model_version == "v000002"
            # PR-8 invariant fleet-wide: the retired version's entries are
            # gone from every process, not just the one that promoted
            assert cache.stats()["entries"] == 0

        asyncio.run(body())

    def test_local_transitions_reconcile_to_noop(self, tmp_path):
        a, b, store = _synced_pair(tmp_path)

        async def body():
            lane = a._load_lane_from_registry("v000002")
            a.stage_candidate_lane(lane, mode="canary", fraction=0.5)
            gen = store.state_generation("regtest")
            cand = a._candidate
            await a._registry_sync_tick()  # A reconciling its own write
            assert a._candidate is cand  # same lane object: no re-stage
            assert store.state_generation("regtest") == gen  # no writes

        asyncio.run(body())

    def test_models_endpoint_reports_state_generation(self, tmp_path):
        from tests.test_registry import _run_server

        a, b, store = _synced_pair(tmp_path)

        async def body(client):
            data = await (await client.get("/models")).json()
            assert data["registry"]["stateGeneration"] >= 1
            assert (
                data["registry"]["state"]["generation"]
                == data["registry"]["stateGeneration"]
            )

        _run_server(body, a)


# ---------------------------------------------------------------------------
# graceful drain (satellite: SIGTERM must not tear down in-flight work)
# ---------------------------------------------------------------------------


class TestGracefulDrain:
    def test_drain_answers_inflight_and_unreadies_healthz(self):
        from tests.test_registry import _run_server, _tag_lane, _tag_server

        server = _tag_server(drain_grace_s=5.0)
        server._active = _tag_lane("v1", delay_s=0.3)  # slow lane

        async def body(client):
            inflight = asyncio.ensure_future(
                client.post("/queries.json", json={"qid": 1, "user": "u"})
            )
            await asyncio.sleep(0.1)  # the query is on the dispatch thread
            drain = asyncio.ensure_future(server.drain())
            await asyncio.sleep(0.05)
            health = await client.get("/healthz")
            assert health.status == 503
            assert (await health.json())["draining"] is True
            resp = await inflight
            assert resp.status == 200  # answered, not torn down
            assert (await resp.json())["model"] == "v1"
            await drain
            assert server._inflight_requests == 0

        _run_server(body, server)

    def test_drain_is_idempotent_and_bounded(self):
        from tests.test_registry import _run_server, _tag_server

        server = _tag_server(drain_grace_s=0.2)

        async def body(client):
            await server.drain()
            await server.drain()  # second call returns immediately
            assert server._draining

        _run_server(body, server)


# ---------------------------------------------------------------------------
# e2e: kill a worker mid-rollout under load (the chaos stage)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestKillMidRolloutE2E:
    """Real worker processes + real gateway + real load. SIGKILL one
    worker while a canary bakes; the stable lane must never 5xx, the
    dead replica must be ejected within the probe window, the supervisor
    must restart and the gateway readmit it, and the bake gate must
    still converge (promote) fleet-wide.

    The fleet flight recorder rides the same chaos (ISSUE 11): the kill
    must leave an incident bundle holding the dead worker's stderr tail,
    a merged gateway+replica trace for an affected request, the
    telemetry-ring window covering the kill, and the registry state (with
    generation) at trigger time — and the on-disk ring must cover the
    kill after the fact."""

    def test_kill_worker_mid_rollout(self, tmp_path):
        from predictionio_tpu.data.storage.registry import Storage
        from tests.test_registry import _train_version

        basedir = str(tmp_path / "store")
        registry_dir = str(tmp_path / "registry")
        storage = Storage(env={"PIO_FS_BASEDIR": basedir})
        _train_version(storage, registry_dir, algo_id=3)  # v000001 stable
        _train_version(storage, registry_dir, algo_id=5)  # v000002
        store = ArtifactStore(registry_dir)

        import socket

        def free_port() -> int:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        specs = [WorkerSpec(f"w{i}", free_port()) for i in range(2)]
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            # long enough that the SIGKILL lands MID-bake (stage -> kill is
            # under a second; auto-promote waits out this window first)
            "FLEET_BAKE_WINDOW": "6.0",
            "FLEET_BAKE_MIN": "5",
            "PIO_FS_BASEDIR": basedir,
        }

        from predictionio_tpu.fleet.launch import (
            build_obs_plane,
            wire_incident_sources,
        )
        from predictionio_tpu.fleet.worklog import spawn_with_log

        metrics = MetricsRegistry()
        obs_dir = str(tmp_path / "obs")
        obs = build_obs_plane(obs_dir, metrics, registry_dir=registry_dir)

        def spawn(spec):
            return spawn_with_log(
                [
                    sys.executable,
                    os.path.join(REPO, "tests", "fleet_worker.py"),
                    registry_dir,
                    str(spec.port),
                    basedir,
                ],
                obs["logbook"],
                spec.name,
                env=env,
                cwd=REPO,
            )

        sup = Supervisor(
            spawn,
            specs,
            SupervisorConfig(
                poll_interval_s=0.1, backoff_base_s=0.2, term_grace_s=8.0
            ),
            metrics=metrics,
            logbook=obs["logbook"],
            on_crash=obs["on_crash"],
        )
        gw = Gateway(
            GatewayConfig(
                ip="127.0.0.1",
                port=free_port(),
                replica_urls=tuple(s.url for s in specs),
                probe_interval_s=0.2,
                probe_timeout_s=1.0,
                request_timeout_s=8.0,
                telemetry_interval_s=0.2,
            ),
            metrics=metrics,
            telemetry=obs["telemetry"],
            incidents=obs["incidents"],
        )
        wire_incident_sources(obs["incidents"], gw, sup)
        results: dict = {"statuses": [], "errors": [], "eject_s": None}
        try:
            asyncio.run(self._drive(sup, gw, store, results))
        finally:
            sup.stop()
            obs["telemetry"].close()
        fivexx = [s for s in results["statuses"] if s >= 500]
        assert fivexx == [], (
            f"{len(fivexx)} 5xx under replica loss "
            f"(of {len(results['statuses'])} requests): "
            f"{results.get('bodies_5xx', [])[:5]}"
        )
        assert results["errors"] == []
        assert len(results["statuses"]) > 50
        assert results["eject_s"] is not None and results["eject_s"] < 3.0
        assert store.get_state("regtest").stable == "v000002"
        self._assert_flight_recorder_evidence(
            obs_dir, results["t_kill_unix"], results["victim"]
        )

    def _assert_flight_recorder_evidence(
        self, obs_dir, t_kill_unix, victim
    ) -> None:
        """ISSUE-11 acceptance: the SIGKILL left a full evidence chain."""
        from predictionio_tpu.obs.incidents import list_bundles, load_bundle
        from predictionio_tpu.obs.tsring import TelemetryRing

        inc_dir = os.path.join(obs_dir, "incidents")
        refs = list_bundles(inc_dir)
        crash = [r for r in refs if r.trigger == "worker-crash"]
        assert crash, f"no worker-crash bundle (got {[r.trigger for r in refs]})"
        bundle = load_bundle(inc_dir, crash[0].bundle_id)
        # 1. the dead worker's stderr tail
        assert bundle["manifest"]["context"]["replica"] == victim
        tail = bundle["texts"].get("stderr_tail", "")
        assert "fleet worker serving" in tail, f"stderr tail missing: {tail!r}"
        # 2. a merged gateway+replica trace for an affected request: some
        # trace id must carry spans from BOTH tiers in the captured view
        traces = bundle["parts"]["traces"]
        by_tid: dict = {}
        for s in traces:
            by_tid.setdefault(s.get("traceId"), set()).add(
                "gateway" if s.get("source") == "gateway" else "replica"
            )
        assert any(
            tiers == {"gateway", "replica"} for tiers in by_tid.values()
        ), "no trace with both tiers in the captured merge"
        # 3. the telemetry-ring tail rode along and the on-disk ring's
        # window covers the kill (records both before and after it)
        assert bundle["parts"]["telemetry"], "no telemetry tail in bundle"
        ring = TelemetryRing(os.path.join(obs_dir, "telemetry"))
        times = [float(r["t"]) for r in ring.records()]
        assert times and min(times) < t_kill_unix < max(times), (
            "ring window does not cover the kill"
        )
        # 4. registry state with generation at trigger time
        registry = bundle["parts"]["registry"]
        assert any(
            isinstance(v, dict) and v.get("generation", 0) >= 1
            for v in registry.values()
        ), registry
        # 5. the supervisor ladder rode along
        assert any(w["name"] == victim for w in bundle["parts"]["supervisor"])

    async def _drive(self, sup, gw, store, results) -> None:
        import aiohttp

        sup.start()
        sup_task = asyncio.ensure_future(sup.run())
        await gw.start()
        gw_url = f"http://127.0.0.1:{gw.config.port}"
        session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=10)
        )
        stop_load = asyncio.Event()
        load_task = None
        try:
            # both workers serving (worker start pays the jax import)
            for spec in sup.workers:
                await self._wait_ready(session, spec.url, 90.0)
            load_task = asyncio.ensure_future(
                self._load(session, gw_url, stop_load, results)
            )
            await asyncio.sleep(0.3)
            # stage the canary THROUGH the gateway (one replica handles
            # it; the other adopts via registry sync)
            async with session.post(
                f"{gw_url}/models/candidate",
                json={"version": "v000002", "mode": "canary", "fraction": 0.4},
            ) as resp:
                assert resp.status == 200, await resp.text()
            for spec in sup.workers:
                await self._poll_async(
                    lambda spec=spec: self._worker_candidate(session, spec.url),
                    "candidate never propagated to every worker",
                    10.0,
                )
            # SIGKILL a worker mid-bake
            victim = sup.snapshot()[1]
            os.kill(victim["pid"], signal.SIGKILL)
            t_kill = time.monotonic()
            results["victim"] = victim["name"]
            results["t_kill_unix"] = time.time()
            await self._poll_async(
                lambda: self._gw_healthy_count(session, gw_url, 1),
                "dead replica never ejected",
                10.0,
            )
            results["eject_s"] = time.monotonic() - t_kill
            # supervisor restarts it; gateway readmits
            await self._poll_async(
                lambda: self._gw_healthy_count(session, gw_url, 2),
                "restarted replica never readmitted",
                90.0,
            )
            # the bake gate converges fleet-wide: promote lands in the
            # registry and every replica serves v2
            deadline = time.monotonic() + 45.0
            while store.get_state("regtest").stable != "v000002":
                assert time.monotonic() < deadline, "bake gate never converged"
                await asyncio.sleep(0.25)

            async def _serves_v2() -> bool:
                async with session.post(
                    f"{gw_url}/queries.json",
                    json={"qid": 1, "user": "convergence-check"},
                ) as resp:
                    if resp.status != 200:
                        return False
                    return (await resp.json()).get("algo_id") == 5

            await self._poll_async(
                _serves_v2, "fleet never served the promoted version", 15.0
            )
        finally:
            stop_load.set()
            if load_task is not None:
                await asyncio.gather(load_task, return_exceptions=True)
            sup_task.cancel()
            await asyncio.gather(sup_task, return_exceptions=True)
            await session.close()
            await gw.stop()

    async def _load(self, session, gw_url, stop, results) -> None:
        i = 0
        while not stop.is_set():
            i += 1
            try:
                async with session.post(
                    f"{gw_url}/queries.json",
                    json={"qid": i, "user": f"u{i % 40}"},
                ) as resp:
                    body = await resp.read()
                    results["statuses"].append(resp.status)
                    if resp.status >= 500:
                        # keep the failure diagnosable: which 5xx was it
                        results.setdefault("bodies_5xx", []).append(
                            body[:120].decode("utf-8", "replace")
                        )
            except Exception as exc:  # gateway itself must never drop us
                results["errors"].append(repr(exc))
            await asyncio.sleep(0.01)

    async def _wait_ready(self, session, url, deadline_s) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                async with session.get(f"{url}/healthz") as resp:
                    if resp.status == 200:
                        return
            except Exception:
                pass
            assert time.monotonic() < deadline, f"{url} never became ready"
            await asyncio.sleep(0.25)

    async def _poll_async(self, cond, message, deadline_s) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                ok = await cond()
            except Exception:
                ok = False
            if ok:
                return
            assert time.monotonic() < deadline, message
            await asyncio.sleep(0.1)

    async def _worker_candidate(self, session, url) -> bool:
        async with session.get(f"{url}/models") as resp:
            if resp.status != 200:
                return False
            data = await resp.json()
            cand = data.get("candidate")
            return bool(cand and cand.get("version") == "v000002")

    async def _gw_healthy_count(self, session, gw_url, expect) -> bool:
        async with session.get(f"{gw_url}/healthz") as resp:
            data = await resp.json()
            return data.get("replicasHealthy") == expect
