"""Engine dataflow tests (ref EngineTest / EngineWorkflowTest semantics)."""

import dataclasses
import json

import pytest

from predictionio_tpu.controller import (
    Engine,
    EngineParams,
    EmptyParams,
    Params,
    ParamsError,
    TrainOptions,
    params_from_dict,
)
from predictionio_tpu.workflow.context import WorkflowContext
from tests.sample_engine import (
    Algo0,
    AlgoParams,
    DataSource0,
    DSParams,
    Model0,
    Preparator0,
    Query,
    Serving0,
    ServingSum,
)


def make_engine(serving=Serving0):
    return Engine(
        {"ds": DataSource0},
        {"prep": Preparator0},
        {"a": Algo0},
        {"s": serving},
    )


def params(ds_id=1, prep_id=2, algos=((3,),)):
    return EngineParams(
        data_source=("ds", DSParams(id=ds_id)),
        preparator=("prep", DSParams(id=prep_id)),
        algorithms=[("a", AlgoParams(id=a[0])) for a in algos],
        serving=("s", EmptyParams()),
    )


CTX = WorkflowContext(mode="training")


class TestTrain:
    def test_single_algo_dataflow(self):
        models = make_engine().train(CTX, params())
        assert models == [Model0(3, 1, 2)]

    def test_multi_algo(self):
        models = make_engine().train(CTX, params(algos=((3,), (4,), (5,))))
        assert [m.algo_id for m in models] == [3, 4, 5]
        assert all(m.ds_id == 1 and m.prep_id == 2 for m in models)

    def test_sanity_check_failure_propagates(self):
        ep = params()
        ep.data_source = ("ds", DSParams(id=1, fail_sanity=True))
        with pytest.raises(AssertionError):
            make_engine().train(CTX, ep)

    def test_skip_sanity_check(self):
        ep = params()
        ep.data_source = ("ds", DSParams(id=1, fail_sanity=True))
        models = make_engine().train(
            CTX, ep, TrainOptions(skip_sanity_check=True)
        )
        assert len(models) == 1

    def test_stop_after_read(self):
        models = make_engine().train(CTX, params(), TrainOptions(stop_after_read=True))
        assert models == []

    def test_stop_after_prepare(self):
        models = make_engine().train(
            CTX, params(), TrainOptions(stop_after_prepare=True)
        )
        assert models == []

    def test_unknown_component_name(self):
        ep = params()
        ep.algorithms = [("nope", AlgoParams(id=1))]
        with pytest.raises(KeyError):
            make_engine().train(CTX, ep)


class TestEval:
    def test_join_graph_multi_algo_multi_fold(self):
        engine = make_engine(serving=ServingSum)
        ep = params(algos=((7,), (8,)))
        results = engine.eval(CTX, ep)
        assert len(results) == 2  # two folds
        for fold, (ei, qpa) in enumerate(results):
            assert ei == {"fold": fold}
            assert len(qpa) == 3
            for q, p, a in qpa:
                assert q.qid == a.qid  # actual joined to right query
                assert p["qid"] == q.qid
                assert p["algo_ids"] == [7, 8]  # both algos contributed

    def test_fold_training_data_differs(self):
        engine = make_engine()
        results = engine.eval(CTX, params())
        (_, fold0), (_, fold1) = results
        # fold index shifts the ds_id through TrainingData
        assert fold0[0][1].ds_id == 1
        assert fold1[0][1].ds_id == 2


class TestVariantExtraction:
    def test_engine_params_from_variant(self):
        variant = {
            "id": "default",
            "engineFactory": "x",
            "datasource": {"name": "ds", "params": {"id": 9}},
            "preparator": {"name": "prep", "params": {"id": 10}},
            "algorithms": [
                {"name": "a", "params": {"id": 11}},
                {"name": "a", "params": {"id": 12}},
            ],
            "serving": {"name": "s"},
        }
        engine = make_engine()
        ep = engine.engine_params_from_variant(variant)
        assert ep.data_source[1].id == 9
        assert ep.preparator[1].id == 10
        assert [p.id for _, p in ep.algorithms] == [11, 12]
        models = engine.train(CTX, ep)
        assert [m.algo_id for m in models] == [11, 12]

    def test_unknown_param_field_rejected(self):
        variant = {
            "datasource": {"name": "ds", "params": {"id": 1, "typo_field": 2}},
            "algorithms": [],
            "preparator": {"name": "prep"},
            "serving": {"name": "s"},
        }
        with pytest.raises(ParamsError):
            make_engine().engine_params_from_variant(variant)

    def test_params_without_params_class_rejected(self):
        """A component with no params_class must REFUSE variant params, not
        silently train with defaults while the user's hyperparameters sit
        ignored in engine.json (code-review r4)."""

        class NoParamsAlgo:
            def __init__(self, params=None):
                pass

        from predictionio_tpu.controller import Engine
        from tests.sample_engine import DataSource0, Preparator0, Serving0

        engine = Engine(
            {"ds": DataSource0},
            {"prep": Preparator0},
            {"np": NoParamsAlgo},
            {"s": Serving0},
        )
        variant = {
            "datasource": {"name": "ds"},
            "preparator": {"name": "prep"},
            "algorithms": [{"name": "np", "params": {"rank": 32}}],
            "serving": {"name": "s"},
        }
        with pytest.raises(ValueError, match="would be ignored"):
            engine.engine_params_from_variant(variant)

    def test_params_to_json_roundtrip(self):
        ep = params(algos=((3,),))
        flat = Engine.engine_params_to_json(ep)
        assert json.loads(flat["data_source_params"])["id"] == 1
        algos = json.loads(flat["algorithms_params"])
        assert algos[0]["name"] == "a" and algos[0]["params"]["id"] == 3


class TestParamsCoercion:
    def test_types(self):
        @dataclasses.dataclass(frozen=True)
        class P(Params):
            n: int
            rate: float
            name: str = "x"
            flags: list = dataclasses.field(default_factory=list)

        p = params_from_dict(P, {"n": 5, "rate": 1, "flags": ["a"]})
        assert p.rate == 1.0 and isinstance(p.rate, float)
        assert p.name == "x"

    def test_required_missing(self):
        @dataclasses.dataclass(frozen=True)
        class P(Params):
            n: int

        with pytest.raises(ParamsError):
            params_from_dict(P, {})

    def test_optional_fields(self):
        from typing import Optional

        @dataclasses.dataclass(frozen=True)
        class P(Params):
            cap: Optional[int] = None

        assert params_from_dict(P, {}).cap is None
        assert params_from_dict(P, {"cap": 3}).cap == 3
        assert params_from_dict(P, {"cap": None}).cap is None

    def test_nested_dataclass(self):
        @dataclasses.dataclass(frozen=True)
        class Inner(Params):
            k: int = 1

        @dataclasses.dataclass(frozen=True)
        class Outer(Params):
            inner: Inner = dataclasses.field(default_factory=Inner)

        o = params_from_dict(Outer, {"inner": {"k": 7}})
        assert o.inner.k == 7
