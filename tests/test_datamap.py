"""DataMap spec — ported behaviors from reference DataMapSpec.scala."""

import pytest

from predictionio_tpu.data.datamap import DataMap, DataMapError


def test_get_required_field():
    dm = DataMap({"a": 1, "b": "x", "c": [1, 2], "d": 2.5})
    assert dm.get("a") == 1
    assert dm.get_string("b") == "x"
    assert dm.get_list("c") == [1, 2]
    assert dm.get_double("d") == 2.5
    assert dm.get_int("a") == 1


def test_get_missing_raises():
    dm = DataMap({"a": 1})
    with pytest.raises(DataMapError):
        dm.get("missing")


def test_get_null_raises():
    dm = DataMap({"a": None})
    with pytest.raises(DataMapError):
        dm.get("a")


def test_get_opt_and_or_else():
    dm = DataMap({"a": 1, "n": None})
    assert dm.get_opt("a") == 1
    assert dm.get_opt("missing") is None
    assert dm.get_opt("n") is None
    assert dm.get_or_else("missing", 9) == 9
    assert dm.get_or_else("n", 9) == 9
    assert dm.get_or_else("a", 9) == 1


def test_union_right_wins():
    a = DataMap({"x": 1, "y": 2})
    b = DataMap({"y": 3, "z": 4})
    assert a.union(b) == DataMap({"x": 1, "y": 3, "z": 4})


def test_diff_removes_keys():
    a = DataMap({"x": 1, "y": 2, "z": 3})
    assert a.diff(["y", "nope"]) == DataMap({"x": 1, "z": 3})


def test_json_roundtrip():
    dm = DataMap({"a": 1, "b": [1, "two"], "c": {"nested": True}})
    assert DataMap.from_json(dm.to_json()) == dm


def test_mapping_protocol():
    dm = DataMap({"a": 1})
    assert "a" in dm
    assert len(dm) == 1
    assert dict(dm) == {"a": 1}
    assert dm.keyset() == {"a"}
