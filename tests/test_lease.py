"""Lease-based registry mutex (registry/lease.py): TTL expiry, steals,
fencing tokens — the shared-storage story that replaces flock's
single-box guarantee (docs/model_registry.md §Lease mutex).

Three layers of proof, mirroring the PR-9 flock suite:

- fake-clock units: expiry/steal/fencing/torn-file semantics with zero
  real sleeping;
- store integration: `_state_mutex` holds the lease across a transition,
  `_save_state` refuses to persist on a stolen token, and
  `state_generation` never reports a spurious 0 through a concurrent
  writer's rename window;
- a two-process hammer driving :class:`LeaseMutex` directly (the flock
  fast path serializes same-host store calls, so raw-mutex contention is
  the cross-host case): no lost increments, fencing tokens strictly
  increasing and never reissued.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.registry.lease import (
    LeaseLostError,
    LeaseMutex,
    LeaseRecord,
    LeaseTimeoutError,
    lease_enabled,
    register_lease_metrics,
)
from predictionio_tpu.registry.store import ArtifactStore, RolloutState

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def _mx(path: str, owner: str, clock: FakeClock, ttl_s: float = 10.0):
    return LeaseMutex(
        str(path),
        owner=owner,
        ttl_s=ttl_s,
        clock=clock,
        sleep=lambda s: clock.advance(s),
        poll_interval_s=0.5,
    )


# ---------------------------------------------------------------------------
# fake-clock units
# ---------------------------------------------------------------------------


class TestLeaseMutex:
    def test_fresh_acquire_issues_token_one(self, tmp_path):
        clock = FakeClock()
        a = _mx(tmp_path / "l", "a", clock)
        assert a.acquire() == 1
        assert a.held
        rec = a.read()
        assert rec.owner == "a" and rec.generation == 1

    def test_release_preserves_generation(self, tmp_path):
        # the tombstone keeps the counter: a token, once issued, is never
        # reissued — the whole point of fencing
        clock = FakeClock()
        a = _mx(tmp_path / "l", "a", clock)
        a.acquire()
        a.release()
        rec = a.read()
        assert rec.free() and rec.generation == 1
        assert a.acquire() == 2

    def test_waiter_times_out_on_live_holder(self, tmp_path):
        clock = FakeClock()
        a = _mx(tmp_path / "l", "a", clock)
        b = _mx(tmp_path / "l", "b", clock)
        a.acquire()
        # force the slow path: pretend the holder lives elsewhere, or the
        # same-host pid-alive check would see OUR live pid and wait anyway
        rec = a.read()
        rec.host = "elsewhere"
        a._write(rec)
        with pytest.raises(LeaseTimeoutError):
            b.acquire(timeout_s=5.0)  # < ttl: holder never expires

    def test_ttl_expiry_steal_bumps_token_and_fences_old_holder(
        self, tmp_path
    ):
        clock = FakeClock()
        a = _mx(tmp_path / "l", "a", clock, ttl_s=10.0)
        b = _mx(tmp_path / "l", "b", clock, ttl_s=10.0)
        tok_a = a.acquire()
        rec = a.read()
        rec.host = "elsewhere"  # disable the same-host fast steal
        a._write(rec)
        clock.advance(11.0)  # past TTL: the holder is presumed dead
        tok_b = b.acquire(timeout_s=5.0)
        assert tok_b == tok_a + 1
        # the fenced-out holder must fail verify() and must NOT clobber
        # the thief's record on release
        with pytest.raises(LeaseLostError):
            a.verify()
        a._held = True  # simulate a zombie that still believes it holds
        a.release()
        rec = b.read()
        assert rec.owner == "b" and rec.generation == tok_b

    def test_same_host_dead_pid_steals_instantly(self, tmp_path):
        # flock's single-box property, preserved: a SIGKILLed holder on
        # THIS host is stealable immediately, no TTL wait
        clock = FakeClock()
        p = subprocess.Popen([sys.executable, "-c", ""])
        p.wait()
        b = _mx(tmp_path / "l", "b", clock, ttl_s=300.0)
        b._write(
            LeaseRecord(
                owner="dead",
                generation=7,
                acquired_at=clock(),
                ttl_s=300.0,
                host=b.host,
                pid=p.pid,
            )
        )
        assert b.acquire(timeout_s=1.0) == 8  # token continues, not reset

    def test_torn_lease_file_is_contention_not_free(self, tmp_path):
        clock = FakeClock()
        path = tmp_path / "l"
        path.write_text("{ torn garbage")
        b = _mx(path, "b", clock)
        assert b.read().owner == "<unreadable>"
        with pytest.raises(LeaseTimeoutError):
            b.acquire(timeout_s=2.0)

    def test_renew_restamps_without_new_token(self, tmp_path):
        clock = FakeClock()
        a = _mx(tmp_path / "l", "a", clock, ttl_s=10.0)
        tok = a.acquire()
        clock.advance(8.0)
        assert a.renew() == tok
        rec = a.read()
        assert rec.acquired_at == clock() and rec.generation == tok

    def test_context_manager(self, tmp_path):
        clock = FakeClock()
        a = _mx(tmp_path / "l", "a", clock)
        with a:
            assert a.held
        assert not a.held and a.read().free()

    def test_lease_enabled_env(self, monkeypatch):
        monkeypatch.delenv("PIO_REGISTRY_LEASE", raising=False)
        assert lease_enabled()
        monkeypatch.setenv("PIO_REGISTRY_LEASE", "0")
        assert not lease_enabled()

    def test_metrics_exported(self, tmp_path):
        clock = FakeClock()
        a = _mx(tmp_path / "l", "a", clock)
        a.acquire()
        a.release()
        m = MetricsRegistry()
        register_lease_metrics(m)
        text = m.render_prometheus()
        assert "pio_registry_lease_acquires_total" in text
        assert "pio_registry_lease_generation" in text


# ---------------------------------------------------------------------------
# store integration: the lease under _state_mutex + fencing on save
# ---------------------------------------------------------------------------


class TestStoreLease:
    def test_transition_holds_and_releases_lease(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        with store._state_mutex("eng"):
            mx = store._leases[store.engine_key("eng")]
            assert mx.held and mx.generation >= 1
        assert not mx.held
        assert mx.read().free()  # tombstone, generation preserved

    def test_lease_disabled_env_skips_lease_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PIO_REGISTRY_LEASE", "0")
        store = ArtifactStore(str(tmp_path))
        with store._state_mutex("eng"):
            pass
        assert not store._leases  # flock-only, the pre-lease behavior

    def test_save_state_fences_stolen_lease(self, tmp_path):
        # the Lamport discipline: a holder that lost its lease mid-
        # critical-section must abort BEFORE persisting
        store = ArtifactStore(str(tmp_path))
        mx = store._lease_for("eng")
        mx.acquire()
        mx._write(
            LeaseRecord(
                owner="thief",
                generation=mx.generation + 1,
                acquired_at=mx._clock(),
                ttl_s=30.0,
            )
        )
        with pytest.raises(LeaseLostError):
            store._save_state("eng", RolloutState())
        assert not os.path.exists(store._state_path("eng"))

    def test_state_generation_survives_rename_window(self, tmp_path):
        # S2 regression: a concurrent writer's tmp+rename makes the state
        # file momentarily unreadable; the generation answer must be the
        # floor this store already saw, never a spurious 0 (which would
        # stampede every fleet worker's sync loop into a reload)
        store = ArtifactStore(str(tmp_path))
        key = store.engine_key("eng")
        path = store._state_path("eng")
        os.makedirs(os.path.dirname(path), exist_ok=True)

        def land(gen: int) -> None:
            state = RolloutState()
            state.generation = gen
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(state.to_json_dict(), fh)

        land(7)
        assert store.state_generation("eng") == 7
        os.unlink(path)  # the writer is mid-rename
        assert store.state_generation("eng") == 7
        with open(path, "w", encoding="utf-8") as fh:
            fh.write("{ torn")  # half-written — same answer
        assert store.state_generation("eng") == 7
        land(8)  # the rename lands
        assert store.state_generation("eng") == 8
        # a FRESH store that never saw state correctly reports 0
        assert ArtifactStore(str(tmp_path)).state_generation("other") == 0
        _ = key


# ---------------------------------------------------------------------------
# two-process hammer: raw LeaseMutex contention (the cross-host case —
# the flock fast path serializes same-host store calls above this layer)
# ---------------------------------------------------------------------------

_HAMMER = """
import os, sys
from predictionio_tpu.registry.lease import LeaseMutex

lease, counter, log, n, tag = sys.argv[1:6]
mx = LeaseMutex(lease, owner=tag, ttl_s=30.0, poll_interval_s=0.002)
for _ in range(int(n)):
    token = mx.acquire(timeout_s=60.0)
    try:
        with open(counter, encoding="utf-8") as fh:
            v = int(fh.read())
    except FileNotFoundError:
        v = 0
    with open(counter, "w", encoding="utf-8") as fh:
        fh.write(str(v + 1))
    with open(log, "a", encoding="utf-8") as fh:
        fh.write(f"{token} {v} {tag}\\n")
    mx.release()
"""


class TestLeaseHammer:
    def test_two_process_hammer_no_lost_updates_or_token_reuse(
        self, tmp_path
    ):
        n = 20
        lease = str(tmp_path / "state.lease")
        counter = str(tmp_path / "counter")
        log = str(tmp_path / "log")
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _HAMMER, lease, counter, log, str(n), tag],
                cwd=REPO,
            )
            for tag in ("p1", "p2")
        ]
        for p in procs:
            assert p.wait(timeout=120) == 0
        # no lost increments: every read-modify-write was serialized
        with open(counter, encoding="utf-8") as fh:
            assert int(fh.read()) == 2 * n
        lines = [ln.split() for ln in open(log, encoding="utf-8")]
        assert len(lines) == 2 * n
        values = [int(parts[1]) for parts in lines]
        tokens = [int(parts[0]) for parts in lines]
        # appends happen under the lease: the observed counter sequence
        # is exactly 0..2n-1 in order — no torn read ever surfaced
        assert values == list(range(2 * n))
        # fencing tokens: unique, strictly increasing, never reissued
        assert tokens == sorted(tokens)
        assert len(set(tokens)) == len(tokens)
        assert tokens[-1] >= 2 * n
