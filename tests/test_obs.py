"""Observability layer tests (tier-1, CPU-only, fast).

Covers the acceptance trail of the telemetry PR: the metrics registry
(including histogram bucket math under concurrent writers), Prometheus
exposition + the `pio top` parser round-trip, trace-id propagation
end-to-end (ingress header -> micro-batch -> storage span share one trace
id, in both the ring buffer and the structured JSON log), the re-based
/stats.json, the compile watcher, and counters moving under chaos
(shed/deadline/breaker) on live servers.
"""

import asyncio
import json
import logging
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tracing import (
    TRACE_HEADER,
    Tracer,
    current_trace_id,
    get_tracer,
    mint_trace_id,
    reset_trace_id,
    set_trace_id,
)
from predictionio_tpu.resilience import CLOSED, OPEN
from predictionio_tpu.tools.top import (
    parse_prometheus,
    render,
    run_top,
    summarize,
)


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class TestCounterGauge:
    def test_counter_labels_and_totals(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "help", labelnames=("status",))
        c.inc(status="200")
        c.inc(2, status="200")
        c.inc(status="503")
        assert c.value(status="200") == 3
        assert c.value(status="503") == 1
        assert c.total() == 4

    def test_counter_is_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total")
        with pytest.raises(ValueError):
            c.inc(-1)
        c.inc(5)
        c.set_total(3)  # mirror below current value: clamped, never down
        assert c.value() == 5

    def test_gauge_set_and_callback(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth")
        g.set(7)
        assert g.value() == 7
        box = {"v": 1.0}
        g2 = reg.gauge("live_depth")
        g2.set_function(lambda: box["v"])
        box["v"] = 42.0
        assert g2.value() == 42.0
        assert "live_depth 42" in reg.render_prometheus()

    def test_get_or_create_and_conflicts(self):
        reg = MetricsRegistry()
        a = reg.counter("same", labelnames=("x",))
        b = reg.counter("same", labelnames=("x",))
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("same")
        with pytest.raises(ValueError):
            reg.counter("same", labelnames=("y",))
        with pytest.raises(ValueError):
            a.inc(wrong_label="1")

    def test_invalid_names_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("bad name")
        with pytest.raises(ValueError):
            reg.counter("ok", labelnames=("bad-label",))


class TestHistogram:
    def test_percentiles_interpolate_in_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0))
        for _ in range(99):
            h.observe(0.05)  # (0.01, 0.1] bucket
        h.observe(5.0)  # +Inf bucket
        s = h.summary()
        assert s["count"] == 100
        assert 0.01 < s["p50"] <= 0.1
        assert 0.01 < s["p95"] <= 0.1
        # p99 still lands in the populated finite bucket (99 of 100)
        assert s["p99"] <= 1.0
        assert s["sum"] == pytest.approx(99 * 0.05 + 5.0)

    def test_empty_summary(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        assert h.summary() == {"count": 0}
        assert h.percentile(0.5) == 0.0

    def test_bucket_math_under_concurrent_writers(self):
        """The satellite guarantee: concurrent observes never lose or
        double-count — total count, per-bucket sums, and _sum agree."""
        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.001, 0.01, 0.1, 1.0))
        values = (0.0005, 0.005, 0.05, 0.5, 2.0)
        n_threads, per_thread = 8, 2000

        def hammer(seed: int):
            for i in range(per_thread):
                h.observe(values[(i + seed) % len(values)])

        threads = [
            threading.Thread(target=hammer, args=(t,)) for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = n_threads * per_thread
        s = h.summary()
        assert s["count"] == total
        expected_sum = sum(values) / len(values) * total
        assert s["sum"] == pytest.approx(expected_sum)
        # the rendered cumulative buckets agree with the count
        metrics = parse_prometheus(reg.render_prometheus())
        inf_bucket = [
            v for labels, v in metrics["lat_bucket"] if labels["le"] == "+Inf"
        ]
        assert inf_bucket == [total]
        # each value class landed in exactly one bucket: cumulative counts
        # step by total/len(values) per populated bound
        per_class = total // len(values)
        cums = sorted(v for _, v in metrics["lat_bucket"])
        assert cums == [per_class * (i + 1) for i in range(len(values))]


class TestPrometheusExposition:
    def test_render_and_parse_round_trip(self):
        reg = MetricsRegistry()
        reg.counter("req_total", "requests", labelnames=("status",)).inc(
            3, status="200"
        )
        reg.gauge("depth", "queue depth").set(2)
        reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.05)
        text = reg.render_prometheus()
        assert "# HELP req_total requests" in text
        assert "# TYPE req_total counter" in text
        assert "# TYPE lat histogram" in text
        parsed = parse_prometheus(text)
        assert parsed["req_total"] == [({"status": "200"}, 3.0)]
        assert parsed["depth"] == [({}, 2.0)]
        assert ({"le": "+Inf"}, 1.0) in parsed["lat_bucket"]
        assert parsed["lat_count"] == [({}, 1.0)]

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("esc_total", labelnames=("msg",)).inc(
            msg='say "hi"\nback\\slash'
        )
        parsed = parse_prometheus(reg.render_prometheus())
        [(labels, value)] = parsed["esc_total"]
        assert value == 1.0
        assert labels["msg"] == 'say "hi"\nback\\slash'

    def test_collectors_run_at_scrape(self):
        reg = MetricsRegistry()
        g = reg.gauge("sampled")
        calls = []
        reg.register_collector(lambda: (calls.append(1), g.set(len(calls))))
        reg.render_prometheus()
        snap = reg.snapshot()
        assert len(calls) == 2
        assert snap["sampled"]["samples"][0]["value"] == 2


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


class TestTracing:
    def test_span_records_ring_and_log(self, caplog):
        tracer = Tracer(ring_size=4)
        token = set_trace_id("feedbeef00000000")
        try:
            with caplog.at_level(logging.INFO, logger="pio.trace"):
                with tracer.span("unit.work", kind="serving", step=1) as sp:
                    sp.tags["extra"] = "yes"
        finally:
            reset_trace_id(token)
        [recent] = tracer.recent()
        assert recent["traceId"] == "feedbeef00000000"
        assert recent["name"] == "unit.work"
        assert recent["kind"] == "serving"
        assert recent["tags"] == {"step": 1, "extra": "yes"}
        assert recent["durationMs"] >= 0
        # the structured log line is the span as one JSON object
        line = json.loads(caplog.records[-1].getMessage())
        assert line["traceId"] == "feedbeef00000000"
        assert line["status"] == "ok"

    def test_span_marks_error_status_and_reraises(self):
        tracer = Tracer()
        with pytest.raises(ValueError):
            with tracer.span("explodes"):
                raise ValueError("boom")
        assert tracer.recent()[0]["status"] == "ValueError"

    def test_ring_is_bounded_newest_first(self):
        tracer = Tracer(ring_size=3)
        for i in range(5):
            tracer.record_span(f"s{i}", "internal", 0.0)
        names = [s["name"] for s in tracer.recent()]
        assert names == ["s4", "s3", "s2"]
        assert tracer.spans_recorded == 5

    def test_contextvar_isolation(self):
        assert current_trace_id() is None
        token = set_trace_id("aaaa")
        assert current_trace_id() == "aaaa"
        reset_trace_id(token)
        assert current_trace_id() is None

    def test_mint_is_unique(self):
        assert mint_trace_id() != mint_trace_id()


# ---------------------------------------------------------------------------
# compile watcher
# ---------------------------------------------------------------------------


class TestCompileWatcher:
    def test_counts_recompiles_after_baseline(self, caplog):
        import jax
        import jax.numpy as jnp

        from predictionio_tpu.obs.jaxprof import CompileWatcher

        reg = MetricsRegistry()
        watcher = CompileWatcher(reg, storm_threshold=2)

        @jax.jit
        def f(x):
            return x * 2

        f(jnp.ones(2))  # warmup compile, then baseline
        assert watcher.watch("test.f", f)
        assert watcher.sample() == 0  # baseline: warmup doesn't count
        f(jnp.ones(2))  # cache hit
        assert watcher.sample() == 0
        with caplog.at_level(logging.WARNING):
            f(jnp.ones(3))  # new shape -> recompile
            f(jnp.ones(4))  # another -> storm at threshold 2
            assert watcher.sample() == 2
        assert watcher.total_misses() == 2
        assert any("recompile storm" in r.getMessage() for r in caplog.records)
        parsed = parse_prometheus(reg.render_prometheus())
        assert (
            sum(v for _, v in parsed["pio_jit_cache_misses_total"]) == 2
        )
        sizes = {l["fn"]: v for l, v in parsed["pio_jit_cache_size"]}
        assert sizes["test.f"] == 3


# ---------------------------------------------------------------------------
# stats.json re-base
# ---------------------------------------------------------------------------


class TestStatsRebase:
    def _event(self, name="rate", target=None):
        from predictionio_tpu.data.event import Event

        return Event(
            event=name,
            entity_type="user",
            entity_id="u1",
            target_entity_type=target,
            target_entity_id="i1" if target else None,
        )

    def test_legacy_shape_and_registry_agree(self):
        from predictionio_tpu.data.api.stats import StatsCollector

        reg = MetricsRegistry()
        stats = StatsCollector(registry=reg)
        stats.bookkeeping(1, 201, self._event())
        stats.bookkeeping(1, 201, self._event(target="item"))
        stats.bookkeeping(1, 500, self._event())
        stats.bookkeeping(2, 201, self._event())  # other app: filtered out
        out = stats.get_stats(1)
        assert out["longLive"]["statusCode"] == [
            {"status": 201, "count": 2},
            {"status": 500, "count": 1},
        ]
        basic = out["longLive"]["basic"]
        assert {b["event"] for b in basic} == {"rate"}
        assert {b["targetEntityType"] for b in basic} == {None, "item"}
        assert out["currentHour"]["statusCode"] == out["longLive"]["statusCode"]
        assert "prevHour" not in out
        # the same totals back /metrics
        parsed = parse_prometheus(reg.render_prometheus())
        totals = {
            (l["app_id"], l["status"]): v
            for l, v in parsed["pio_events_ingested_total"]
        }
        assert totals[("1", "201")] == 2
        assert totals[("2", "201")] == 1


# ---------------------------------------------------------------------------
# query server end-to-end
# ---------------------------------------------------------------------------


def _run_query_server(body, **cfg_kw):
    import sys

    sys.path.insert(0, "tests") if "tests" not in sys.path else None
    from tests.test_resilience import _make_query_server

    async def outer():
        get_tracer().clear()
        server = _make_query_server(**cfg_kw)
        client = TestClient(TestServer(server.make_app()))
        await client.start_server()
        try:
            await body(client, server)
        finally:
            await client.close()

    asyncio.run(outer())


class TestQueryServerObs:
    def test_metrics_endpoint_is_prometheus_parseable(self):
        """Acceptance: GET /metrics on a deployed QueryServer returns
        Prometheus-parseable text including the request latency histogram,
        admission-queue depth, breaker state, and jit recompile count."""

        async def body(client, server):
            for qid in range(3):
                resp = await client.post("/queries.json", json={"qid": qid})
                assert resp.status == 200
            m = await client.get("/metrics")
            assert m.status == 200
            assert m.headers["Content-Type"].startswith("text/plain")
            parsed = parse_prometheus(await m.text())
            assert (
                {"endpoint": "/queries.json", "status": "200"},
                3.0,
            ) in parsed["pio_requests_total"]
            assert any(
                l.get("le") == "+Inf" and v == 3.0
                for l, v in parsed["pio_request_seconds_bucket"]
            )
            assert parsed["pio_queue_depth"] == [({}, 0.0)]
            assert ({"breaker": "dispatch"}, 0.0) in parsed["pio_breaker_state"]
            # jit recompile count present (0 after warmup baseline is fine)
            assert "pio_jit_recompile_storm" in parsed
            assert "pio_load_shed_total" in parsed
            assert "pio_deadline_exceeded_total" in parsed

        _run_query_server(body)

    def test_trace_id_spans_ingress_batch_and_storage(self, memory_storage):
        """Acceptance: one trace id observed across ingress, batch, and
        storage spans — via the ring buffer, /traces/recent, and the
        structured JSON log."""
        from predictionio_tpu.data.storage.traced import trace_dao
        from tests.sample_engine import Serving0

        traced_apps = trace_dao(
            memory_storage.get_meta_data_apps(), "apps"
        )

        class StorageTouchingServing(Serving0):
            """Realistic query-time storage read (e.g. the ecommerce
            template fetching recent user events at predict time)."""

            def supplement(self, query):
                traced_apps.get_all()
                return query

        tid = mint_trace_id()

        async def body(client, server):
            trace_logger = logging.getLogger("pio.trace")
            records: list[str] = []

            class Capture(logging.Handler):
                def emit(self, record):
                    records.append(record.getMessage())

            handler = Capture(level=logging.INFO)
            old_level = trace_logger.level
            trace_logger.setLevel(logging.INFO)
            trace_logger.addHandler(handler)
            try:
                resp = await client.post(
                    "/queries.json",
                    json={"qid": 5},
                    headers={TRACE_HEADER: tid},
                )
                assert resp.status == 200
                assert resp.headers[TRACE_HEADER] == tid
            finally:
                trace_logger.removeHandler(handler)
                trace_logger.setLevel(old_level)
            spans = get_tracer().find(tid)
            kinds = {s["kind"] for s in spans}
            assert {"ingress", "batch", "storage"} <= kinds, spans
            storage_span = next(s for s in spans if s["kind"] == "storage")
            assert storage_span["name"] == "storage.apps.get_all"
            batch_span = next(s for s in spans if s["kind"] == "batch")
            for key in ("queue_ms", "dispatch_ms", "fetch_ms"):
                assert key in batch_span["tags"]
            # /traces/recent serves the same spans
            t = await client.get("/traces/recent?limit=50")
            served = [s for s in (await t.json())["spans"] if s["traceId"] == tid]
            assert {s["kind"] for s in served} >= {"ingress", "batch", "storage"}
            # the structured log saw all three hops under ONE trace id
            logged = [json.loads(r) for r in records]
            logged_kinds = {s["kind"] for s in logged if s["traceId"] == tid}
            assert {"ingress", "batch", "storage"} <= logged_kinds

        # swap the serving class into the engine the helper builds
        import sys

        sys.path.insert(0, "tests") if "tests" not in sys.path else None
        from tests.test_resilience import _make_query_server

        async def outer():
            get_tracer().clear()
            server = _make_query_server()
            engine = server.engine
            engine.serving_classes = {"s": StorageTouchingServing}
            server._active = server._active._replace(
                serving=StorageTouchingServing()
            )
            client = TestClient(TestServer(server.make_app()))
            await client.start_server()
            try:
                await body(client, server)
            finally:
                await client.close()

        asyncio.run(outer())

    def test_shed_and_deadline_counters_move_under_chaos(self):
        """Acceptance: a chaos run shows shed/deadline counters moving."""
        from tests.sample_engine import Algo0

        async def body(client, server):
            # wedge the dispatch path so queries pile into the queue
            original = Algo0.predict_batch_dispatch

            def slow_dispatch(self, model, queries):
                import time as _t

                _t.sleep(0.4)  # > request_timeout_s
                return original(self, model, queries)

            Algo0.predict_batch_dispatch = slow_dispatch
            try:
                results = await asyncio.gather(
                    *(
                        client.post("/queries.json", json={"qid": i})
                        for i in range(8)
                    )
                )
                statuses = [r.status for r in results]
                assert all(s in (200, 503) for s in statuses)
                assert 503 in statuses
            finally:
                Algo0.predict_batch_dispatch = original
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            shed = sum(v for _, v in parsed.get("pio_load_shed_total", ()))
            deadlines = sum(
                v for _, v in parsed.get("pio_deadline_exceeded_total", ())
            )
            assert shed + deadlines > 0
            # 503s are counted per status by the envelope
            assert any(
                l.get("status") == "503" and v > 0
                for l, v in parsed["pio_requests_total"]
            )

        _run_query_server(
            body,
            request_timeout_s=0.15,
            queue_high_water=2,
            max_batch_size=1,
        )

    def test_breaker_transitions_counted(self):
        async def body(client, server):
            for _ in range(server.config.breaker_threshold):
                server.dispatch_breaker.record_failure()
            assert server.dispatch_breaker.state == OPEN
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert (
                {"breaker": "dispatch", "to": "open"},
                1.0,
            ) in parsed["pio_breaker_transitions_total"]
            assert ({"breaker": "dispatch"}, 2.0) in parsed["pio_breaker_state"]
            server.dispatch_breaker.reset()
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert (
                {"breaker": "dispatch", "to": "closed"},
                1.0,
            ) in parsed["pio_breaker_transitions_total"]

        _run_query_server(body)


# ---------------------------------------------------------------------------
# event server end-to-end
# ---------------------------------------------------------------------------


EVENT = {"event": "rate", "entityType": "user", "entityId": "u1"}


def _run_event_server(body):
    import sys

    sys.path.insert(0, "tests") if "tests" not in sys.path else None
    from tests.test_resilience import _make_event_server

    async def outer():
        get_tracer().clear()
        server, injector, key = _make_event_server()
        client = TestClient(TestServer(server.make_app()))
        await client.start_server()
        try:
            await body(client, server, injector, key)
        finally:
            await client.close()

    asyncio.run(outer())


class TestEventServerObs:
    def test_metrics_and_trace_header(self):
        async def body(client, server, injector, key):
            tid = mint_trace_id()
            resp = await client.post(
                f"/events.json?accessKey={key}",
                json=EVENT,
                headers={TRACE_HEADER: tid},
            )
            assert resp.status == 201
            assert resp.headers[TRACE_HEADER] == tid
            # the storage span joined the ingress trace across the
            # executor hop
            spans = get_tracer().find(tid)
            kinds = {s["kind"] for s in spans}
            assert {"ingress", "storage"} <= kinds, spans
            names = {s["name"] for s in spans}
            assert "storage.l_events.insert" in names
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert (
                {"endpoint": "/events.json", "status": "201"},
                1.0,
            ) in parsed["pio_requests_total"]
            # ingestion counters are always-on (the --stats flag only
            # gates serving the legacy /stats.json view)
            assert any(
                l["status"] == "201" and v == 1.0
                for l, v in parsed["pio_events_ingested_total"]
            )
            assert ({"breaker": "eventdata"}, 0.0) in parsed["pio_breaker_state"]

        _run_event_server(body)

    def test_retry_and_breaker_counters_move_under_chaos(self):
        """Acceptance: chaos shows retry + breaker counters moving."""

        async def body(client, server, injector, key):
            injector.inject("insert", fail_count=1)
            resp = await client.post(f"/events.json?accessKey={key}", json=EVENT)
            assert resp.status == 201  # retried through the transient fault
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert sum(
                v for _, v in parsed["pio_storage_retries_total"]
            ) >= 1.0
            # now a persistent fault trips the breaker
            injector.inject("insert", fail_count=1000)
            await client.post(f"/events.json?accessKey={key}", json=EVENT)
            assert server.storage_policy.breaker.state == OPEN
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert (
                {"breaker": "eventdata", "to": "open"},
                1.0,
            ) in parsed["pio_breaker_transitions_total"]
            assert ({"breaker": "eventdata"}, 2.0) in parsed["pio_breaker_state"]
            server.storage_policy.breaker.reset()

        _run_event_server(body)

    def test_stats_json_still_backward_compatible(self):
        import sys

        sys.path.insert(0, "tests") if "tests" not in sys.path else None
        from predictionio_tpu.data.api.event_server import (
            EventServer,
            EventServerConfig,
        )
        from tests.test_event_server import make_storage

        async def outer():
            storage, key = make_storage()
            server = EventServer(
                storage=storage, config=EventServerConfig(stats=True)
            )
            client = TestClient(TestServer(server.make_app()))
            await client.start_server()
            try:
                await client.post(f"/events.json?accessKey={key}", json=EVENT)
                resp = await client.get(f"/stats.json?accessKey={key}")
                assert resp.status == 200
                data = await resp.json()
                assert data["longLive"]["statusCode"] == [
                    {"status": 201, "count": 1}
                ]
                assert data["longLive"]["basic"][0]["event"] == "rate"
                assert data["currentHour"]["startTime"]
            finally:
                await client.close()

        asyncio.run(outer())


# ---------------------------------------------------------------------------
# pio top + dashboard panels
# ---------------------------------------------------------------------------


def _fake_metrics_text(requests=100.0, shed=5.0) -> str:
    reg = MetricsRegistry()
    reg.counter(
        "pio_requests_total", labelnames=("endpoint", "status")
    ).inc(requests, endpoint="/queries.json", status="200")
    reg.counter("pio_load_shed_total").inc(shed)
    reg.counter("pio_deadline_exceeded_total").inc(2)
    reg.gauge("pio_queue_depth").set(3)
    reg.gauge("pio_queue_high_water").set(256)
    reg.gauge("pio_breaker_state", labelnames=("breaker",)).set(
        2, breaker="dispatch"
    )
    reg.counter("pio_jit_cache_misses_total", labelnames=("fn",)).inc(
        4, fn="ops.als._topk"
    )
    h = reg.histogram("pio_request_seconds", labelnames=("endpoint",))
    for v in (0.002, 0.004, 0.008, 0.2):
        h.observe(v, endpoint="/queries.json")
    return reg.render_prometheus()


class TestPioTop:
    def test_summarize_single_sample(self):
        s = summarize(parse_prometheus(_fake_metrics_text()))
        assert s["requests_total"] == 100
        assert s["shed_total"] == 5
        assert s["queue_depth"] == 3
        assert s["queue_high_water"] == 256
        assert s["recompiles"] == 4
        assert s["breakers"] == {"dispatch": "open"}
        assert s["qps"] is None  # needs two samples
        assert 0 < s["p50_ms"] < s["p99_ms"]

    def test_rates_from_two_samples(self):
        prev = parse_prometheus(_fake_metrics_text(requests=100, shed=5))
        cur = parse_prometheus(_fake_metrics_text(requests=150, shed=10))
        s = summarize(cur, prev=prev, interval_s=2.0)
        assert s["qps"] == pytest.approx(25.0)
        assert s["shed_rate"] == pytest.approx(2.5)

    def test_render_one_screen(self):
        s = summarize(parse_prometheus(_fake_metrics_text()))
        screen = render(s, "http://x:8000")
        assert "qps" in screen and "p95" in screen
        assert "dispatch=open" in screen
        assert "recompiles" in screen

    def test_stream_line_absent_without_stream_metrics(self):
        s = summarize(parse_prometheus(_fake_metrics_text()))
        assert s["stream"] is None
        assert "stream" not in render(s, "http://x")

    def test_stream_line_parsed_and_rendered(self):
        text = "\n".join(
            [
                "pio_stream_lag_events 42",
                "pio_stream_lag_seconds 3.5",
                "pio_stream_drains_total 120",
                "pio_stream_events_total 6000",
                "pio_stream_publishes_total 4",
                "pio_stream_drift_suppressed_total 1",
                "pio_stream_last_publish_timestamp 990",
            ]
        )
        s = summarize(parse_prometheus(text), now=1000.0)
        assert s["stream"]["lag_events"] == 42
        assert s["stream"]["lag_seconds"] == pytest.approx(3.5)
        assert s["stream"]["publishes_total"] == 4
        assert s["stream"]["drift_suppressed"] == 1
        assert s["stream"]["last_publish_age_s"] == pytest.approx(10.0)
        screen = render(s, "http://x")
        assert "stream" in screen
        assert "lag 42 ev / 3.5s" in screen
        assert "published 4 (age 10s)" in screen
        assert "drift-suppressed 1" in screen

    def test_stream_drain_rate_from_two_samples(self):
        prev = parse_prometheus("pio_stream_drains_total 100")
        cur = parse_prometheus("pio_stream_drains_total 110")
        s = summarize(cur, prev=prev, interval_s=5.0)
        assert s["stream_drain_rate"] == pytest.approx(2.0)
        assert "drains 2/s (110)" in render(s, "http://x")

    def test_run_top_loop_with_injected_fetch(self):
        screens: list[str] = []
        fetches = []

        def fetch(url):
            fetches.append(url)
            return _fake_metrics_text(requests=100 * (len(fetches)))

        rc = run_top(
            "http://fake:1",
            interval_s=0.0,
            iterations=3,
            fetch=fetch,
            out=screens.append,
            clear_screen=False,
            sleep=lambda s: None,
        )
        assert rc == 0
        assert len(screens) == 3
        assert "pio top — http://fake:1" in screens[0]

    def test_run_top_unreachable(self):
        screens: list[str] = []

        def fetch(url):
            raise ConnectionError("nope")

        rc = run_top(
            "http://down:1",
            iterations=1,
            fetch=fetch,
            out=screens.append,
            clear_screen=False,
        )
        assert rc == 0
        assert "unreachable" in screens[0]

    def test_cli_top_subcommand_registered(self):
        from predictionio_tpu.tools.cli import build_parser

        args = build_parser().parse_args(
            ["top", "--url", "http://h:8000", "--once"]
        )
        assert args.url == "http://h:8000" and args.once

    def test_top_against_live_server(self):
        """pio top's fetch/parse path against a real QueryServer."""

        async def body(client, server):
            await client.post("/queries.json", json={"qid": 1})
            text = await (await client.get("/metrics")).text()
            s = summarize(parse_prometheus(text))
            assert s["requests_total"] == 1
            assert s["breakers"].get("dispatch") == CLOSED
            assert render(s, "live")  # renders without raising

        _run_query_server(body)


class TestDashboardPanels:
    def test_panels_render_from_metrics(self, memory_storage):
        from predictionio_tpu.tools.dashboard import Dashboard

        dash = Dashboard(
            storage=memory_storage,
            metrics_urls=["http://qs:8000", "http://down:9"],
        )

        async def fake_fetch(url):
            return _fake_metrics_text() if "qs" in url else None

        dash._fetch_metrics = fake_fetch

        async def outer():
            client = TestClient(TestServer(dash.make_app()))
            await client.start_server()
            try:
                resp = await client.get("/")
                assert resp.status == 200
                page = await resp.text()
                assert "http://qs:8000" in page
                assert "state-open" in page  # breaker panel shows the state
                assert "jit recompiles" in page
                assert "unreachable" in page  # the down server degrades
            finally:
                await client.close()

        asyncio.run(outer())

    def test_no_sources_hint(self, memory_storage):
        from predictionio_tpu.tools.dashboard import Dashboard

        dash = Dashboard(storage=memory_storage)

        async def outer():
            client = TestClient(TestServer(dash.make_app()))
            await client.start_server()
            try:
                page = await (await client.get("/")).text()
                assert "--metrics-url" in page
            finally:
                await client.close()

        asyncio.run(outer())
