"""Observability layer tests (tier-1, CPU-only, fast).

Covers the acceptance trail of the telemetry PR: the metrics registry
(including histogram bucket math under concurrent writers), Prometheus
exposition + the `pio top` parser round-trip, trace-id propagation
end-to-end (ingress header -> micro-batch -> storage span share one trace
id, in both the ring buffer and the structured JSON log), the re-based
/stats.json, the compile watcher, and counters moving under chaos
(shed/deadline/breaker) on live servers.
"""

import asyncio
import json
import logging
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from predictionio_tpu.obs.metrics import MetricsRegistry
from predictionio_tpu.obs.tracing import (
    TRACE_HEADER,
    Tracer,
    current_trace_id,
    get_tracer,
    mint_trace_id,
    reset_trace_id,
    set_trace_id,
)
from predictionio_tpu.resilience import CLOSED, OPEN
from predictionio_tpu.tools.top import (
    parse_prometheus,
    render,
    run_top,
    summarize,
)


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class TestCounterGauge:
    def test_counter_labels_and_totals(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "help", labelnames=("status",))
        c.inc(status="200")
        c.inc(2, status="200")
        c.inc(status="503")
        assert c.value(status="200") == 3
        assert c.value(status="503") == 1
        assert c.total() == 4

    def test_counter_is_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total")
        with pytest.raises(ValueError):
            c.inc(-1)
        c.inc(5)
        c.set_total(3)  # mirror below current value: clamped, never down
        assert c.value() == 5

    def test_gauge_set_and_callback(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth")
        g.set(7)
        assert g.value() == 7
        box = {"v": 1.0}
        g2 = reg.gauge("live_depth")
        g2.set_function(lambda: box["v"])
        box["v"] = 42.0
        assert g2.value() == 42.0
        assert "live_depth 42" in reg.render_prometheus()

    def test_get_or_create_and_conflicts(self):
        reg = MetricsRegistry()
        a = reg.counter("same", labelnames=("x",))
        b = reg.counter("same", labelnames=("x",))
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("same")
        with pytest.raises(ValueError):
            reg.counter("same", labelnames=("y",))
        with pytest.raises(ValueError):
            a.inc(wrong_label="1")

    def test_invalid_names_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("bad name")
        with pytest.raises(ValueError):
            reg.counter("ok", labelnames=("bad-label",))


class TestHistogram:
    def test_percentiles_interpolate_in_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0))
        for _ in range(99):
            h.observe(0.05)  # (0.01, 0.1] bucket
        h.observe(5.0)  # +Inf bucket
        s = h.summary()
        assert s["count"] == 100
        assert 0.01 < s["p50"] <= 0.1
        assert 0.01 < s["p95"] <= 0.1
        # p99 still lands in the populated finite bucket (99 of 100)
        assert s["p99"] <= 1.0
        assert s["sum"] == pytest.approx(99 * 0.05 + 5.0)

    def test_empty_summary(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        assert h.summary() == {"count": 0}
        assert h.percentile(0.5) == 0.0

    def test_bucket_math_under_concurrent_writers(self):
        """The satellite guarantee: concurrent observes never lose or
        double-count — total count, per-bucket sums, and _sum agree."""
        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.001, 0.01, 0.1, 1.0))
        values = (0.0005, 0.005, 0.05, 0.5, 2.0)
        n_threads, per_thread = 8, 2000

        def hammer(seed: int):
            for i in range(per_thread):
                h.observe(values[(i + seed) % len(values)])

        threads = [
            threading.Thread(target=hammer, args=(t,)) for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = n_threads * per_thread
        s = h.summary()
        assert s["count"] == total
        expected_sum = sum(values) / len(values) * total
        assert s["sum"] == pytest.approx(expected_sum)
        # the rendered cumulative buckets agree with the count
        metrics = parse_prometheus(reg.render_prometheus())
        inf_bucket = [
            v for labels, v in metrics["lat_bucket"] if labels["le"] == "+Inf"
        ]
        assert inf_bucket == [total]
        # each value class landed in exactly one bucket: cumulative counts
        # step by total/len(values) per populated bound
        per_class = total // len(values)
        cums = sorted(v for _, v in metrics["lat_bucket"])
        assert cums == [per_class * (i + 1) for i in range(len(values))]


class TestPrometheusExposition:
    def test_render_and_parse_round_trip(self):
        reg = MetricsRegistry()
        reg.counter("req_total", "requests", labelnames=("status",)).inc(
            3, status="200"
        )
        reg.gauge("depth", "queue depth").set(2)
        reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.05)
        text = reg.render_prometheus()
        assert "# HELP req_total requests" in text
        assert "# TYPE req_total counter" in text
        assert "# TYPE lat histogram" in text
        parsed = parse_prometheus(text)
        assert parsed["req_total"] == [({"status": "200"}, 3.0)]
        assert parsed["depth"] == [({}, 2.0)]
        assert ({"le": "+Inf"}, 1.0) in parsed["lat_bucket"]
        assert parsed["lat_count"] == [({}, 1.0)]

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("esc_total", labelnames=("msg",)).inc(
            msg='say "hi"\nback\\slash'
        )
        parsed = parse_prometheus(reg.render_prometheus())
        [(labels, value)] = parsed["esc_total"]
        assert value == 1.0
        assert labels["msg"] == 'say "hi"\nback\\slash'

    def test_collectors_run_at_scrape(self):
        reg = MetricsRegistry()
        g = reg.gauge("sampled")
        calls = []
        reg.register_collector(lambda: (calls.append(1), g.set(len(calls))))
        reg.render_prometheus()
        snap = reg.snapshot()
        assert len(calls) == 2
        assert snap["sampled"]["samples"][0]["value"] == 2


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


class TestTracing:
    def test_span_records_ring_and_log(self, caplog):
        tracer = Tracer(ring_size=4)
        token = set_trace_id("feedbeef00000000")
        try:
            with caplog.at_level(logging.INFO, logger="pio.trace"):
                with tracer.span("unit.work", kind="serving", step=1) as sp:
                    sp.tags["extra"] = "yes"
        finally:
            reset_trace_id(token)
        [recent] = tracer.recent()
        assert recent["traceId"] == "feedbeef00000000"
        assert recent["name"] == "unit.work"
        assert recent["kind"] == "serving"
        assert recent["tags"] == {"step": 1, "extra": "yes"}
        assert recent["durationMs"] >= 0
        # the structured log line is the span as one JSON object
        line = json.loads(caplog.records[-1].getMessage())
        assert line["traceId"] == "feedbeef00000000"
        assert line["status"] == "ok"

    def test_span_marks_error_status_and_reraises(self):
        tracer = Tracer()
        with pytest.raises(ValueError):
            with tracer.span("explodes"):
                raise ValueError("boom")
        assert tracer.recent()[0]["status"] == "ValueError"

    def test_ring_is_bounded_newest_first(self):
        tracer = Tracer(ring_size=3)
        for i in range(5):
            tracer.record_span(f"s{i}", "internal", 0.0)
        names = [s["name"] for s in tracer.recent()]
        assert names == ["s4", "s3", "s2"]
        assert tracer.spans_recorded == 5

    def test_contextvar_isolation(self):
        assert current_trace_id() is None
        token = set_trace_id("aaaa")
        assert current_trace_id() == "aaaa"
        reset_trace_id(token)
        assert current_trace_id() is None

    def test_mint_is_unique(self):
        assert mint_trace_id() != mint_trace_id()


# ---------------------------------------------------------------------------
# compile watcher
# ---------------------------------------------------------------------------


class TestCompileWatcher:
    def test_counts_recompiles_after_baseline(self, caplog):
        import jax
        import jax.numpy as jnp

        from predictionio_tpu.obs.jaxprof import CompileWatcher

        reg = MetricsRegistry()
        watcher = CompileWatcher(reg, storm_threshold=2)

        @jax.jit
        def f(x):
            return x * 2

        f(jnp.ones(2))  # warmup compile, then baseline
        assert watcher.watch("test.f", f)
        assert watcher.sample() == 0  # baseline: warmup doesn't count
        f(jnp.ones(2))  # cache hit
        assert watcher.sample() == 0
        with caplog.at_level(logging.WARNING):
            f(jnp.ones(3))  # new shape -> recompile
            f(jnp.ones(4))  # another -> storm at threshold 2
            assert watcher.sample() == 2
        assert watcher.total_misses() == 2
        assert any("recompile storm" in r.getMessage() for r in caplog.records)
        parsed = parse_prometheus(reg.render_prometheus())
        assert (
            sum(v for _, v in parsed["pio_jit_cache_misses_total"]) == 2
        )
        sizes = {l["fn"]: v for l, v in parsed["pio_jit_cache_size"]}
        assert sizes["test.f"] == 3


# ---------------------------------------------------------------------------
# stats.json re-base
# ---------------------------------------------------------------------------


class TestStatsRebase:
    def _event(self, name="rate", target=None):
        from predictionio_tpu.data.event import Event

        return Event(
            event=name,
            entity_type="user",
            entity_id="u1",
            target_entity_type=target,
            target_entity_id="i1" if target else None,
        )

    def test_legacy_shape_and_registry_agree(self):
        from predictionio_tpu.data.api.stats import StatsCollector

        reg = MetricsRegistry()
        stats = StatsCollector(registry=reg)
        stats.bookkeeping(1, 201, self._event())
        stats.bookkeeping(1, 201, self._event(target="item"))
        stats.bookkeeping(1, 500, self._event())
        stats.bookkeeping(2, 201, self._event())  # other app: filtered out
        out = stats.get_stats(1)
        assert out["longLive"]["statusCode"] == [
            {"status": 201, "count": 2},
            {"status": 500, "count": 1},
        ]
        basic = out["longLive"]["basic"]
        assert {b["event"] for b in basic} == {"rate"}
        assert {b["targetEntityType"] for b in basic} == {None, "item"}
        assert out["currentHour"]["statusCode"] == out["longLive"]["statusCode"]
        assert "prevHour" not in out
        # the same totals back /metrics
        parsed = parse_prometheus(reg.render_prometheus())
        totals = {
            (l["app_id"], l["status"]): v
            for l, v in parsed["pio_events_ingested_total"]
        }
        assert totals[("1", "201")] == 2
        assert totals[("2", "201")] == 1


# ---------------------------------------------------------------------------
# query server end-to-end
# ---------------------------------------------------------------------------


def _run_query_server(body, **cfg_kw):
    import sys

    sys.path.insert(0, "tests") if "tests" not in sys.path else None
    from tests.test_resilience import _make_query_server

    async def outer():
        get_tracer().clear()
        server = _make_query_server(**cfg_kw)
        client = TestClient(TestServer(server.make_app()))
        await client.start_server()
        try:
            await body(client, server)
        finally:
            await client.close()

    asyncio.run(outer())


class TestQueryServerObs:
    def test_metrics_endpoint_is_prometheus_parseable(self):
        """Acceptance: GET /metrics on a deployed QueryServer returns
        Prometheus-parseable text including the request latency histogram,
        admission-queue depth, breaker state, and jit recompile count."""

        async def body(client, server):
            for qid in range(3):
                resp = await client.post("/queries.json", json={"qid": qid})
                assert resp.status == 200
            m = await client.get("/metrics")
            assert m.status == 200
            assert m.headers["Content-Type"].startswith("text/plain")
            parsed = parse_prometheus(await m.text())
            assert (
                {"endpoint": "/queries.json", "status": "200"},
                3.0,
            ) in parsed["pio_requests_total"]
            assert any(
                l.get("le") == "+Inf" and v == 3.0
                for l, v in parsed["pio_request_seconds_bucket"]
            )
            assert parsed["pio_queue_depth"] == [({}, 0.0)]
            assert ({"breaker": "dispatch"}, 0.0) in parsed["pio_breaker_state"]
            # jit recompile count present (0 after warmup baseline is fine)
            assert "pio_jit_recompile_storm" in parsed
            assert "pio_load_shed_total" in parsed
            assert "pio_deadline_exceeded_total" in parsed

        _run_query_server(body)

    def test_trace_id_spans_ingress_batch_and_storage(self, memory_storage):
        """Acceptance: one trace id observed across ingress, batch, and
        storage spans — via the ring buffer, /traces/recent, and the
        structured JSON log."""
        from predictionio_tpu.data.storage.traced import trace_dao
        from tests.sample_engine import Serving0

        traced_apps = trace_dao(
            memory_storage.get_meta_data_apps(), "apps"
        )

        class StorageTouchingServing(Serving0):
            """Realistic query-time storage read (e.g. the ecommerce
            template fetching recent user events at predict time)."""

            def supplement(self, query):
                traced_apps.get_all()
                return query

        tid = mint_trace_id()

        async def body(client, server):
            trace_logger = logging.getLogger("pio.trace")
            records: list[str] = []

            class Capture(logging.Handler):
                def emit(self, record):
                    records.append(record.getMessage())

            handler = Capture(level=logging.INFO)
            old_level = trace_logger.level
            trace_logger.setLevel(logging.INFO)
            trace_logger.addHandler(handler)
            try:
                resp = await client.post(
                    "/queries.json",
                    json={"qid": 5},
                    headers={TRACE_HEADER: tid},
                )
                assert resp.status == 200
                assert resp.headers[TRACE_HEADER] == tid
            finally:
                trace_logger.removeHandler(handler)
                trace_logger.setLevel(old_level)
            spans = get_tracer().find(tid)
            kinds = {s["kind"] for s in spans}
            assert {"ingress", "batch", "storage"} <= kinds, spans
            storage_span = next(s for s in spans if s["kind"] == "storage")
            assert storage_span["name"] == "storage.apps.get_all"
            batch_span = next(s for s in spans if s["kind"] == "batch")
            for key in ("queue_ms", "dispatch_ms", "fetch_ms"):
                assert key in batch_span["tags"]
            # /traces/recent serves the same spans
            t = await client.get("/traces/recent?limit=50")
            served = [s for s in (await t.json())["spans"] if s["traceId"] == tid]
            assert {s["kind"] for s in served} >= {"ingress", "batch", "storage"}
            # the structured log saw all three hops under ONE trace id
            logged = [json.loads(r) for r in records]
            logged_kinds = {s["kind"] for s in logged if s["traceId"] == tid}
            assert {"ingress", "batch", "storage"} <= logged_kinds

        # swap the serving class into the engine the helper builds
        import sys

        sys.path.insert(0, "tests") if "tests" not in sys.path else None
        from tests.test_resilience import _make_query_server

        async def outer():
            get_tracer().clear()
            server = _make_query_server()
            engine = server.engine
            engine.serving_classes = {"s": StorageTouchingServing}
            server._active = server._active._replace(
                serving=StorageTouchingServing()
            )
            client = TestClient(TestServer(server.make_app()))
            await client.start_server()
            try:
                await body(client, server)
            finally:
                await client.close()

        asyncio.run(outer())

    def test_shed_and_deadline_counters_move_under_chaos(self):
        """Acceptance: a chaos run shows shed/deadline counters moving."""
        from tests.sample_engine import Algo0

        async def body(client, server):
            # wedge the dispatch path so queries pile into the queue
            original = Algo0.predict_batch_dispatch

            def slow_dispatch(self, model, queries):
                import time as _t

                _t.sleep(0.4)  # > request_timeout_s
                return original(self, model, queries)

            Algo0.predict_batch_dispatch = slow_dispatch
            try:
                results = await asyncio.gather(
                    *(
                        client.post("/queries.json", json={"qid": i})
                        for i in range(8)
                    )
                )
                statuses = [r.status for r in results]
                assert all(s in (200, 503) for s in statuses)
                assert 503 in statuses
            finally:
                Algo0.predict_batch_dispatch = original
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            shed = sum(v for _, v in parsed.get("pio_load_shed_total", ()))
            deadlines = sum(
                v for _, v in parsed.get("pio_deadline_exceeded_total", ())
            )
            assert shed + deadlines > 0
            # 503s are counted per status by the envelope
            assert any(
                l.get("status") == "503" and v > 0
                for l, v in parsed["pio_requests_total"]
            )

        _run_query_server(
            body,
            request_timeout_s=0.15,
            queue_high_water=2,
            max_batch_size=1,
        )

    def test_breaker_transitions_counted(self):
        async def body(client, server):
            for _ in range(server.config.breaker_threshold):
                server.dispatch_breaker.record_failure()
            assert server.dispatch_breaker.state == OPEN
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert (
                {"breaker": "dispatch", "to": "open"},
                1.0,
            ) in parsed["pio_breaker_transitions_total"]
            assert ({"breaker": "dispatch"}, 2.0) in parsed["pio_breaker_state"]
            server.dispatch_breaker.reset()
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert (
                {"breaker": "dispatch", "to": "closed"},
                1.0,
            ) in parsed["pio_breaker_transitions_total"]

        _run_query_server(body)


# ---------------------------------------------------------------------------
# event server end-to-end
# ---------------------------------------------------------------------------


EVENT = {"event": "rate", "entityType": "user", "entityId": "u1"}


def _run_event_server(body):
    import sys

    sys.path.insert(0, "tests") if "tests" not in sys.path else None
    from tests.test_resilience import _make_event_server

    async def outer():
        get_tracer().clear()
        server, injector, key = _make_event_server()
        client = TestClient(TestServer(server.make_app()))
        await client.start_server()
        try:
            await body(client, server, injector, key)
        finally:
            await client.close()

    asyncio.run(outer())


class TestEventServerObs:
    def test_metrics_and_trace_header(self):
        async def body(client, server, injector, key):
            tid = mint_trace_id()
            resp = await client.post(
                f"/events.json?accessKey={key}",
                json=EVENT,
                headers={TRACE_HEADER: tid},
            )
            assert resp.status == 201
            assert resp.headers[TRACE_HEADER] == tid
            # the storage span joined the ingress trace across the
            # executor hop
            spans = get_tracer().find(tid)
            kinds = {s["kind"] for s in spans}
            assert {"ingress", "storage"} <= kinds, spans
            names = {s["name"] for s in spans}
            assert "storage.l_events.insert" in names
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert (
                {"endpoint": "/events.json", "status": "201"},
                1.0,
            ) in parsed["pio_requests_total"]
            # ingestion counters are always-on (the --stats flag only
            # gates serving the legacy /stats.json view)
            assert any(
                l["status"] == "201" and v == 1.0
                for l, v in parsed["pio_events_ingested_total"]
            )
            assert ({"breaker": "eventdata"}, 0.0) in parsed["pio_breaker_state"]

        _run_event_server(body)

    def test_retry_and_breaker_counters_move_under_chaos(self):
        """Acceptance: chaos shows retry + breaker counters moving."""

        async def body(client, server, injector, key):
            injector.inject("insert", fail_count=1)
            resp = await client.post(f"/events.json?accessKey={key}", json=EVENT)
            assert resp.status == 201  # retried through the transient fault
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert sum(
                v for _, v in parsed["pio_storage_retries_total"]
            ) >= 1.0
            # now a persistent fault trips the breaker
            injector.inject("insert", fail_count=1000)
            await client.post(f"/events.json?accessKey={key}", json=EVENT)
            assert server.storage_policy.breaker.state == OPEN
            parsed = parse_prometheus(await (await client.get("/metrics")).text())
            assert (
                {"breaker": "eventdata", "to": "open"},
                1.0,
            ) in parsed["pio_breaker_transitions_total"]
            assert ({"breaker": "eventdata"}, 2.0) in parsed["pio_breaker_state"]
            server.storage_policy.breaker.reset()

        _run_event_server(body)

    def test_stats_json_still_backward_compatible(self):
        import sys

        sys.path.insert(0, "tests") if "tests" not in sys.path else None
        from predictionio_tpu.data.api.event_server import (
            EventServer,
            EventServerConfig,
        )
        from tests.test_event_server import make_storage

        async def outer():
            storage, key = make_storage()
            server = EventServer(
                storage=storage, config=EventServerConfig(stats=True)
            )
            client = TestClient(TestServer(server.make_app()))
            await client.start_server()
            try:
                await client.post(f"/events.json?accessKey={key}", json=EVENT)
                resp = await client.get(f"/stats.json?accessKey={key}")
                assert resp.status == 200
                data = await resp.json()
                assert data["longLive"]["statusCode"] == [
                    {"status": 201, "count": 1}
                ]
                assert data["longLive"]["basic"][0]["event"] == "rate"
                assert data["currentHour"]["startTime"]
            finally:
                await client.close()

        asyncio.run(outer())


# ---------------------------------------------------------------------------
# pio top + dashboard panels
# ---------------------------------------------------------------------------


def _fake_metrics_text(requests=100.0, shed=5.0) -> str:
    reg = MetricsRegistry()
    reg.counter(
        "pio_requests_total", labelnames=("endpoint", "status")
    ).inc(requests, endpoint="/queries.json", status="200")
    reg.counter("pio_load_shed_total").inc(shed)
    reg.counter("pio_deadline_exceeded_total").inc(2)
    reg.gauge("pio_queue_depth").set(3)
    reg.gauge("pio_queue_high_water").set(256)
    reg.gauge("pio_breaker_state", labelnames=("breaker",)).set(
        2, breaker="dispatch"
    )
    reg.counter("pio_jit_cache_misses_total", labelnames=("fn",)).inc(
        4, fn="ops.als._topk"
    )
    h = reg.histogram("pio_request_seconds", labelnames=("endpoint",))
    for v in (0.002, 0.004, 0.008, 0.2):
        h.observe(v, endpoint="/queries.json")
    return reg.render_prometheus()


class TestPioTop:
    def test_summarize_single_sample(self):
        s = summarize(parse_prometheus(_fake_metrics_text()))
        assert s["requests_total"] == 100
        assert s["shed_total"] == 5
        assert s["queue_depth"] == 3
        assert s["queue_high_water"] == 256
        assert s["recompiles"] == 4
        assert s["breakers"] == {"dispatch": "open"}
        assert s["qps"] is None  # needs two samples
        assert 0 < s["p50_ms"] < s["p99_ms"]

    def test_rates_from_two_samples(self):
        prev = parse_prometheus(_fake_metrics_text(requests=100, shed=5))
        cur = parse_prometheus(_fake_metrics_text(requests=150, shed=10))
        s = summarize(cur, prev=prev, interval_s=2.0)
        assert s["qps"] == pytest.approx(25.0)
        assert s["shed_rate"] == pytest.approx(2.5)

    def test_render_one_screen(self):
        s = summarize(parse_prometheus(_fake_metrics_text()))
        screen = render(s, "http://x:8000")
        assert "qps" in screen and "p95" in screen
        assert "dispatch=open" in screen
        assert "recompiles" in screen

    def test_stream_line_absent_without_stream_metrics(self):
        s = summarize(parse_prometheus(_fake_metrics_text()))
        assert s["stream"] is None
        assert "stream" not in render(s, "http://x")

    def test_stream_line_parsed_and_rendered(self):
        text = "\n".join(
            [
                "pio_stream_lag_events 42",
                "pio_stream_lag_seconds 3.5",
                "pio_stream_drains_total 120",
                "pio_stream_events_total 6000",
                "pio_stream_publishes_total 4",
                "pio_stream_drift_suppressed_total 1",
                "pio_stream_last_publish_timestamp 990",
            ]
        )
        s = summarize(parse_prometheus(text), now=1000.0)
        assert s["stream"]["lag_events"] == 42
        assert s["stream"]["lag_seconds"] == pytest.approx(3.5)
        assert s["stream"]["publishes_total"] == 4
        assert s["stream"]["drift_suppressed"] == 1
        assert s["stream"]["last_publish_age_s"] == pytest.approx(10.0)
        screen = render(s, "http://x")
        assert "stream" in screen
        assert "lag 42 ev / 3.5s" in screen
        assert "published 4 (age 10s)" in screen
        assert "drift-suppressed 1" in screen

    def test_stream_drain_rate_from_two_samples(self):
        prev = parse_prometheus("pio_stream_drains_total 100")
        cur = parse_prometheus("pio_stream_drains_total 110")
        s = summarize(cur, prev=prev, interval_s=5.0)
        assert s["stream_drain_rate"] == pytest.approx(2.0)
        assert "drains 2/s (110)" in render(s, "http://x")

    def test_run_top_loop_with_injected_fetch(self):
        screens: list[str] = []
        fetches = []

        def fetch(url):
            fetches.append(url)
            return _fake_metrics_text(requests=100 * (len(fetches)))

        rc = run_top(
            "http://fake:1",
            interval_s=0.0,
            iterations=3,
            fetch=fetch,
            out=screens.append,
            clear_screen=False,
            sleep=lambda s: None,
        )
        assert rc == 0
        assert len(screens) == 3
        assert "pio top — http://fake:1" in screens[0]

    def test_run_top_unreachable(self):
        screens: list[str] = []

        def fetch(url):
            raise ConnectionError("nope")

        rc = run_top(
            "http://down:1",
            iterations=1,
            fetch=fetch,
            out=screens.append,
            clear_screen=False,
        )
        assert rc == 0
        assert "unreachable" in screens[0]

    def test_cli_top_subcommand_registered(self):
        from predictionio_tpu.tools.cli import build_parser

        args = build_parser().parse_args(
            ["top", "--url", "http://h:8000", "--once"]
        )
        assert args.url == "http://h:8000" and args.once

    def test_top_against_live_server(self):
        """pio top's fetch/parse path against a real QueryServer."""

        async def body(client, server):
            await client.post("/queries.json", json={"qid": 1})
            text = await (await client.get("/metrics")).text()
            s = summarize(parse_prometheus(text))
            assert s["requests_total"] == 1
            assert s["breakers"].get("dispatch") == CLOSED
            assert render(s, "live")  # renders without raising

        _run_query_server(body)


class TestDashboardPanels:
    def test_panels_render_from_metrics(self, memory_storage):
        from predictionio_tpu.tools.dashboard import Dashboard

        dash = Dashboard(
            storage=memory_storage,
            metrics_urls=["http://qs:8000", "http://down:9"],
        )

        async def fake_fetch(url):
            return _fake_metrics_text() if "qs" in url else None

        dash._fetch_metrics = fake_fetch

        async def outer():
            client = TestClient(TestServer(dash.make_app()))
            await client.start_server()
            try:
                resp = await client.get("/")
                assert resp.status == 200
                page = await resp.text()
                assert "http://qs:8000" in page
                assert "state-open" in page  # breaker panel shows the state
                assert "jit recompiles" in page
                assert "unreachable" in page  # the down server degrades
            finally:
                await client.close()

        asyncio.run(outer())

    def test_no_sources_hint(self, memory_storage):
        from predictionio_tpu.tools.dashboard import Dashboard

        dash = Dashboard(storage=memory_storage)

        async def outer():
            client = TestClient(TestServer(dash.make_app()))
            await client.start_server()
            try:
                page = await (await client.get("/")).text()
                assert "--metrics-url" in page
            finally:
                await client.close()

        asyncio.run(outer())


# ---------------------------------------------------------------------------
# histogram exemplars
# ---------------------------------------------------------------------------


class TestExemplars:
    def test_observe_with_exemplar_and_accessor(self):
        reg = MetricsRegistry()
        h = reg.histogram("ex_seconds", labelnames=("phase",))
        h.observe(0.0003, exemplar="aaaa000011112222", phase="fetch")
        h.observe(0.2, exemplar="bbbb000011112222", phase="fetch")
        ex = h.exemplars(phase="fetch")
        assert ex["0.0005"]["exemplar"] == "aaaa000011112222"
        assert ex["0.25"]["exemplar"] == "bbbb000011112222"
        assert ex["0.25"]["value"] == pytest.approx(0.2)

    def test_last_writer_wins_per_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("ex2_seconds")
        h.observe(0.0003, exemplar="first000")
        h.observe(0.0004, exemplar="second00")
        assert h.exemplars()["0.0005"]["exemplar"] == "second00"

    def test_render_plain_vs_openmetrics(self):
        reg = MetricsRegistry()
        h = reg.histogram("ex3_seconds")
        h.observe(0.0003, exemplar="cafe0000deadbeef")
        plain = reg.render_prometheus()
        assert "trace_id" not in plain  # strict v0.0.4 stays strict
        assert "# EOF" not in plain
        om = reg.render_prometheus(exemplars=True)
        assert '# {trace_id="cafe0000deadbeef"} 0.0003' in om
        assert om.rstrip().endswith("# EOF")

    def test_top_parser_tolerates_exemplar_clauses(self):
        reg = MetricsRegistry()
        h = reg.histogram("ex4_seconds")
        for v in (0.0003, 0.002, 0.3):
            h.observe(v, exemplar="feed0000feed0000")
        parsed = parse_prometheus(reg.render_prometheus(exemplars=True))
        # every bucket line still parses to its numeric value
        assert sum(
            v for l, v in parsed["ex4_seconds_bucket"] if l.get("le") == "+Inf"
        ) == 3.0
        assert parsed["ex4_seconds_count"] == [({}, 3.0)]


# ---------------------------------------------------------------------------
# phase waterfall end-to-end (the latency-attribution acceptance trail)
# ---------------------------------------------------------------------------


PHASE_NAMES = (
    "ingress_parse",
    "cache",  # version-keyed result-cache lookup (PR 8)
    "queue_wait",
    "batch_assembly",
    "dispatch",
    "device_compute",
    "fetch",
    "serve",
    "respond",
)


class TestWaterfallE2E:
    def test_phases_tile_e2e_latency_within_tolerance(self):
        """Acceptance: a serving round-trip produces a phase waterfall
        whose per-phase means sum to within 10% of the measured e2e
        latency (they tile the same wall clock by construction)."""

        async def body(client, server):
            for i in range(40):
                resp = await client.post("/queries.json", json={"qid": i})
                assert resp.status == 200
            hist = server.waterfall.hist
            counts = {p: hist.summary(phase=p).get("count") for p in PHASE_NAMES}
            assert all(c == 40 for c in counts.values()), counts
            phase_sum = sum(hist.summary(phase=p)["mean"] for p in PHASE_NAMES)
            e2e = server._m_latency.summary(endpoint="/queries.json")["mean"]
            assert phase_sum == pytest.approx(e2e, rel=0.10)

        _run_query_server(body)

    def test_phase_exemplar_resolves_to_trace(self):
        """Acceptance: every phase is visible on /metrics with an exemplar
        trace id resolvable in /traces/recent."""
        import re as _re

        async def body(client, server):
            for i in range(5):
                await client.post("/queries.json", json={"qid": i})
            m = await client.get("/metrics?exemplars=1")
            assert m.headers["Content-Type"].startswith(
                "application/openmetrics-text"
            )
            text = await m.text()
            by_phase: dict[str, set] = {}
            for match in _re.finditer(
                r'pio_phase_seconds_bucket\{phase="([a-z_]+)"[^}]*\}'
                r' \d+ # \{trace_id="([0-9a-f]+)"\}',
                text,
            ):
                by_phase.setdefault(match.group(1), set()).add(match.group(2))
            assert set(by_phase) == set(PHASE_NAMES), sorted(by_phase)
            served = (await (await client.get("/traces/recent?limit=500")).json())[
                "spans"
            ]
            ring_ids = {s["traceId"] for s in served}
            for phase, tids in by_phase.items():
                assert tids & ring_ids, f"{phase} exemplars not in trace ring"

        _run_query_server(body)

    def test_batch_and_ingress_spans_carry_phase_tags(self):
        tid = mint_trace_id()

        async def body(client, server):
            await client.post(
                "/queries.json", json={"qid": 1}, headers={TRACE_HEADER: tid}
            )
            spans = get_tracer().find(tid)
            batch = next(s for s in spans if s["kind"] == "batch")
            for key in (
                "queue_ms",
                "dispatch_ms",
                "fetch_ms",
                "device_compute_ms",
                "serve_ms",
                "fetch_residual_ms",
            ):
                assert key in batch["tags"], batch["tags"]
            ingress = next(s for s in spans if s["kind"] == "ingress")
            assert "ingress_parse_ms" in ingress["tags"]
            assert "respond_ms" in ingress["tags"]

        _run_query_server(body)

    def test_default_metrics_scrape_stays_plain_v004(self):
        async def body(client, server):
            await client.post("/queries.json", json={"qid": 1})
            m = await client.get("/metrics")
            assert m.headers["Content-Type"].startswith("text/plain")
            assert "trace_id" not in await m.text()

        _run_query_server(body)


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------


class TestSLOEngine:
    def _engine_with_counter(self, objective=0.999):
        from predictionio_tpu.obs.slo import SLOEngine, counter_ratio_source

        reg = MetricsRegistry()
        c = reg.counter("t_total", labelnames=("status",))
        engine = SLOEngine(reg)
        engine.add(
            "availability",
            "non-5xx",
            objective,
            counter_ratio_source(
                c, bad=lambda l: l.get("status", "").startswith("5")
            ),
        )
        return reg, c, engine

    def test_burn_rate_math_multi_window(self):
        reg, c, engine = self._engine_with_counter(objective=0.999)
        c.inc(100, status="200")
        engine.tick(now=0.0)
        c.inc(90, status="200")
        c.inc(10, status="503")
        engine.tick(now=100.0)
        [report] = engine.evaluate(now=100.0)
        fast, slow = report["windows"]
        # 10 bad / 100 total over the window = 10% bad; budget 0.1% -> 100x
        assert fast["bad_ratio"] == pytest.approx(0.1)
        assert fast["burn_rate"] == pytest.approx(100.0)
        assert slow["burn_rate"] == pytest.approx(100.0)
        assert report["alerting"] is True
        assert report["budget_remaining"] == 0.0
        # gauges refreshed for pio top / Prometheus
        parsed = parse_prometheus(reg.render_prometheus())
        burns = {
            l["window"]: v
            for l, v in parsed["pio_slo_burn_rate"]
            if l["slo"] == "availability"
        }
        assert burns["300"] == pytest.approx(100.0)
        assert ({"slo": "availability"}, 1.0) in parsed["pio_slo_alerting"]

    def test_healthy_traffic_not_alerting(self):
        reg, c, engine = self._engine_with_counter(objective=0.5)
        c.inc(100, status="200")
        engine.tick(now=0.0)
        c.inc(100, status="200")
        c.inc(10, status="503")
        engine.tick(now=60.0)
        [report] = engine.evaluate(now=60.0)
        # ~9% bad against a 50% budget: burn ~0.18, nowhere near threshold
        assert report["windows"][0]["burn_rate"] < 1.0
        assert report["alerting"] is False
        assert report["budget_remaining"] > 0.5

    def test_single_sample_is_no_data_not_alert(self):
        reg, c, engine = self._engine_with_counter()
        c.inc(5, status="500")
        engine.tick(now=0.0)
        [report] = engine.evaluate(now=0.0)
        assert report["alerting"] is False
        assert all(w["burn_rate"] == 0.0 for w in report["windows"])

    def test_histogram_threshold_source_counts_over_threshold(self):
        from predictionio_tpu.obs.slo import histogram_threshold_source

        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", labelnames=("endpoint",))
        for _ in range(9):
            h.observe(0.005, endpoint="/q")
        h.observe(0.05, endpoint="/q")
        src = histogram_threshold_source(h, 0.010, endpoint="/q")
        total, bad = src()
        assert (total, bad) == (10, 1)

    def test_loose_objective_can_still_alert(self):
        """Burn is bounded by 1/budget, so the SRE-default thresholds
        (14.4/6) are unreachable for a p50-style objective of 0.50 —
        thresholds must clamp to the achievable ceiling or the flagship
        latency SLO could structurally never alert."""
        reg, c, engine = self._engine_with_counter(objective=0.50)
        c.inc(10, status="200")
        engine.tick(now=0.0)
        c.inc(100, status="503")  # every event bad: burn = 1/0.5 = 2.0
        engine.tick(now=100.0)
        [report] = engine.evaluate(now=100.0)
        assert report["windows"][0]["burn_rate"] == pytest.approx(2.0)
        # clamped threshold: min(14.4, 0.9 * 2.0) = 1.8 < 2.0 -> alert
        assert report["windows"][0]["max_burn"] == pytest.approx(1.8)
        assert report["alerting"] is True

    def test_event_server_availability_rates_collection_routes_only(self):
        """A 100% ingestion outage must alert even while health checks
        and scrapes (counted by the same middleware) keep succeeding."""

        async def body(client, server, injector, key):
            # monitoring traffic: healthy non-collection requests
            for _ in range(20):
                server._m_requests.inc(endpoint="/healthz", status="200")
            server.slo.tick(now=0.0)
            # the entire collection API fails
            for _ in range(10):
                server._m_requests.inc(endpoint="/events.json", status="503")
            for _ in range(20):
                server._m_requests.inc(endpoint="/healthz", status="200")
            server.slo.tick(now=100.0)
            [report] = server.slo.evaluate(now=100.0)
            fast = report["windows"][0]
            assert fast["total"] == 10.0  # /healthz not in the denominator
            assert fast["bad_ratio"] == pytest.approx(1.0)
            assert report["alerting"] is True

        _run_event_server(body)

    def test_duplicate_and_invalid_objectives_rejected(self):
        reg, c, engine = self._engine_with_counter()
        with pytest.raises(ValueError):
            engine.add("availability", "dup", 0.9, lambda: (0, 0))
        with pytest.raises(ValueError):
            engine.add("impossible", "no budget", 1.0, lambda: (0, 0))

    def test_slo_endpoint_on_live_server(self):
        async def body(client, server):
            for i in range(3):
                await client.post("/queries.json", json={"qid": i})
            resp = await client.get("/slo")
            assert resp.status == 200
            data = await resp.json()
            names = {s["name"] for s in data["slos"]}
            assert names == {"latency", "availability", "shed"}
            for s in data["slos"]:
                assert {"objective", "windows", "alerting"} <= set(s)
            # the /slo report embeds the phase waterfall summary
            assert set(data["phases"]) == set(PHASE_NAMES)

        _run_query_server(body)

    def test_event_server_slo_endpoint(self):
        async def body(client, server, injector, key):
            await client.post(f"/events.json?accessKey={key}", json=EVENT)
            data = await (await client.get("/slo")).json()
            assert [s["name"] for s in data["slos"]] == ["availability"]

        _run_event_server(body)


# ---------------------------------------------------------------------------
# pio top: waterfall + SLO + --json
# ---------------------------------------------------------------------------


def _waterfall_metrics_text() -> str:
    reg = MetricsRegistry()
    h = reg.histogram("pio_phase_seconds", labelnames=("phase",))
    for phase, v in (
        ("ingress_parse", 0.0002),
        ("queue_wait", 0.0001),
        ("dispatch", 0.002),
        ("fetch", 0.004),
    ):
        h.observe(v, phase=phase)
    reg.gauge("pio_slo_objective", labelnames=("slo",)).set(0.5, slo="latency")
    g = reg.gauge("pio_slo_burn_rate", labelnames=("slo", "window"))
    g.set(0.4, slo="latency", window="300")
    g.set(0.2, slo="latency", window="3600")
    reg.gauge("pio_slo_alerting", labelnames=("slo",)).set(1.0, slo="latency")
    return _fake_metrics_text() + reg.render_prometheus()


class TestTopWaterfallSLO:
    def test_phases_and_slo_summarized(self):
        s = summarize(parse_prometheus(_waterfall_metrics_text()))
        assert list(s["phases"]) == [
            "ingress_parse",
            "queue_wait",
            "dispatch",
            "fetch",
        ]  # request order, not alphabetical
        assert s["phases"]["fetch"]["count"] == 1
        assert s["phases"]["fetch"]["p50_ms"] > s["phases"]["queue_wait"]["p50_ms"]
        assert s["slo"]["latency"]["objective"] == 0.5
        assert s["slo"]["latency"]["burn"] == {"300": 0.4, "3600": 0.2}
        assert s["slo"]["latency"]["alerting"] is True

    def test_render_waterfall_and_slo_lines(self):
        s = summarize(parse_prometheus(_waterfall_metrics_text()))
        screen = render(s, "http://x")
        assert "waterfall" in screen
        assert "ingress parse" in screen and "fetch" in screen
        assert "slo" in screen
        assert "latency burn 0.40/0.20 ALERT" in screen

    def test_absent_without_waterfall_metrics(self):
        s = summarize(parse_prometheus(_fake_metrics_text()))
        assert s["phases"] is None and s["slo"] is None
        screen = render(s, "http://x")
        assert "waterfall" not in screen and "slo" not in screen

    def test_json_mode_one_object_per_snapshot(self):
        outs: list[str] = []
        rc = run_top(
            "http://fake:1",
            interval_s=0.0,
            iterations=3,
            fetch=lambda url: _waterfall_metrics_text(),
            out=outs.append,
            sleep=lambda s: None,
            json_mode=True,
        )
        assert rc == 0
        assert len(outs) == 3
        for line in outs:
            snap = json.loads(line)  # every snapshot is one valid JSON line
            assert snap["url"] == "http://fake:1"
            assert snap["phases"]["dispatch"]["count"] == 1
            assert snap["slo"]["latency"]["alerting"] is True
            assert "\x1b" not in line  # no screen control codes

    def test_json_mode_unreachable_is_json_too(self):
        outs: list[str] = []

        def fetch(url):
            raise ConnectionError("nope")

        run_top(
            "http://down:1",
            iterations=1,
            fetch=fetch,
            out=outs.append,
            json_mode=True,
        )
        assert json.loads(outs[0])["error"] == "nope"

    def test_cli_top_json_flag(self):
        from predictionio_tpu.tools.cli import build_parser

        args = build_parser().parse_args(["top", "--json", "--once"])
        assert args.json and args.once


# ---------------------------------------------------------------------------
# metrics contract: every documented pio_* metric is actually registered
# ---------------------------------------------------------------------------


class TestMetricsContract:
    def test_documented_metrics_all_registered(self, tmp_path):
        """Every `pio_*` metric named in the docs/observability.md tables
        must be registered (and therefore exported with a # TYPE line) by
        the surface that owns it — docs that drift from the exporters are
        worse than no docs."""
        import os
        import re as _re
        import sys

        sys.path.insert(0, "tests") if "tests" not in sys.path else None
        from predictionio_tpu.fleet.gateway import Gateway, GatewayConfig
        from predictionio_tpu.fleet.supervisor import Supervisor, WorkerSpec
        from predictionio_tpu.obs.metrics import MetricsRegistry
        from predictionio_tpu.stream.pipeline import StreamInstruments
        from tests.test_resilience import _make_event_server, _make_query_server

        doc = open(
            os.path.join(os.path.dirname(__file__), "..", "docs", "observability.md")
        ).read()
        documented = set()
        for line in doc.splitlines():
            if line.lstrip().startswith("|"):
                documented.update(_re.findall(r"`(pio_[a-z0-9_]+)`", line))
        assert len(documented) > 30, "doc tables went missing?"

        registered: set[str] = set()
        qs = _make_query_server()
        registered.update(qs.metrics._metrics)
        es, _, _ = _make_event_server()
        registered.update(es.metrics._metrics)
        registered.update(StreamInstruments().registry._metrics)
        # the offline batchpredict family rides the run's own registry
        # (no server to scrape — docs/batch_predict.md)
        from predictionio_tpu.workflow.batch_predict import (
            BatchPredictInstruments,
        )

        registered.update(BatchPredictInstruments().registry._metrics)
        # the evaluation-grid family rides the grid run's own registry
        # (docs/evaluation.md)
        from predictionio_tpu.tuning import EvalGridInstruments

        registered.update(EvalGridInstruments().registry._metrics)
        # the fleet family lives on the gateway/supervisor registry (the
        # `pio deploy --fleet` parent), not on any worker's — including
        # the flight-recorder instruments (telemetry ring + incidents)
        from predictionio_tpu.fleet.worklog import WorkerLogBook
        from predictionio_tpu.obs.incidents import IncidentRecorder

        fleet_metrics = MetricsRegistry()
        Gateway(
            GatewayConfig(replica_urls=("http://127.0.0.1:1",)),
            metrics=fleet_metrics,
        )
        sup = Supervisor(
            spawn=lambda spec: None,
            specs=[WorkerSpec(name="w0", port=1)],
            metrics=fleet_metrics,
            logbook=WorkerLogBook(str(tmp_path / "logs")),
        )
        IncidentRecorder(str(tmp_path / "incidents"), metrics=fleet_metrics)
        # the pio_autoscaler_* family rides the same fleet-parent registry
        from predictionio_tpu.fleet.autoscaler import (
            Autoscaler,
            AutoscalerConfig,
            ScalingPolicy,
        )
        from predictionio_tpu.fleet.gateway import Gateway as _Gw
        from predictionio_tpu.fleet.gateway import GatewayConfig as _GwCfg

        Autoscaler(
            ScalingPolicy(AutoscalerConfig()),
            sup,
            _Gw(
                _GwCfg(replica_urls=("http://127.0.0.1:1",)),
                metrics=MetricsRegistry(),
            ),
            lambda cls: WorkerSpec(name="w9", port=9),
            metrics=fleet_metrics,
        )
        # the pio_lifecycle_* family rides the fleet-parent registry too
        # (or a standalone `pio lifecycle run`'s own — same template)
        from predictionio_tpu.lifecycle import register_lifecycle_metrics

        register_lifecycle_metrics(fleet_metrics)
        registered.update(fleet_metrics._metrics)
        missing = documented - registered
        assert not missing, f"documented but not registered: {sorted(missing)}"
