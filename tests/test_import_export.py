"""Bulk import/export round-trips (ref FileToEvents.scala:45-120,
EventsToFile.scala:85-95 — including the json-or-parquet format switch)."""

import datetime as dt
import json

import numpy as np
import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.data.storage.memory import MemoryStorageClient
from predictionio_tpu.tools.import_export import export_events, import_events

UTC = dt.timezone.utc


def _mk_storage():
    client = MemoryStorageClient()

    class _S:
        def get_meta_data_apps(self):
            return client.apps()

        def get_meta_data_channels(self):
            return client.channels()

        def get_l_events(self):
            return client.l_events()

        def get_p_events(self):
            return client.p_events()

    s = _S()
    s.get_meta_data_apps().insert(App(0, "ioapp"))
    return s


def _seed(storage, n=25):
    app = storage.get_meta_data_apps().get_by_name("ioapp")
    lev = storage.get_l_events()
    for k in range(n):
        lev.insert(
            Event(
                event="rate" if k % 2 else "view",
                entity_type="user",
                entity_id=f"u{k % 5}",
                target_entity_type="item",
                target_entity_id=f"i{k % 7}",
                properties=DataMap({"rating": float(k % 5 + 1)})
                if k % 2
                else DataMap({}),
                event_time=dt.datetime(2026, 1, 1, 0, 0, k, tzinfo=UTC),
            ),
            app.id,
        )
    return app


class TestJsonRoundTrip:
    def test_export_import(self, tmp_path):
        src = _mk_storage()
        _seed(src)
        out = tmp_path / "events.jsonl"
        n = export_events(str(out), "ioapp", storage=src, format="json")
        assert n == 25
        # wire rows parse as API events
        rows = [json.loads(line) for line in out.read_text().splitlines()]
        assert all("eventTime" in r for r in rows)

        dst = _mk_storage()
        n2 = import_events(str(out), "ioapp", storage=dst)
        assert n2 == 25
        src_events = sorted(
            src.get_p_events().find(1), key=lambda e: e.event_time
        )
        dst_events = sorted(
            dst.get_p_events().find(1), key=lambda e: e.event_time
        )
        for a, b in zip(src_events, dst_events):
            assert (a.event, a.entity_id, a.target_entity_id) == (
                b.event, b.entity_id, b.target_entity_id
            )
            assert dict(a.properties) == dict(b.properties)
            assert a.event_time == b.event_time


class TestParquetRoundTrip:
    def test_export_import(self, tmp_path):
        pytest.importorskip("pyarrow")
        src = _mk_storage()
        _seed(src)
        out = tmp_path / "events.parquet"
        n = export_events(str(out), "ioapp", storage=src, format="parquet")
        assert n == 25

        # the file is real parquet with wire-named columns
        import pyarrow.parquet as pq

        table = pq.read_table(out)
        assert {"event", "entityType", "entityId", "eventTime"} <= set(
            table.column_names
        )
        assert table.num_rows == 25

        dst = _mk_storage()
        n2 = import_events(str(out), "ioapp", storage=dst)
        assert n2 == 25
        src_events = sorted(
            src.get_p_events().find(1), key=lambda e: e.event_time
        )
        dst_events = sorted(
            dst.get_p_events().find(1), key=lambda e: e.event_time
        )
        for a, b in zip(src_events, dst_events):
            assert (a.event, a.entity_id, a.target_entity_id) == (
                b.event, b.entity_id, b.target_entity_id
            )
            assert dict(a.properties) == dict(b.properties)
            assert a.event_time == b.event_time

    def test_timestamp_columns_are_typed(self, tmp_path):
        """eventTime/creationTime must be real tz-aware timestamp columns
        (the reference's Spark schema uses TimestampType), not ISO strings
        (code-review r5)."""
        pytest.importorskip("pyarrow")
        import pyarrow as pa
        import pyarrow.parquet as pq

        src = _mk_storage()
        _seed(src, n=3)
        out = tmp_path / "t.parquet"
        export_events(str(out), "ioapp", storage=src, format="parquet")
        schema = pq.read_table(out).schema
        assert schema.field("eventTime").type == pa.timestamp("us", tz="UTC")
        assert schema.field("creationTime").type == pa.timestamp("us", tz="UTC")

    def test_properties_json_column(self, tmp_path):
        """Schema-free properties ride as a JSON string column (documented
        deviation from the reference's Spark struct)."""
        pytest.importorskip("pyarrow")
        src = _mk_storage()
        _seed(src, n=4)
        out = tmp_path / "p.parquet"
        export_events(str(out), "ioapp", storage=src, format="parquet")
        import pyarrow.parquet as pq

        col = pq.read_table(out).to_pylist()
        with_props = [r for r in col if r["properties"]]
        assert with_props
        assert all(
            isinstance(json.loads(r["properties"]), dict) for r in with_props
        )


class TestNpzExport:
    def test_columnar(self, tmp_path):
        src = _mk_storage()
        _seed(src)
        out = tmp_path / "cols.npz"
        n = export_events(str(out), "ioapp", storage=src, format="npz")
        assert n == 25
        with np.load(out, allow_pickle=True) as z:
            assert len(z["entity_ids"]) == 25
            assert len(z["entity_vocab"]) == 5


def test_unknown_format_rejected(tmp_path):
    src = _mk_storage()
    with pytest.raises(ValueError, match="json|parquet|npz"):
        export_events(str(tmp_path / "x"), "ioapp", storage=src, format="xml")
